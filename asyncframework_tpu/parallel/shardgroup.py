"""Sharded parameter server: range-partitioned model, elastic shard group.

The single :class:`~asyncframework_tpu.parallel.ps_dcn.ParameterServer`
process was the last unprotected single point of failure in the training
plane (ROADMAP open item 1): every robustness layer (chaos fabric, elastic
worker supervision, durable dedup-window checkpoints) funnelled through one
process holding the whole model, so one kill -9 stalled the run until a
full restart, and one NIC bounded aggregate pull/push bandwidth.  This
module breaks that ceiling the classic parameter-server way, shaped by the
delay-tolerant analysis of "Faster Asynchronous SGD" (arXiv:1601.04033):
**staleness becomes a per-shard vector, not a scalar**.

Topology
--------

- the model ``w[0:d]`` is **range-partitioned** (:func:`shard_ranges`,
  contiguous near-equal ranges) across N stock ``ParameterServer``
  processes; each shard owns ``w[lo:hi]`` with its OWN merge clock, taw
  admission, dedup window, and durable checkpoint.  The elementwise ASGD
  update commutes with slicing, so per-range applies are exact;
- a :class:`ShardMap` names the group: workers and serving replicas
  resolve it **at HELLO** (the WELCOME reply carries it) or via the
  ``SHARDMAP`` op -- no side channel, no config fan-out;
- **shard 0 is the primary**: it keeps the partial-barrier wave gate
  (cohort semantics unchanged at ``shards=1``), the elastic WORKER
  supervisor, the calibration broadcast, and the end-of-run EVAL plane.
  Secondaries serve their ranges ungated (``bucket_ratio=0``) and never
  self-finish (their iteration budget is unbounded; the primary's DONE is
  broadcast to them as ``FINISH``);
- a worker-side :class:`ShardedPSClient` presents the PSClient surface to
  the stock worker loops: a PULL becomes N parallel sub-pulls (sent
  back-to-back, reaped primary-first -- each sub-pull reuses the
  per-shard ``have=`` NM/XDELTA/FULL negotiation and CRC gating), a PUSH
  fans out per-shard gradient rows under per-shard ``(sid, seq)``
  exactly-once sessions, and the model version is a **vector** of
  per-shard clocks assembled worker-side.

Elastic shard failover
----------------------

:class:`ShardGroup` spawns the shard processes (the same env-driven child
``python -m asyncframework_tpu.parallel.shardgroup`` the k8s manifests
run) and folds them into the PR 2
:class:`~asyncframework_tpu.parallel.supervisor.ElasticSupervisor` as
first-class members (``adopt=False`` slots, one per shard): each monitor
tick probes every shard's port (the contact signal) and the supervisor
declares a shard dead on **local pid exit or silence** -- exactly the
worker-death contract.  A dead shard is restarted on its pinned port from
its durable checkpoint (model + clock + dedup window captured under one
lock, PR 2); live shards keep serving their ranges meanwhile, so the run
degrades to "one range stalls briefly" instead of "the plane is down".
In-flight pushes to the dead shard replay through the PR 5 wire-window
machinery onto the recovered shard: entries are stamped once and replayed
wholesale on reconnect, so a push the dead shard applied-but-never-ACKed
is re-answered from the RESTORED dedup window, never merged twice, while
a push lost past the checkpoint is applied now (its effect was rolled
back with the model).  Serving replicas degrade per range: a dark range
keeps its last validated slice (partial refresh) and the replica answers
UNHEALTHY-per-range rather than ever assembling a torn model
(``serving/replica.py``).

``async.ps.shards = 1`` (the default) never touches any of this: the
launcher provisions the classic single PS and the wire is byte- and
step-identical (asserted via per-op frame-byte totals under a fixed seed,
``tests/test_shardgroup.py``).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from asyncframework_tpu.metrics import flightrec as _flight
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.parallel import supervisor as supervisor_mod

# ------------------------------------------------------------- group totals
# Process-global shard-group counters (metrics/registry.py family
# "shardgroup"): bumped by the controller (restarts) and the worker-side
# facade (finish broadcasts, assembled pulls) in whichever process hosts
# them -- the same per-process discipline as every other family.
_totals_lock = threading.Lock()
_totals: Dict[str, int] = {}


def shard_totals() -> Dict[str, int]:
    """Shard-group counters: shard_deaths (supervisor declared a shard
    dead), shards_restarted (children relaunched from checkpoint),
    restart_failures (relaunch attempts that did not come back),
    finish_broadcasts (primary DONE fanned out to secondaries),
    sharded_pulls / sharded_pushes (assembled vector-clock round trips),
    shard_round_errors (fan-out rounds abandoned on a sub-shard fault)."""
    with _totals_lock:
        return dict(_totals)


def reset_shard_totals() -> None:
    """Zero the process-global shard-group counters (per-run isolation;
    see ``asyncframework_tpu.metrics.reset_totals``)."""
    with _totals_lock:
        _totals.clear()


def _bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] = _totals.get(key, 0) + n


# The controller running in THIS process, if any (the cluster driver, the
# chaos harness): /api/status pages add a per-shard section from it
# (metrics/live.py reads it via active_group()).  Last started wins; a
# stopped group unhooks itself identity-gated, so a stale reference can
# never shadow a live one.
_active_group_lock = threading.Lock()
_active_group: Optional["ShardGroup"] = None


def active_group() -> Optional["ShardGroup"]:
    with _active_group_lock:
        return _active_group


def _set_active_group(group, *, only_if=None) -> None:
    global _active_group
    with _active_group_lock:
        if only_if is not None and _active_group is not only_if:
            return
        _active_group = group


# ---------------------------------------------------------------- shard map
def shard_ranges(d: int, shards: int) -> List[Tuple[int, int]]:
    """Contiguous near-equal ``[lo, hi)`` ranges covering ``[0, d)``.
    Shard count is clamped to ``d`` (a shard must own >= 1 coordinate);
    the first ``d % shards`` ranges carry the remainder coordinate."""
    d = int(d)
    shards = max(1, min(int(shards), d))
    base, rem = divmod(d, shards)
    out: List[Tuple[int, int]] = []
    lo = 0
    for i in range(shards):
        hi = lo + base + (1 if i < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


class ShardMap:
    """The group's wire-shareable identity: per-shard ``(host, port, lo,
    hi)`` in range order.  Validated contiguous on construction -- a map
    with a hole or an overlap cannot exist, so worker-side assembly by
    concatenation is correct by construction."""

    __slots__ = ("entries",)

    def __init__(self, entries: Sequence[Sequence]):
        norm = [(str(h), int(p), int(lo), int(hi))
                for (h, p, lo, hi) in entries]
        if not norm:
            raise ValueError("empty shard map")
        expect = 0
        for (_h, _p, lo, hi) in norm:
            if lo != expect or hi <= lo:
                raise ValueError(f"non-contiguous shard map: {norm}")
            expect = hi
        self.entries = norm

    @classmethod
    def from_wire(cls, wire) -> "ShardMap":
        return cls(wire)

    def to_wire(self) -> List[List]:
        return [list(e) for e in self.entries]

    @property
    def n_shards(self) -> int:
        return len(self.entries)

    @property
    def d(self) -> int:
        return self.entries[-1][3]

    def ranges(self) -> List[Tuple[int, int]]:
        return [(lo, hi) for (_h, _p, lo, hi) in self.entries]

    def __repr__(self) -> str:
        return f"ShardMap({self.entries})"


#: telemetry-port pre-assignment uses the shared reserve-and-release
#: helper (net/frame.py): the slot's scrape URL must be known BEFORE
#: the child binds it, and must survive relaunches
_free_port = _frame.free_port


def _oneshot(host: str, port: int, header: dict,
             timeout_s: float) -> dict:
    """One framed request/reply on a FRESH connection (never a data
    connection: a prefetched PULL reply may be parked in its buffer and
    must not be mispaired with this reply).  Returns the reply header."""
    s = _frame.connect((host, int(port)), timeout=timeout_s)
    try:
        s.settimeout(timeout_s)
        _frame.send_msg(s, header)
        reply, _payload = _frame.recv_msg(s)
        return reply
    finally:
        s.close()


def fetch_shard_map(host: str, port: int,
                    timeout_s: float = 10.0) -> Optional[ShardMap]:
    """One SHARDMAP round trip against any group member.  Returns None
    when the server is unsharded (the classic single PS answers an empty
    map).  Raises on transport failure -- callers own retry pacing."""
    smap, _epochs, _epoch = fetch_group_info(host, port, timeout_s)
    return smap


def fetch_group_info(host: str, port: int, timeout_s: float = 10.0
                     ) -> Tuple[Optional[ShardMap],
                                Optional[List[int]], int]:
    """One SHARDMAP round trip returning ``(shard_map, epochs, epoch)``:
    the group map (None when unsharded), the per-shard fencing-epoch
    vector (None when fencing is off or unknown), and the answering
    server's own epoch (0 = fencing off) -- everything a subscriber
    needs to stamp its reads so a fenced zombie can never serve it."""
    header = _oneshot(host, port, {"op": "SHARDMAP"}, timeout_s)
    wire = header.get("shards") or []
    epochs = header.get("epochs")
    epoch = int(header.get("epoch", 0) or 0)
    if len(wire) <= 1:
        return None, None, epoch
    return (ShardMap.from_wire(wire),
            [int(e) for e in epochs] if epochs else None, epoch)


def finish_endpoint(host: str, port: int, timeout_s: float = 5.0) -> None:
    """One FINISH round trip; idempotent server-side."""
    _oneshot(host, port, {"op": "FINISH"}, timeout_s)


def resolve_live_group(entries, timeout_s: float = 2.0
                       ) -> Tuple[Optional[ShardMap],
                                  Optional[List[int]]]:
    """Sweep a (possibly stale) map's entries for any LIVE member and
    return its view of the CURRENT ``(shard_map, epochs)`` -- the one
    re-resolution primitive behind every 'a promotion moved an
    endpoint' recovery path (worker facade, serving subscriber, the
    eval fan-out).  ``(None, None)`` when nobody answers."""
    for e in list(entries):
        try:
            smap, epochs, _ep = fetch_group_info(
                str(e[0]), int(e[1]), timeout_s=timeout_s)
        except (ConnectionError, OSError):
            continue
        if smap is not None:
            return smap, epochs
        return None, None  # an unsharded answer: nothing to re-resolve
    return None, None


# ------------------------------------------------------- worker-side facade
class ShardedPSClient:
    """The PSClient surface over a shard group: same methods the stock
    worker loops call (serial pull/push, the prefetch pair, the windowed
    push pipe, orders/eval/bye), fanned out per shard.

    Version vector: :meth:`pull` returns ``ts`` as a TUPLE of per-shard
    clocks; :meth:`push` takes that tuple back and stamps each sub-push
    with its own shard's component -- each shard prices staleness against
    its own clock (the per-shard vector contract).  ``accepted`` / ``done``
    verdicts are the PRIMARY's: its clock drives cohorts, calibration,
    and run completion; secondaries follow via FINISH.

    Fault discipline: any sub-shard RPC that exhausts its retry budget
    abandons the WHOLE round on every shard (windows dropped, sockets
    reset) and re-raises -- exactly how the serial loop loses a round
    today, except per-shard sessions guarantee the abandoned pushes that
    DID land are never re-applied when their stamps are seen again.
    Within the retry budget, a restarting shard is ridden out invisibly:
    each sub-client reconnects and replays its unacked window onto the
    recovered shard (dedup-cached re-ACKs, never a double apply).
    """

    def __init__(self, smap: ShardMap, timeout_s: float = 120.0,
                 proc: Optional[str] = None, recorder=None,
                 pull_mode: Optional[str] = None, pl_stats=None,
                 cv_buf=None, epochs: Optional[Sequence[int]] = None,
                 ctrl_sink=None):
        from asyncframework_tpu.parallel.ps_dcn import PSClient

        self.smap = smap
        # rebuild context for hot-standby promotion (ISSUE 13): a
        # sub-shard endpoint can MOVE mid-run (the controller promotes
        # the standby onto its own port), so _re_resolve needs
        # everything a fresh sub-client takes
        self._timeout_s = float(timeout_s)
        self._proc = proc
        self._recorder = recorder
        self._pull_mode = pull_mode
        self._pl_stats = pl_stats
        self._cv_buf = cv_buf
        # adaptive control (parallel/controller.py): EVERY sub-client
        # shares the sink -- any shard may deliver a newer CTRL payload
        # (SETMAP reached it first) and the monotone install keeps the
        # newest decision regardless of which range answered first
        self._ctrl_sink = ctrl_sink
        # piggybacked telemetry (trace spans, pipeline counters,
        # convergence samples) rides the PRIMARY connection only: the
        # primary folds it into the process that serves the dashboard;
        # shipping copies per shard would double-count every sample.
        # ``epochs`` (WELCOME handshake) seeds per-shard fencing epochs:
        # each sub-client stamps ITS shard's epoch -- ranges fence
        # independently, exactly like the staleness vector.
        self.clients: List[PSClient] = [
            PSClient(h, p, timeout_s=timeout_s, proc=proc,
                     recorder=recorder if i == 0 else None,
                     pull_mode=pull_mode,
                     pl_stats=pl_stats if i == 0 else None,
                     cv_buf=cv_buf if i == 0 else None,
                     epoch=(int(epochs[i])
                            if epochs and i < len(epochs) else 0),
                     ctrl_sink=ctrl_sink)
            for i, (h, p, _lo, _hi) in enumerate(smap.entries)
        ]
        self._saw_done = False
        self._finished = False
        # faulted fan-out rounds since construction: every 3rd one also
        # re-resolves the map (promotion-following, paced -- see _reset)
        self._round_errors = 0

    # ------------------------------------------------------------ plumbing
    @property
    def released(self) -> bool:
        return any(c.released for c in self.clients)

    def take_orders(self) -> List[int]:
        return self.clients[0].take_orders()

    def hello(self, proc: str, wids: List[int],
              pid: Optional[int] = None) -> dict:
        return self.clients[0].hello(proc, wids, pid=pid)

    def _rebuild_client(self, i: int, host: str, port: int,
                        epoch: int):
        """One sub-client re-homed onto a moved endpoint (promotion).
        The replacement keeps the OLD client's ClientSession and
        inherits its unacked push window VERBATIM -- original
        ``(sid, seq)`` stamps, original epoch stamps -- and drains the
        replay synchronously: an entry the deposed primary applied AND
        streamed re-answers from the promoted standby's REPLICATED
        dedup window (exactly-once across the failover); an unapplied
        or unstreamed one comes back REJECT_FENCED on its stale stamp
        and is dropped -- the same loss as an abandoned round, never a
        double apply."""
        from asyncframework_tpu.parallel.ps_dcn import PSClient

        old = self.clients[i]
        nc = PSClient(host, int(port), timeout_s=self._timeout_s,
                      proc=self._proc,
                      recorder=self._recorder if i == 0 else None,
                      pull_mode=self._pull_mode,
                      pl_stats=self._pl_stats if i == 0 else None,
                      cv_buf=self._cv_buf if i == 0 else None,
                      session=old.session, epoch=int(epoch),
                      ctrl_sink=self._ctrl_sink)
        with old._win_lock:
            entries = list(old._push_window)
            old._push_window.clear()
        old._drop_sock()
        if entries:
            nc._push_window.extend(entries)
            nc._drop_sock()  # push_finish's reconnect REPLAYS them all
            for _ in range(len(entries)):
                try:
                    nc.push_finish()
                except (ConnectionError, OSError):
                    nc.push_abandon()
                    break
        return nc

    def _re_resolve(self) -> bool:
        """After a sub-shard fault: ask any reachable member for the
        CURRENT map (a promotion re-SETMAPs every member) and re-home
        the sub-clients whose endpoints moved -- every moved one in ONE
        pass, judged against each CLIENT's actual endpoint (an earlier
        partial re-resolve must never mask a still-stale client).
        Best-effort -- the caller is already on an error path and
        retries either way."""
        smap, epochs = resolve_live_group(self.smap.entries,
                                          timeout_s=2.0)
        if smap is None or smap.ranges() != self.smap.ranges():
            return False
        changed = False
        for i, entry in enumerate(smap.entries):
            c = self.clients[i]
            if (str(entry[0]), int(entry[1])) == (c.host, c.port):
                if (epochs and i < len(epochs)
                        and int(epochs[i]) > c.epoch):
                    c.epoch = int(epochs[i])
                continue
            try:
                self.clients[i] = self._rebuild_client(
                    i, entry[0], entry[1],
                    int(epochs[i]) if epochs and i < len(epochs) else 0)
            except (ConnectionError, OSError):
                continue  # that replacement not up yet; retry later
            changed = True
        if changed:
            self.smap = smap
            _bump("map_re_resolves")
        return changed

    def _reset(self) -> None:
        """Abandon the whole fan-out round: every shard's unacked window
        is dropped (piggybacks requeued) and every socket closed, so the
        next round starts from a clean slate on fresh connections --
        a half-consumed reply can never be mispaired."""
        _bump("shard_round_errors")
        self._round_errors += 1
        if self._round_errors % 3 == 0:
            # hot-standby promotion moves a shard's endpoint mid-run:
            # learn the current map and re-home moved sub-clients
            # (their windows ride along and replay against the
            # replicated dedup window).  PACED to every third faulted
            # round -- the overwhelmingly common _reset trigger is a
            # transient (a shard mid-relaunch), which must stay pure
            # local cleanup, not a serial network sweep whose dark-
            # member connect timeouts stall the worker's error path.
            try:
                self._re_resolve()
            except Exception:  # noqa: BLE001 - recovery must never
                pass           # mask the fault that brought us here
        for c in self.clients:
            try:
                c.push_abandon()
            except (ConnectionError, OSError):  # pragma: no cover
                pass
            c._drop_sock()

    def _note_done(self, done: bool) -> None:
        if done:
            self._saw_done = True

    def _broadcast_finish(self) -> None:
        """Primary DONE -> tell the secondaries (idempotent, best-effort;
        the controller's own finish() is the backstop)."""
        if self._finished:
            return
        self._finished = True
        _bump("finish_broadcasts")
        for (h, p, _lo, _hi) in self.smap.entries[1:]:
            try:
                finish_endpoint(h, p)
            except (ConnectionError, OSError):
                pass

    # ---------------------------------------------------------- model pull
    def pull_start(self, wid: int, tr=None) -> None:
        for i, c in enumerate(self.clients):
            c.pull_start(wid, tr=tr if i == 0 else None)

    def pull_ready(self) -> bool:
        return all(c.pull_ready() for c in self.clients)

    def _assemble(self, gots) -> Optional[tuple]:
        if any(g is None for g in gots):
            # DONE (run over / FINISHed shard) or RELEASED (primary
            # deposed this wid).  A torn mix -- some shards answered a
            # model -- is discarded whole; PULL is idempotent.
            if gots[0] is None and not self.released:
                self._note_done(True)
            return None
        _bump("sharded_pulls")
        ts = tuple(int(g[0]) for g in gots)
        w = np.concatenate([g[1] for g in gots])
        return ts, w, float(gots[0][2]), bool(gots[0][3])

    def pull_finish(self, wid: int) -> Optional[tuple]:
        try:
            gots = [c.pull_finish(wid) for c in self.clients]
        except (ConnectionError, OSError):
            self._reset()
            raise
        return self._assemble(gots)

    def pull(self, wid: int, tr=None) -> Optional[tuple]:
        """N parallel sub-pulls: all requests go out back-to-back (the
        primary's parks in the wave gate; secondaries answer immediately
        into their kernel buffers), then replies are reaped
        primary-first.  Returns ``(ts_vector, w_assembled, avg_delay_ms,
        calibrated)`` or None on DONE/RELEASED."""
        try:
            self.pull_start(wid, tr=tr)
        except (ConnectionError, OSError):
            self._reset()
            raise
        return self.pull_finish(wid)

    # ---------------------------------------------------------- model push
    def _slice(self, g: np.ndarray, i: int) -> np.ndarray:
        _h, _p, lo, hi = self.smap.entries[i]
        return g[lo:hi]

    def _ts_of(self, ts, i: int) -> int:
        if isinstance(ts, (tuple, list)):
            return int(ts[i])
        return int(ts)  # defensive: scalar stamps every shard

    def push(self, wid: int, ts, g: np.ndarray, sparse: bool = False,
             diff: Optional[np.ndarray] = None, tr=None
             ) -> Tuple[bool, bool]:
        """Fan one gradient out as per-shard row pushes (per-shard
        ``(sid, seq)`` stamps, per-shard version stamps from the pull's
        vector), overlapped: all sub-pushes are SENT before any ACK is
        reaped (the per-connection server loop replies in order, so ACKs
        pair FIFO per shard).  Verdict is the primary's."""
        if diff is not None:
            raise ValueError("ASAGA does not ride the sharded PS group "
                             "(PS-side sampling is range-global)")
        g = np.asarray(g, np.float32)
        try:
            for i, c in enumerate(self.clients):
                c.push_start(wid, self._ts_of(ts, i), self._slice(g, i),
                             sparse=sparse, tr=tr if i == 0 else None)
            accepted = done = False
            for i, c in enumerate(self.clients):
                a, dn = c.push_finish()
                if i == 0:
                    accepted, done = a, dn
        except (ConnectionError, OSError):
            self._reset()
            raise
        _bump("sharded_pushes")
        self._note_done(done)
        return accepted, done

    # ------------------------------------------------- windowed push pipe
    def push_start(self, wid: int, ts, g: np.ndarray,
                   sparse: bool = False, diff: Optional[np.ndarray] = None,
                   tr=None) -> None:
        if diff is not None:
            raise ValueError("ASAGA does not ride the sharded PS group")
        g = np.asarray(g, np.float32)
        for i, c in enumerate(self.clients):
            c.push_start(wid, self._ts_of(ts, i), self._slice(g, i),
                         sparse=sparse, tr=tr if i == 0 else None)

    def push_finish(self) -> Tuple[bool, bool]:
        try:
            accepted, done = self.clients[0].push_finish()
            for c in self.clients[1:]:
                c.push_finish()
        except (ConnectionError, OSError):
            self._reset()
            raise
        _bump("sharded_pushes")
        self._note_done(done)
        return accepted, done

    def push_abandon(self) -> int:
        return max(c.push_abandon() for c in self.clients)

    def inflight_pushes(self) -> int:
        return max(c.inflight_pushes() for c in self.clients)

    # -------------------------------------------------------- end of run
    def snapshots(self) -> Tuple[List[float], np.ndarray]:
        """Assembled trajectory stacks: per-shard stacks are fetched and
        tail-aligned (snapshot cadences can drift a row or two across
        shards when accept patterns differ), then concatenated per row in
        range order.  Times are the primary's -- its clock stamps the
        trajectory the same way it governs the run."""
        stacks = [c.snapshots() for c in self.clients]
        length = min(len(t) for (t, _W) in stacks)
        # positive start index: a shard relaunched fresh past the run's
        # last cadence tick has an EMPTY stack, and [-0:] would take every
        # row of the others instead of none
        times = list(stacks[0][0][len(stacks[0][0]) - length:])
        W = np.concatenate(
            [W[W.shape[0] - length:] for (_t, W) in stacks], axis=1)
        return times, W

    def send_eval(self, wid: int, losses: np.ndarray) -> None:
        self.clients[0].send_eval(wid, losses)

    def bye(self) -> None:
        if self._saw_done and not self.released:
            # this worker watched the run finish: make sure the
            # secondaries learn (idempotent; racing peers are fine)
            self._broadcast_finish()
        for c in self.clients:
            c.bye()


# ------------------------------------------------------ serving-side facade
class ShardedSubscriber:
    """The serving tier's view of a shard group (``serving/replica.py``):
    per-range SUBSCRIBE fan-out with replica-side assembly.

    Each range rides the stock delta-pull machinery (``have=`` NM/XDELTA/
    FULL, CRC-gated, full-pull fallback) on its own connection, and the
    subscriber keeps every range's LAST VALIDATED reply.  A refresh round
    touches every range even after one fails, so live ranges keep their
    basis caches warm while a dead shard restarts -- that is the partial
    refresh: when the dark range comes back, one NM/delta round trip
    completes the model instead of a full resync.

    :meth:`subscribe` assembles the per-range slices (each individually
    CRC-validated -- a torn slice is unrepresentable) and returns the
    PSClient.subscribe tuple shape with SUMMED version/clock/k scalars,
    so ``clock - ts`` is the total versions behind across ranges.  Sum
    equality is NOT version identity (a restarted shard rolls its clock
    back, so distinct vectors can sum equal): :attr:`changed_since_last`
    carries the exact vector comparison, and the replica consults it
    before reusing a device buffer on an apparently-unchanged ts.  ``age_ms`` is the WORST range's content age including time
    a dark range has been unreachable -- the replica's freshness gate
    prices the range that is actually stale, not the average.  A range
    with no validated reply yet raises (there is nothing correct to
    serve); per-range ages are exposed so the replica can answer
    UNHEALTHY naming the stale ranges rather than serving a silent lie.
    """

    def __init__(self, smap: ShardMap, timeout_s: float = 120.0,
                 epochs: Optional[Sequence[int]] = None):
        from asyncframework_tpu.net.retry import RetryPolicy
        from asyncframework_tpu.parallel.ps_dcn import PSClient

        self.smap = smap
        # snappy per-call retry: the refresh LOOP is the real retry here
        # (it comes back every interval), so a dark range must cost this
        # round milliseconds of backoff, not the full worker-grade budget
        # -- live ranges' freshness is priced by wall clock and a slow
        # dead-range probe would smear staleness onto healthy ranges.
        # The attempt timeout is capped too: a SYN-blackholed shard (node
        # death, the k8s case) times out the connect, and a 120s socket
        # budget there would stall the serial round just as badly as the
        # backoff would -- a range that cannot answer a SUBSCRIBE in 5s
        # is already hopeless for a 50ms-refresh serving tier
        # max_attempts=1: after ~breaker-threshold dark rounds the shared
        # circuit opens and subsequent rounds fail INSTANTLY, so steady-
        # state cost of a dead range is one <=2s half-open probe per
        # cooldown, not a per-round stall
        retry = RetryPolicy.from_conf(
            attempt_timeout_s=min(float(timeout_s), 2.0), max_attempts=1,
            base_ms=20.0, max_ms=80.0,
        )
        self._retry = retry
        self._timeout_s = float(timeout_s)
        self.clients = [
            PSClient(h, p, timeout_s=timeout_s, retry=retry,
                     pull_mode="delta",
                     epoch=(int(epochs[i])
                            if epochs and i < len(epochs) else 0))
            for i, (h, p, _lo, _hi) in enumerate(smap.entries)
        ]
        self._last: List[Optional[tuple]] = [None] * smap.n_shards
        self._ok_mono: List[Optional[float]] = [None] * smap.n_shards
        # consecutive dark rounds per range: every third one also asks a
        # live member whether the range's endpoint MOVED (hot-standby
        # promotion) -- bounded extra probing, so a plainly-dead shard
        # mid-restart does not buy a map round trip per refresh
        self._dark_rounds: List[int] = [0] * smap.n_shards
        # collision guard for the replica's NOT_MODIFIED fast path: the
        # returned ts is the SUM of per-shard versions (the lag math
        # needs clock - ts in merge units), but a shard RESTART rolls its
        # clock back, so two different vectors can sum equal.  The
        # replica consults this flag before reusing its device buffer.
        self._prev_vector: Optional[tuple] = None
        self.changed_since_last = True

    # aggregated PSClient-compatible counters (the replica reports these)
    @property
    def pull_wenc(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self.clients:
            for k, v in c.pull_wenc.items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def delta_fallbacks(self) -> int:
        return sum(c.delta_fallbacks for c in self.clients)

    def subscribe(self, rid: int = 0
                  ) -> Tuple[int, np.ndarray, int, int, float, bool]:
        """One refresh round over every range; see the class docstring.
        Raises ``ConnectionError`` only when some range has never
        answered -- a partially-dark group still returns the assembled
        model (stale ranges priced into ``age_ms``)."""
        for i, c in enumerate(self.clients):
            try:
                got = c.subscribe(rid)
            except (ConnectionError, OSError):
                _bump("subscribe_dark_rounds")
                self._dark_rounds[i] += 1
                if self._dark_rounds[i] % 3 == 0:
                    self._maybe_re_resolve(i)
                continue
            self._dark_rounds[i] = 0
            if got is None:  # pragma: no cover - SUBSCRIBE never says DONE
                continue
            self._last[i] = got
            # stamped per reply, not per round: a dark range's retry
            # budget burns seconds mid-round, and pricing that wait into
            # the LIVE ranges' freshness would mark the whole model stale
            self._ok_mono[i] = time.monotonic()
        now = time.monotonic()
        missing = [i for i, l in enumerate(self._last) if l is None]
        if missing:
            raise ConnectionError(
                f"sharded SUBSCRIBE: no validated model yet for "
                f"range(s) {missing}"
            )
        vector = tuple(int(l[0]) for l in self._last)
        self.changed_since_last = vector != self._prev_vector
        self._prev_vector = vector
        ts = sum(vector)
        w = np.concatenate([l[1] for l in self._last])
        clock = sum(int(l[2]) for l in self._last)
        k = sum(int(l[3]) for l in self._last)
        age = 0.0
        for i, l in enumerate(self._last):
            age = max(age,
                      float(l[4]) + (now - self._ok_mono[i]) * 1e3)
        done = all(bool(l[5]) for l in self._last)
        _bump("sharded_subscribes")
        return ts, w, clock, k, age, done

    def _maybe_re_resolve(self, i: int) -> None:
        """Range ``i`` has been dark for a few rounds: ask a LIVE member
        for the current map -- a hot-standby promotion moved the range's
        endpoint, and the subscriber must follow it (the replica's
        partial-refresh machinery then completes the model with one
        NM/delta round trip).  Rebuilds EVERY range whose endpoint
        moved (simultaneous promotions included), judged against each
        CLIENT's actual endpoint -- adopting the new map while
        rebuilding only one range would strand the others forever.
        Best-effort and bounded: one sweep, the dark range excluded
        from the query targets (its blackholed probe must not stall
        the refresh round)."""
        from asyncframework_tpu.parallel.ps_dcn import PSClient

        others = [e for j, e in enumerate(self.smap.entries) if j != i]
        smap, epochs = resolve_live_group(others, timeout_s=1.0)
        if smap is None or smap.ranges() != self.smap.ranges():
            return
        changed = False
        for j, entry in enumerate(smap.entries):
            c = self.clients[j]
            if (str(entry[0]), int(entry[1])) == (c.host, c.port):
                continue
            try:
                nc = PSClient(entry[0], int(entry[1]),
                              timeout_s=self._timeout_s,
                              retry=self._retry, pull_mode="delta",
                              epoch=(int(epochs[j])
                                     if epochs and j < len(epochs)
                                     else 0))
            except (ConnectionError, OSError):
                continue  # that replacement not up yet; next dark round
            c._drop_sock()
            self.clients[j] = nc
            changed = True
        if changed:
            self.smap = smap
            _bump("subscriber_re_resolves")

    def oldest_ok_age_ms(self) -> Optional[float]:
        """Age of the STALEST range's last successful refresh; None until
        every range has answered at least once."""
        if any(m is None for m in self._ok_mono):
            return None
        now = time.monotonic()
        return max((now - m) * 1e3 for m in self._ok_mono)

    def stale_ranges(self, max_age_ms: float) -> List[int]:
        """Range indices whose last successful refresh is older than
        ``max_age_ms`` (never-refreshed ranges included) -- the
        UNHEALTHY-per-range answer."""
        now = time.monotonic()
        return [
            i for i, m in enumerate(self._ok_mono)
            if m is None or (now - m) * 1e3 > max_age_ms
        ]

    def range_status(self) -> List[Dict]:
        """Per-range freshness for the replica's STATUS reply."""
        now = time.monotonic()
        out = []
        for i, (_h, _p, lo, hi) in enumerate(self.smap.entries):
            last, ok = self._last[i], self._ok_mono[i]
            out.append({
                "shard": i, "lo": lo, "hi": hi,
                "ts": int(last[0]) if last is not None else None,
                "clock": int(last[2]) if last is not None else None,
                "ok_age_ms": (round((now - ok) * 1e3, 1)
                              if ok is not None else None),
            })
        return out

    def bye(self) -> None:
        for c in self.clients:
            try:
                c.bye()
            except (ConnectionError, OSError):  # pragma: no cover
                pass


# --------------------------------------------------------- group controller
class _ShardProc:
    """One managed shard child: Popen handle, pinned port, stdout pump."""

    def __init__(self, index: int):
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.resumed_from: Optional[int] = None
        self.lines: List[str] = []
        self.lines_cv = threading.Condition()
        self._reader: Optional[threading.Thread] = None

    def attach(self, proc: subprocess.Popen) -> None:
        self.proc = proc
        lines: List[str] = []
        self.lines = lines

        # the pump binds ITS life's list: a previous life's reader still
        # draining a killed child's pipe buffer must not deposit a stale
        # line into the new child's announce slot
        def pump(p=proc, lines=lines):
            for line in p.stdout:
                with self.lines_cv:
                    lines.append(line.rstrip("\n"))
                    self.lines_cv.notify_all()

        self._reader = threading.Thread(
            target=pump, name=f"shard-{self.index}-stdout", daemon=True
        )
        self._reader.start()

    def next_line(self, seen: int, timeout_s: float) -> Optional[str]:
        deadline = time.monotonic() + timeout_s
        with self.lines_cv:
            while len(self.lines) <= seen:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self.lines_cv.wait(timeout=min(left, 0.2))
            return self.lines[seen]


class ShardGroup:
    """Spawn, supervise, and recover a PS shard group on this host.

    The controller is deliberately jax-free: it Popens the env-driven
    shard child (:func:`_child_main` -- the same entry the k8s manifests
    run), probes each shard's port every monitor tick (the supervisor's
    contact signal), and lets a PR 2 :class:`ElasticSupervisor`
    (``adopt=False``, one slot per shard) declare deaths by **local pid
    exit or silence**.  A dead shard is killed-if-wedged and relaunched
    on its pinned port from its durable checkpoint; the restarted child's
    hello line reports ``resumed_from`` so recovery is observable.

    ``indices`` selects which shards THIS controller manages (the cluster
    CLI runs the primary in-process and manages only the secondaries;
    the chaos harness manages all of them).  ``fixed_entries`` names the
    unmanaged shards' endpoints so the full :class:`ShardMap` can be
    assembled and SETMAP'd to every managed child.
    """

    def __init__(self, cfg, d: int, n: int, shards: int,
                 host: str = "127.0.0.1", algo: str = "asgd",
                 checkpoint_dir: Optional[str] = None,
                 indices: Optional[Sequence[int]] = None,
                 fixed_entries: Optional[Dict[int, Tuple[str, int]]] = None,
                 conf_overlays: Optional[Dict[str, object]] = None,
                 env: Optional[Dict[str, str]] = None,
                 worker_procs: int = 0, elastic: bool = False,
                 stderr_dir: Optional[str] = None,
                 dead_after_s: float = 2.0,
                 check_interval_s: float = 0.25,
                 max_restarts: int = 10,
                 spawn_timeout_s: float = 90.0,
                 standbys: Optional[int] = None,
                 telemetry_ports: Optional[object] = None):
        if algo != "asgd":
            raise ValueError("sharded PS groups support algo='asgd' only "
                             "(ASAGA's PS-side sampling is range-global)")
        if shards < 1:
            raise ValueError("ShardGroup needs shards >= 1")
        if int(d) < int(shards):
            # shard_ranges would clamp, but the controller still spawns
            # `shards` children -- the surplus ones would die at an
            # IndexError before announcing and start() would block its
            # full spawn timeout on a misleading "did not announce"
            raise ValueError(f"d={d} cannot range-partition over "
                             f"{shards} shards (a shard owns >= 1 "
                             f"coordinate)")
        # shards=1 is the control arm: ONE managed child process serving
        # the classic single-PS wire (no shard map is assembled or
        # advertised, so clients cannot tell it from an unsharded PS) --
        # the bench's like-for-like process-boundary baseline
        self.cfg = cfg
        self.d, self.n = int(d), int(n)
        self.shards = int(shards)
        self.host = host
        self.algo = algo
        self.checkpoint_dir = checkpoint_dir
        self.indices = sorted(indices if indices is not None
                              else range(self.shards))
        self.fixed_entries = dict(fixed_entries or {})
        self.conf_overlays = dict(conf_overlays or {})
        self.env = dict(env if env is not None else os.environ)
        self.worker_procs = int(worker_procs)
        self.elastic = bool(elastic)
        self.stderr_dir = stderr_dir
        self.max_restarts = int(max_restarts)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._ranges = shard_ranges(self.d, self.shards)
        self._procs: Dict[int, _ShardProc] = {
            i: _ShardProc(i) for i in self.indices
        }
        self.smap: Optional[ShardMap] = None
        # adaptive control (parallel/controller.py): the group's stored
        # CTRL payload, re-announced with every SETMAP so decisions
        # reach every shard and survive relaunches/promotions.  None =
        # control off -- SETMAPs carry no ctrl key.  The coalescing
        # announcer thread (lazily started by install_ctrl) keeps dark-
        # member connect timeouts off the controller's decision loop.
        self._ctrl: Optional[dict] = None
        self._ctrl_announce_evt = threading.Event()
        self._ctrl_announce_thread: Optional[threading.Thread] = None
        # epoch fencing (async.fence.enabled, read through the overlays
        # the children will see so controller and children agree): the
        # controller is the epoch minter for its managed shards -- a
        # shard's running epoch is 1 + its slot's supervisor fence count,
        # passed down at spawn and re-announced to the group via SETMAP
        # after every relaunch.  The child additionally bumps past its
        # checkpoint's persisted epoch, so even a controller-less restart
        # (the k8s Deployment path) mints a fresh incarnation.
        from asyncframework_tpu.conf import (
            FENCE_ENABLED,
            GRAY_RTT_FACTOR,
            GRAY_RTT_MIN_MS,
            LEASE_S,
            PS_STANDBY,
            SUSPECT_AFTER_S,
            AsyncConf,
        )

        overlay_conf = AsyncConf(self.conf_overlays)
        self.fence = bool(overlay_conf.get(FENCE_ENABLED))
        # hot-standby replication (ISSUE 13, async.ps.standby read
        # through the same overlays the children see): one warm standby
        # child per managed shard, fed by its primary's REPL stream.
        # Failover becomes PROMOTE-under-the-minted-epoch instead of
        # restart-from-checkpoint -- promotion additionally requires
        # fencing (the epoch IS the safety primitive) and a shard map
        # to re-announce; without either, standbys still serve as read
        # replicas and recovery stays the classic relaunch.
        if standbys is None:
            standbys = int(overlay_conf.get(PS_STANDBY))
        self.standbys = 1 if int(standbys) > 0 else 0
        self._standby_procs: Dict[int, _ShardProc] = {}
        self._standby_ok: Dict[int, float] = {}
        self._standby_probe_t: Dict[int, float] = {}
        self._standby_gen: Dict[int, int] = {}
        self._promotions: Dict[int, int] = {}
        self.promotions = 0
        # deposed-but-alive primaries (promoted over while partitioned):
        # fenced out of every write path, kept only so stop() reaps them
        self._deposed: List[subprocess.Popen] = []
        # gray-failure detection: the liveness probes below time their
        # round trips into a cohort RTT suspector; a slow-but-alive shard
        # is marked SUSPECT in membership (and surfaced in telemetry)
        # without being killed -- lease expiry alone escalates to DEAD.
        # Tuning is read through the SAME overlays the children see (the
        # fence-flag discipline): controller and children must agree.
        from asyncframework_tpu.net.health import RttSuspector

        self._gray = RttSuspector(
            factor=overlay_conf.get(GRAY_RTT_FACTOR),
            min_ms=overlay_conf.get(GRAY_RTT_MIN_MS),
        )
        # PR 2 supervisor, shard edition: one slot per shard, no adoption
        # planning (a PS shard is re-homed by RESTART, not by handing its
        # range to a peer -- the range's durable state lives in its
        # checkpoint).  Port probes feed touch(); pid probes catch local
        # exits between ticks.
        # async.lease.s / async.suspect.after.s (same overlay discipline
        # as the fence flag) override the ctor's dead_after_s default, so
        # an operator widening the shard lease for slow bring-up or long
        # partitions is actually obeyed here, not just worker-side
        self.sup = supervisor_mod.ElasticSupervisor(
            self.shards, dead_after_s=dead_after_s,
            check_interval_s=check_interval_s, boot_grace_s=dead_after_s,
            adopt=False, fence=self.fence,
            lease_s=float(overlay_conf.get(LEASE_S)) or None,
            suspect_after_s=float(overlay_conf.get(SUSPECT_AFTER_S))
            or None,
        )
        self._check_interval_s = float(check_interval_s)
        self._stop = threading.Event()
        self._finished = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._restart_lock = threading.Lock()
        self._ts_source = None
        # per-slot telemetry endpoints (cluster-observer discovery):
        # "auto" pre-assigns one free port per PRIMARY slot, a dict pins
        # them explicitly.  The slot's port survives relaunches -- the
        # same _child_env every (re)spawn sets it via
        # ASYNCTPU_ASYNC_METRICS_PORT, so the observer's scrape URL for
        # "ps-shard-i" stays valid across a failover.  Standbys get
        # their OWN ports (two processes cannot share one bind), and a
        # PROMOTION hands the standby's port to the slot -- the role
        # name keeps resolving to whoever currently serves the range
        # instead of pointing at a dead primary's port forever.
        self.telemetry_ports: Dict[int, int] = {}
        self._standby_tports: Dict[int, int] = {}
        if telemetry_ports == "auto":
            self.telemetry_ports = {
                i: _free_port(self.host) for i in self.indices
            }
            if self.standbys:
                self._standby_tports = {
                    i: _free_port(self.host) for i in self.indices
                }
        elif isinstance(telemetry_ports, dict):
            self.telemetry_ports = {
                int(i): int(p) for i, p in telemetry_ports.items()
            }

    def telemetry_targets(self) -> List[Tuple[str, str, str]]:
        """(name, role, url) scrape targets for the observer: one per
        managed shard slot with an assigned telemetry port."""
        return [
            (f"ps-shard-{i}", "ps",
             f"http://{self.host}:{port}")
            for i, port in sorted(self.telemetry_ports.items())
        ]

    # ------------------------------------------------------------ lifecycle
    def _ckpt_path(self, index: int) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        return os.path.join(self.checkpoint_dir,
                            f"ps_shard{index}.npz")

    def _ckpt_standby_path(self, index: int) -> Optional[str]:
        """Where THIS GENERATION's standby would checkpoint its range
        once promoted.  Per-generation file (the spawn counter is in
        the name): every durable file for a range has exactly ONE
        writer ever -- a mirror never checkpoints while standby, and
        successive promoted incarnations never share a path, so no
        zombie's final save can race or roll back a successor's image."""
        if not self.checkpoint_dir:
            return None
        gen = self._standby_gen.get(index, 0)
        return os.path.join(self.checkpoint_dir,
                            f"ps_shard{index}.standby{gen}.npz")

    def _ckpt_newest_path(self, index: int) -> Optional[str]:
        """The range's FRESHEST durable image for a fallback relaunch:
        after promotions the acting primary persists to its generation's
        standby file, so restoring the original path would silently
        roll the range back past everything merged since the first
        failover.  Candidates are ranked by the image's own (epoch,
        clock) -- mtime alone could prefer a fenced zombie's last
        stale save -- with mtime as the tiebreak/fallback for
        unreadable files."""
        primary = self._ckpt_path(index)
        if not primary:
            return None
        import glob as _glob

        candidates = [p for p in [primary] + sorted(_glob.glob(
            os.path.join(self.checkpoint_dir,
                         f"ps_shard{index}.standby*.npz")))
            if os.path.exists(p)]
        if not candidates:
            return primary

        def rank(path):
            try:
                with np.load(path, allow_pickle=False) as z:
                    meta = json.loads(str(z["__meta__"]))
                return (int(meta.get("epoch", 0)),
                        int(meta.get("clock", 0)),
                        os.path.getmtime(path))
            except Exception:  # noqa: BLE001 - torn/corrupt image
                return (-1, -1, os.path.getmtime(path))

        return max(candidates, key=rank)

    def _child_env(self, index: int, bind_port: int,
                   role: str = "primary") -> Dict[str, str]:
        import dataclasses

        env = dict(self.env)
        env["ASYNC_SHARD_INDEX"] = str(index)
        env["ASYNC_SHARD_COUNT"] = str(self.shards)
        env["ASYNC_SHARD_D"] = str(self.d)
        env["ASYNC_SHARD_N"] = str(self.n)
        env["ASYNC_SHARD_ALGO"] = self.algo
        env["ASYNC_SHARD_BIND_PORT"] = str(bind_port)
        env["ASYNC_SHARD_CFG"] = json.dumps(dataclasses.asdict(self.cfg))
        env["ASYNC_SHARD_ROLE"] = role
        env["ASYNC_SHARD_CKPT"] = (
            (self._ckpt_standby_path(index) if role == "standby"
             else self._ckpt_newest_path(index)) or ""
        )
        env["ASYNC_SHARD_WORKER_PROCS"] = str(self.worker_procs)
        env["ASYNC_SHARD_ELASTIC"] = (
            "1" if self.elastic and role == "primary" else "0"
        )
        env["ASYNC_SHARD_CONF"] = json.dumps(self.conf_overlays)
        env["ASYNC_SHARD_MAP"] = (json.dumps(self.smap.to_wire())
                                  if self.smap is not None else "")
        env["ASYNC_SHARD_EPOCH"] = str(self.epoch_of(index))
        epochs = self.epochs_wire()
        env["ASYNC_SHARD_EPOCHS"] = json.dumps(epochs) if epochs else ""
        sbs = self.standbys_wire() if role == "primary" else None
        env["ASYNC_SHARD_STANDBYS"] = (
            json.dumps(sbs) if sbs and any(sbs) else ""
        )
        mport = (self.telemetry_ports.get(index) if role == "primary"
                 else self._standby_tports.get(index))
        if mport:
            # the slot's pinned telemetry endpoint (observer discovery):
            # conf async.metrics.port's env spelling, same as the k8s
            # manifests -- start_telemetry_from_conf in the child's main
            # lights it up.  Standbys bind their own port; a promotion
            # hands it to the slot (see _promote).
            env["ASYNCTPU_ASYNC_METRICS_PORT"] = str(mport)
        return env

    def epoch_of(self, index: int) -> int:
        """The fencing epoch shard ``index`` currently runs at (0 =
        fencing off): base epoch 1 plus one bump per lease-expiry/exit
        fence the supervisor declared for its slot."""
        if not self.fence:
            return 0
        return 1 + self.sup.epoch_of(index)

    def epochs_wire(self) -> Optional[List[int]]:
        """The whole group's epoch vector in range order (None with
        fencing off); unmanaged shards (the cluster CLI's in-process
        primary) sit at their base epoch unless their own restarts bump
        them -- their minting rides their checkpoints, not this
        controller."""
        if not self.fence:
            return None
        return [self.epoch_of(i) for i in range(self.shards)]

    def _spawn(self, index: int, bind_port: int,
               role: str = "primary") -> dict:
        standby = role == "standby"
        if standby:
            # per-generation identity (names this life's post-promotion
            # checkpoint file -- see _ckpt_standby_path)
            self._standby_gen[index] = (
                self._standby_gen.get(index, 0) + 1)
        rec = (self._standby_procs if standby else self._procs)[index]
        stderr = subprocess.DEVNULL
        if self.stderr_dir:
            # crash forensics (chaos tests, field debugging): each life of
            # each shard appends to its own log
            os.makedirs(self.stderr_dir, exist_ok=True)
            suffix = "-standby" if standby else ""
            stderr = open(os.path.join(
                self.stderr_dir,
                f"shard{index}{suffix}.stderr.log"), "a")
        proc = subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.parallel.shardgroup"],
            env=self._child_env(index, bind_port, role=role),
            stdout=subprocess.PIPE, stderr=stderr, text=True,
        )
        if stderr is not subprocess.DEVNULL:
            stderr.close()  # the child owns the fd now
        rec.attach(proc)
        if not standby:
            # register the relaunch IMMEDIATELY -- pid + /proc start
            # time land under the supervisor lock the moment the child
            # exists, not after its (possibly long) announce wait.
            # Before this, the slot stayed DEAD for the whole spawn and
            # a concurrent scan (check_once is public; tests and
            # operators call it) could schedule a SECOND spawn for the
            # same shard, killing the fresh child.  _restart's
            # membership guard is the other half of the fix.
            self.sup.register(f"ps-shard-{index}", [index], pid=proc.pid,
                              host=socket.gethostname())
        line = rec.next_line(0, self.spawn_timeout_s)
        if line is None:
            proc.kill()
            raise RuntimeError(
                f"PS shard {index} {role} did not announce within "
                f"{self.spawn_timeout_s:.0f}s"
            )
        hello = json.loads(line)
        rec.port = int(hello["port"])
        if standby:
            self._standby_ok[index] = time.monotonic()
        return hello

    def start(self) -> "ShardGroup":
        try:
            for i in self.indices:
                self._spawn(i, 0)
            if self.standbys:
                # warm standbys, one per managed shard: spawned AFTER
                # the primaries (a standby is useless without a stream
                # source) and announced to them via SETMAP below.  A
                # failed standby spawn degrades that shard to the
                # classic restart recovery -- never fails the group.
                for i in self.indices:
                    self._standby_procs[i] = _ShardProc(i)
                    try:
                        self._spawn(i, 0, role="standby")
                    except (RuntimeError, OSError):
                        _bump("standby_spawn_failures")
                        del self._standby_procs[i]
            if self.shards > 1:
                entries = []
                for i, (lo, hi) in enumerate(self._ranges):
                    if i in self._procs:
                        entries.append(
                            (self.host, self._procs[i].port, lo, hi))
                    else:
                        fh, fp = self.fixed_entries[i]
                        entries.append((fh, int(fp), lo, hi))
                self.smap = ShardMap(entries)
                # hand every managed child the assembled map (it answers
                # SHARDMAP / HELLO from it); unmanaged shards get it from
                # their own launcher (the cluster CLI constructs its
                # in-process primary with shard_map= directly)
                for i in self.indices:
                    self._setmap(i)
            elif self.standbys and self._standby_procs:
                # shards=1 control arm: no map, but the single child
                # still learns its standby endpoint (read replica +
                # replicated state; failover for the unmapped single PS
                # stays restart-from-checkpoint -- there is no map to
                # re-announce a moved endpoint through)
                for i in self.indices:
                    self._setmap(i)
        except Exception:
            # a later spawn, map assembly, or SETMAP failed: the children
            # already up must not be leaked (the caller's `group` variable
            # was never assigned, so its cleanup path cannot reach them)
            for rec in list(self._procs.values()) + list(
                    self._standby_procs.values()):
                if rec.proc is not None and rec.proc.poll() is None:
                    rec.proc.kill()
            raise
        self._monitor = threading.Thread(
            target=self._run, name="shard-group-monitor", daemon=True
        )
        self._monitor.start()
        # continuous telemetry: per-range availability becomes the
        # ``ps_shards.*`` series each sampler tick -- the
        # shard_availability SLO rule's input surface
        from asyncframework_tpu.metrics import timeseries as _ts

        self._ts_source = self._telemetry_source
        _ts.register_source("ps_shards", self._ts_source)
        _set_active_group(self)
        return self

    def standbys_wire(self) -> Optional[List]:
        """Per-shard standby endpoints in range order (``[host, port]``
        or None per entry; None overall when the standby plane is off).
        What SETMAP installs and SHARDMAP advertises."""
        if not self.standbys:
            return None
        out: List = []
        for i in range(self.shards):
            rec = self._standby_procs.get(i)
            alive = (rec is not None and rec.port is not None
                     and rec.proc is not None and rec.proc.poll() is None)
            out.append([self.host, rec.port] if alive else None)
        return out

    def install_ctrl(self, wire: dict) -> None:
        """Adaptive-control decision fan-out (parallel/controller.py):
        store the CTRL payload and re-SETMAP it to every member next to
        the map/epochs/standbys.  The STORE is what makes decisions
        survive failover -- a relaunched shard's boot SETMAP and a
        promoted standby's re-announce both carry the group's current
        ctrl, and each member's monotone (ep, seq) install refuses
        anything stale.

        The announce runs on a lazily-started coalescing thread (the
        relaycast offer-thread discipline): a dark/partitioned member's
        per-target connect timeout must burn the announcer, never the
        controller's decision loop -- which is busiest exactly when a
        member is dark.  Back-to-back decisions coalesce into one sweep
        carrying the newest ctrl."""
        self._ctrl = dict(wire)
        if self._ctrl_announce_thread is None:
            import threading as _threading

            from asyncframework_tpu.utils.threads import guarded

            def _announce_loop() -> None:
                while not self._stop.is_set():
                    if not self._ctrl_announce_evt.wait(timeout=0.5):
                        continue
                    self._ctrl_announce_evt.clear()
                    self._announce_group()

            self._ctrl_announce_thread = _threading.Thread(
                target=guarded(_announce_loop),
                name="shardgroup-ctrl-announce", daemon=True)
            self._ctrl_announce_thread.start()
        self._ctrl_announce_evt.set()

    def _setmap(self, index: int) -> None:
        hdr = {"op": "SETMAP", "index": index,
               "shards": (self.smap.to_wire()
                          if self.smap is not None else [])}
        epochs = self.epochs_wire()
        if epochs:
            hdr["epochs"] = epochs
        sbs = self.standbys_wire()
        if sbs is not None:
            hdr["standbys"] = sbs
        if self._ctrl is not None:
            hdr["ctrl"] = self._ctrl
        _oneshot(self.host, self._procs[index].port, hdr, timeout_s=10.0)

    def _announce_group(self, timeout_s: float = 3.0) -> None:
        """Best-effort SETMAP of the CURRENT map + epoch vector +
        standby endpoints to every reachable member (unmanaged fixed
        entries included -- the cluster CLI's in-process primary serves
        every worker HELLO, so it above all must hand out current
        state).  This is where a promotion or a standby respawn
        actually reaches the wire; a still-partitioned member self-
        heals later via fencing.  The per-target timeout is kept SHORT:
        this runs on the monitor thread, and a partitioned member must
        cost seconds, not stall the next death scan for 10s a target."""
        epochs = self.epochs_wire()
        sbs = self.standbys_wire()
        if self.smap is not None:
            targets = [(j, h, p)
                       for j, (h, p, _lo, _hi)
                       in enumerate(self.smap.entries)]
        else:
            targets = [(i, self.host, rec.port)
                       for i, rec in self._procs.items()
                       if rec.port is not None]
        for j, h, p in targets:
            hdr = {"op": "SETMAP", "index": j,
                   "shards": (self.smap.to_wire()
                              if self.smap is not None else [])}
            if epochs:
                hdr["epochs"] = epochs
            if sbs is not None:
                hdr["standbys"] = sbs
            if self._ctrl is not None:
                # adaptive-control decisions survive relaunch AND
                # promotion: every re-announce re-installs the group's
                # current CTRL next to the map and epoch vector
                hdr["ctrl"] = self._ctrl
            try:
                _oneshot(h, p, hdr, timeout_s=timeout_s)
            except (ConnectionError, OSError):
                pass

    def _telemetry_source(self) -> Dict[str, float]:
        member = self.sup.membership()
        dark = sum(1 for i in self._procs
                   if member.get(i, {}).get("state") == supervisor_mod.DEAD)
        suspect = sum(
            1 for i in self._procs
            if member.get(i, {}).get("state") == supervisor_mod.SUSPECT
        )
        totals = shard_totals()
        live_standbys = sum(
            1 for rec in self._standby_procs.values()
            if rec.proc is not None and rec.proc.poll() is None
        )
        return {
            "total": float(self.shards),
            "managed": float(len(self._procs)),
            "dark_ranges": float(dark),
            "suspect_ranges": float(suspect),
            "live": float(self.shards - dark),
            "restarts": float(totals.get("shards_restarted", 0)),
            "fence_epoch_bumps": float(
                totals.get("fence_epoch_bumps", 0)),
            "standbys": float(live_standbys),
            "promotions": float(self.promotions),
            "done": float(self._finished.is_set()),
        }

    # ------------------------------------------------------------- monitor
    def _probe(self, index: int) -> bool:
        """One liveness probe: a SHARDMAP round trip against the shard's
        pinned port.  Success feeds the supervisor's contact signal (the
        lease renewal) AND the gray-failure RTT suspector: a shard that
        answers, but at a multiple of its cohort's round trip, is marked
        SUSPECT -- surfaced in membership/telemetry, never killed on
        latency alone."""
        endpoint = f"{self.host}:{self._procs[index].port}"
        t0 = time.monotonic()
        try:
            _oneshot(self.host, self._procs[index].port,
                     {"op": "SHARDMAP"}, timeout_s=1.0)
        except (ConnectionError, OSError):
            return False
        if self._gray.observe(endpoint, (time.monotonic() - t0) * 1e3):
            self.sup.suspect(index)
        else:
            self.sup.unsuspect(index)
        self.sup.touch(index, f"ps-shard-{index}")
        return True

    def check_once(self) -> List[int]:
        """One monitor scan (public for deterministic tests): probe every
        managed shard, let the supervisor declare deaths (pid exit or
        probe silence), restart the dead from their checkpoints.  Shards
        still DEAD from an earlier failed relaunch are retried every scan
        (the supervisor reports a death once; the restart loop must not
        strand the range on one unlucky spawn)."""
        for i in self._procs:
            self._probe(i)
        newly_dead = [i for i in self.sup.check_once() if i in self._procs]
        for i in newly_dead:
            _bump("shard_deaths")
            # a dead member's frozen RTT EWMA must leave the cohort:
            # left in, it skews every later suspicion median
            self._gray.forget(f"{self.host}:{self._procs[i].port}")
            self._restart(i)
        member = self.sup.membership()
        for i in self._procs:
            if (i not in newly_dead
                    and member.get(i, {}).get("state")
                    == supervisor_mod.DEAD):
                self._restart(i)
        if self.standbys:
            self._check_standbys()
        return newly_dead

    def _run(self) -> None:
        while not self._stop.wait(self._check_interval_s):
            if self._finished.is_set():
                continue  # post-done exits are teardown, not death
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 - the monitor must outlive
                pass           # any one bad scan (spawn failure, junk IO)

    def _check_standbys(self) -> None:
        """Standby liveness, OUTSIDE the fencing supervisor: a standby
        owns no range, so its death mints no epoch -- it is simply
        respawned, and its primary's stream re-bootstraps it with a
        fresh REPL_SYNC on reconnect.  Runs on the monitor thread, so
        its network work is bounded: probes are PACED (a dark standby's
        1 s timeout must not recur every 0.25 s scan and delay the next
        PRIMARY death scan -- the gap this module exists to bound)."""
        now = time.monotonic()
        if self._stop.is_set() or self._finished.is_set():
            return
        dead_after_s = self.sup.dead_after_ms / 1e3
        probe_gap_s = max(0.5, self._check_interval_s)
        for i in self.indices:
            rec = self._standby_procs.get(i)
            if rec is None:
                # a promotion (or an earlier failed spawn) left this
                # shard un-backed: recreate the slot and try again
                self._standby_procs[i] = rec = _ShardProc(i)
            proc = rec.proc
            if proc is not None and proc.poll() is None:
                if now - self._standby_probe_t.get(i, 0.0) < probe_gap_s:
                    continue  # paced: this scan skips the probe
                self._standby_probe_t[i] = now
                orphaned = False
                try:
                    hdr = _oneshot(self.host, rec.port,
                                   {"op": "SHARDMAP"}, timeout_s=1.0)
                    # a registered standby that no longer ANSWERS as one
                    # is a self-promoted orphan (a PROMOTE was delivered
                    # but its reply timed out, so the controller fell
                    # back to a relaunch): it would wedge the acting
                    # primary's stream with 'not a standby' forever --
                    # reap and respawn a real standby behind it
                    if hdr.get("standby"):
                        self._standby_ok[i] = now
                        continue
                    orphaned = True
                    _bump("standby_orphans_reaped")
                except (ConnectionError, OSError):
                    pass
                if (not orphaned
                        and now - self._standby_ok.get(i, now)
                        <= dead_after_s):
                    continue  # one dark probe is not death
                try:
                    proc.kill()
                    proc.wait(timeout=5.0)
                except OSError:  # pragma: no cover
                    pass
            if proc is not None:
                _bump("standby_deaths")
            try:
                self._spawn(i, 0, role="standby")
            except (RuntimeError, OSError):
                _bump("standby_spawn_failures")
                continue
            _bump("standbys_respawned")
            # the shard's primary must re-target its stream, and every
            # SHARDMAP reply must advertise the new endpoint
            self._announce_group()

    def promotions_of(self, index: int) -> int:
        return self._promotions.get(index, 0)

    def _promote(self, index: int) -> bool:
        """Hot-standby promotion: the shard's warm standby becomes the
        range primary under the slot's freshly-minted fencing epoch --
        no process spawn, no checkpoint replay on the recovery path;
        the availability gap is the suspicion time plus one RPC.
        Returns False when the promotion path is unavailable (standby
        plane off, fencing off, no map to re-announce the moved
        endpoint through, standby dead) -- the caller falls back to
        restart-from-checkpoint."""
        sb = self._standby_procs.get(index)
        if (not self.standbys or not self.fence or self.smap is None
                or sb is None or sb.proc is None
                or sb.proc.poll() is not None or sb.port is None):
            return False
        new_epoch = self.epoch_of(index)  # the death already minted it
        entries = [list(e) for e in self.smap.entries]
        entries[index] = [self.host, sb.port,
                          entries[index][2], entries[index][3]]
        new_map = ShardMap(entries)
        epochs = self.epochs_wire()
        try:
            rep = _oneshot(self.host, sb.port,
                           {"op": "PROMOTE", "epoch": new_epoch,
                            "index": index, "shards": new_map.to_wire(),
                            "epochs": epochs}, timeout_s=10.0)
        except (ConnectionError, OSError):
            _bump("promotion_failures")
            return False
        if rep.get("op") != "ACK":
            # refused (a stale order against a fresh mirror): fall back
            # to the relaunch path rather than install a map pointing
            # at a member that never flipped
            _bump("promotion_failures")
            return False
        old = self._procs[index]
        if old.proc is not None and old.proc.poll() is None:
            # a PARTITIONED-but-alive primary is deliberately NOT
            # killed here: promotion needs nothing it holds (the
            # standby serves on its own port), and cross-host the
            # controller could not reach it anyway -- the minted epoch
            # deposes it the moment its stream append (or any stamped
            # op) bounces REJECT_FENCED at the promoted member.  It is
            # only retained for teardown reaping.
            self._deposed.append(old.proc)
        self._gray.forget(f"{self.host}:{old.port}")
        promoted = sb
        del self._standby_procs[index]
        self._standby_ok.pop(index, None)
        promoted.restarts = old.restarts
        self._procs[index] = promoted
        self.smap = new_map
        self.promotions += 1
        self._promotions[index] = self._promotions.get(index, 0) + 1
        _bump("standby_promotions")
        _flight.note("promote", shard=int(index),
                     epoch=self.epoch_of(index))
        # telemetry-port handoff: the promoted member serves its OWN
        # (ex-standby) port; the dead primary's pre-assigned port would
        # otherwise read DOWN forever in the fleet view.  The fresh
        # standby spawned below gets a new port of its own.
        sb_port = self._standby_tports.pop(index, None)
        if sb_port is not None:
            self.telemetry_ports[index] = sb_port
            self._standby_tports[index] = _free_port(self.host)
        else:
            self.telemetry_ports.pop(index, None)
        # the minted epoch reaches the wire through the announce below
        # -- the same accounting point as the fenced relaunch path
        _bump("fence_epoch_bumps")
        supervisor_mod.bump_total("epoch_bumps")
        self.sup.register(f"ps-shard-{index}", [index],
                          pid=promoted.proc.pid,
                          host=socket.gethostname())
        # a fresh standby behind the new primary (best-effort: a failed
        # spawn leaves the shard un-backed until the next scan retries)
        self._standby_procs[index] = _ShardProc(index)
        try:
            self._spawn(index, 0, role="standby")
        except (RuntimeError, OSError):
            _bump("standby_spawn_failures")
            del self._standby_procs[index]
        # group-wide announce: every member re-learns map + epochs +
        # standbys; workers/replicas re-resolve on their next fault
        self._announce_group()
        return True

    def _restart(self, index: int) -> None:
        """Re-home a dead shard: PROMOTE its warm standby when the
        replication plane is on (failover without a restart), else kill
        the corpse if the pid is somehow still holding the port
        (wedged, not exited) and relaunch on the SAME port from the
        durable checkpoint.  Live shards never stop serving their
        ranges meanwhile."""
        with self._restart_lock:
            if self._stop.is_set() or self._finished.is_set():
                return
            rec = self._procs[index]
            proc = rec.proc
            # double-spawn guard (the other half of _spawn's early
            # registration): a concurrent scan that queued behind this
            # lock while a relaunch was in flight must NOT kill the
            # fresh child and spawn a second one -- if the slot is no
            # longer DEAD (the relaunch registered its pid the moment
            # it was Popen'd) and its process is alive, there is
            # nothing left to recover.
            state = self.sup.membership().get(index, {}).get("state")
            if (state != supervisor_mod.DEAD
                    and proc is not None and proc.poll() is None):
                return
            if proc is not None and proc.poll() == 0:
                # graceful conclusion (DONE/FINISH reached, result printed,
                # exit 0), not a crash: nothing to recover -- restarting
                # would resurrect a finished shard into a run that is over
                return
            if rec.restarts >= self.max_restarts:
                return  # gave up on this range; counted at each failure
            if self._promote(index):
                # failover WITHOUT a restart: the standby took the
                # range under the minted epoch -- no spawn, no
                # checkpoint replay, availability gap = suspicion time
                return
            if not self._ckpt_path(index):
                # no durable state: the relaunch serves a FRESH (zero)
                # model for this range mid-run.  Still better than a dark
                # range, but it must never happen silently -- convergence
                # for the range restarts from scratch.
                _bump("restarts_uncheckpointed")
                print(f"shard-group: restarting shard {index} WITHOUT a "
                      f"checkpoint (no checkpoint_dir) -- its model "
                      f"range resets to zero", file=sys.stderr, flush=True)
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                    proc.wait(timeout=10.0)
                except OSError:  # pragma: no cover
                    pass
            rec.restarts += 1
            try:
                hello = self._spawn(index, rec.port)
            except (RuntimeError, OSError):
                _bump("restart_failures")
                return
            if self._stop.is_set() or self._finished.is_set():
                # stop()/finish() raced this relaunch while _spawn was
                # blocking on the announce line: the fresh child would be
                # an orphan nobody terminates -- reap it here
                if rec.proc is not None and rec.proc.poll() is None:
                    rec.proc.kill()
                return
            _bump("shards_restarted")
            _flight.note("shard_restart", shard=int(index),
                         restarts=rec.restarts)
            # the child announces what it recovered: resumed_from is the
            # checkpointed k it came back at (None = fresh model, e.g.
            # death before the first cadence checkpoint)
            rec.resumed_from = hello.get("resumed_from")
            if self.fence and self.smap is not None:
                # announce the bumped epoch vector to every reachable
                # member -- INCLUDING unmanaged fixed entries (the
                # cluster CLI's in-process primary serves every worker
                # HELLO, so it above all must hand out current epochs):
                # WELCOME hands NEW workers current epochs, and existing
                # clients converge via MODEL ep stamps / REJECT_FENCED
                # verdicts either way -- best-effort by design (a
                # still-partitioned member self-heals later).  This is
                # where a fencing epoch actually reaches the wire, so it
                # is also where recovery.epoch_bumps counts.
                _bump("fence_epoch_bumps")
                supervisor_mod.bump_total("epoch_bumps")
                self._announce_group()

    # ------------------------------------------------------------ plumbing
    def port_of(self, index: int) -> int:
        return self._procs[index].port

    def pid_of(self, index: int) -> int:
        return self._procs[index].proc.pid

    def restarts_of(self, index: int) -> int:
        return self._procs[index].restarts

    def result_of(self, index: int, timeout_s: float) -> Optional[dict]:
        """The child's result JSON line (the line after its hello);
        None on timeout."""
        line = self._procs[index].next_line(1, timeout_s)
        return json.loads(line) if line else None

    def status(self) -> Dict[int, dict]:
        member = self.sup.membership()
        out = {}
        for i, rec in self._procs.items():
            proc = rec.proc
            out[i] = {
                "port": rec.port,
                "pid": proc.pid if proc is not None else None,
                "alive": proc is not None and proc.poll() is None,
                "restarts": rec.restarts,
                "state": member.get(i, {}).get("state"),
            }
        return out

    def finish(self) -> None:
        """Broadcast FINISH to every shard (idempotent): the primary's
        DONE becomes group-wide, secondaries' wait_done returns, and the
        monitor stops treating exits as deaths."""
        self._finished.set()
        if self.smap is not None:
            targets = [(h, p) for (h, p, _lo, _hi) in self.smap.entries]
        else:  # shards=1 control group: no map, but the child still FINISHes
            targets = [(self.host, rec.port)
                       for rec in self._procs.values()
                       if rec.port is not None]
        # standbys learn DONE too (their mirrored k may sit just short
        # of the finish when the stream lags the final merges)
        targets += [(self.host, rec.port)
                    for rec in self._standby_procs.values()
                    if rec.port is not None]
        for (h, p) in targets:
            try:
                finish_endpoint(h, p)
            except (ConnectionError, OSError):
                pass

    def status_section(self) -> dict:
        """The /api/status ``shards`` section: map + per-shard liveness,
        fencing epochs, and the gray-failure RTT view."""
        totals = shard_totals()
        out = {
            "shards": self.shards,
            "map": self.smap.to_wire() if self.smap is not None else None,
            "deaths": totals.get("shard_deaths", 0),
            "restarts": totals.get("shards_restarted", 0),
            "done": self._finished.is_set(),
            "members": {str(i): st for i, st in self.status().items()},
        }
        if self.standbys:
            out["standbys"] = self.standbys_wire()
            out["promotions"] = self.promotions
        if self.fence:
            out["epochs"] = self.epochs_wire()
        gray = self._gray.snapshot()
        if gray:
            out["rtt"] = gray
        return out

    def stop(self, timeout_s: float = 15.0) -> None:
        _set_active_group(None, only_if=self)
        self._stop.set()
        self._finished.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        self.sup.stop()
        if self._ts_source is not None:
            from asyncframework_tpu.metrics import timeseries as _ts

            _ts.unregister_source("ps_shards", self._ts_source)
        procs = [rec.proc for rec in
                 list(self._procs.values())
                 + list(self._standby_procs.values())
                 if rec.proc is not None]
        procs += self._deposed
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout_s
        for proc in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()


# -------------------------------------------------------- in-process groups
def secondary_cfg(cfg):
    """The cfg a NON-primary shard runs: no wave gate (``bucket_ratio=0``
    -- cohorts are the primary's job) and an unbounded iteration budget
    (a secondary must never self-finish on its own accept count; the
    primary's DONE arrives as FINISH)."""
    import dataclasses

    return dataclasses.replace(cfg, bucket_ratio=0.0,
                               num_iterations=2**31 - 1)


def launch_inprocess_group(cfg, d: int, n: int, shards: int,
                           device=None, host: str = "127.0.0.1",
                           algo: str = "asgd",
                           checkpoint_dir: Optional[str] = None,
                           supervisor=None, bus=None):
    """Test/bench helper: the shard group as in-process
    ``ParameterServer`` instances on ephemeral loopback ports.  Returns
    ``(ps_list, shard_map)``; ``shards=1`` returns the classic single PS
    (``shard_map=None``) -- the byte-identity baseline.  Callers stop
    every returned PS."""
    from asyncframework_tpu.parallel.ps_dcn import ParameterServer

    def ckpt(i):
        if not checkpoint_dir:
            return None
        return os.path.join(checkpoint_dir, f"ps_shard{i}.npz")

    if shards <= 1:
        ps = ParameterServer(cfg, d, n, device=device, port=0, algo=algo,
                             checkpoint_path=ckpt(0),
                             supervisor=supervisor, bus=bus).start()
        return [ps], None
    if algo != "asgd":
        raise ValueError("sharded PS groups support algo='asgd' only")
    ranges = shard_ranges(d, shards)
    ps_list = []
    for i, (lo, hi) in enumerate(ranges):
        shard_cfg = cfg if i == 0 else secondary_cfg(cfg)
        ps_list.append(ParameterServer(
            shard_cfg, hi - lo, n, device=device, port=0, algo=algo,
            checkpoint_path=ckpt(i),
            supervisor=supervisor if i == 0 else None,
            bus=bus if i == 0 else None,
            shard_index=i,
        ))
    smap = ShardMap([
        (host, ps.port, lo, hi)
        for ps, (lo, hi) in zip(ps_list, ranges)
    ])
    for ps in ps_list:
        ps.shard_map = smap.to_wire()
    if any(p.epoch for p in ps_list):
        # fencing on (each PS minted its conf-derived epoch): hand every
        # member the group's epoch vector so WELCOME/SHARDMAP carry it
        epochs = [p.epoch for p in ps_list]
        for ps in ps_list:
            ps.shard_epochs = epochs
    # start secondaries first, primary LAST: the primary's ``ps`` rolling
    # telemetry source registration must win (last wins by design)
    for ps in reversed(ps_list):
        ps.start()
    return ps_list, smap


# ------------------------------------------------------------- shard child
class CtrlFanout:
    """Adaptive-control decision fan-out, controller-less edition (the
    k8s shard manifests): no :class:`ShardGroup` owns the children --
    the Deployment controller restarts pods -- so the primary's
    AsyncController hands decisions here and every OTHER map entry gets
    a SETMAP re-announcing the static map + the CTRL payload.  Same
    duck type as ShardGroup.install_ctrl; receivers' monotone (ep, seq)
    install makes re-delivery harmless.

    The sends run on a lazily-started coalescing thread (the same
    discipline ShardGroup.install_ctrl uses): a dark member's connect
    timeout burns the announcer, never the controller's decision loop.
    Back-to-back decisions coalesce into one sweep of the newest wire."""

    def __init__(self, ps):
        self.ps = ps
        self._wire: Optional[dict] = None
        self._evt = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def install_ctrl(self, wire: dict) -> None:
        self._wire = dict(wire)
        if self._thread is None:
            from asyncframework_tpu.utils.threads import guarded

            self._thread = threading.Thread(
                target=guarded(self._loop), name="ctrl-fanout",
                daemon=True)
            self._thread.start()
        self._evt.set()

    def stop(self) -> None:
        self._stop.set()
        self._evt.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._evt.wait(timeout=0.5):
                continue
            self._evt.clear()
            if self._stop.is_set():
                return
            self._sweep()

    def _sweep(self) -> None:
        wire = self._wire
        if wire is None:
            return
        smap = self.ps.shard_map or []
        epochs = self.ps.shard_epochs
        for j, entry in enumerate(smap):
            if j == self.ps.shard_index:
                continue
            hdr = {"op": "SETMAP", "index": j, "shards": smap,
                   "ctrl": wire}
            if epochs:
                hdr["epochs"] = epochs
            try:
                _oneshot(str(entry[0]), int(entry[1]), hdr,
                         timeout_s=3.0)
            except (ConnectionError, OSError):
                pass  # a dark shard re-learns ctrl from the next send


def _child_main() -> int:
    """Env-driven shard process entry (``python -m
    asyncframework_tpu.parallel.shardgroup``): the role both
    :class:`ShardGroup` spawns locally and the k8s shard manifests run.

    Announces ``{"port", "shard", "resumed_from"}`` as the first stdout
    line, serves its range until DONE/FINISH, prints a result line, then
    KEEPS SERVING until the controller tears it down (SIGTERM / pod
    deletion): after the primary's DONE the plane is still draining --
    worker eval rounds fan SNAPSHOTS over every range, pipelined workers
    reap their last pull round, serving replicas keep subscribing -- so a
    shard that exits at DONE yanks its range out from under all of them
    (the exact stall this module exists to prevent).  A non-zero / signal
    exit before FINISH is what the controller treats as death."""
    import signal

    from asyncframework_tpu.conf import AsyncConf, set_global_conf

    term = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: term.set())

    overlays = os.environ.get("ASYNC_SHARD_CONF")
    if overlays:
        set_global_conf(AsyncConf(json.loads(overlays)))
    import jax  # after conf: platform pins ride the child env

    from asyncframework_tpu.parallel.ps_dcn import ParameterServer
    from asyncframework_tpu.solvers import SolverConfig

    index = int(os.environ["ASYNC_SHARD_INDEX"])
    count = int(os.environ["ASYNC_SHARD_COUNT"])
    d = int(os.environ["ASYNC_SHARD_D"])
    n = int(os.environ["ASYNC_SHARD_N"])
    algo = os.environ.get("ASYNC_SHARD_ALGO", "asgd")
    if count > 1 and algo != "asgd":
        print(json.dumps({"error": "sharded PS groups are ASGD-only"}),
              flush=True)
        return 2
    cfg = SolverConfig(**json.loads(os.environ["ASYNC_SHARD_CFG"]))
    lo, hi = shard_ranges(d, count)[index]
    shard_cfg = cfg if index == 0 else secondary_cfg(cfg)
    map_env = os.environ.get("ASYNC_SHARD_MAP") or ""
    smap_wire = json.loads(map_env) if map_env else None
    # hot-standby role (ISSUE 13): a standby child runs the SAME cfg as
    # the shard it shadows (post-promotion behavior must match), applies
    # its primary's replication stream instead of worker pushes, and
    # never runs the worker supervisor (after a promotion, membership
    # rebuilds from live traffic via implicit registration).
    role = os.environ.get("ASYNC_SHARD_ROLE", "primary")
    standby = role == "standby"
    sup = None
    if (index == 0 and not standby
            and os.environ.get("ASYNC_SHARD_ELASTIC") == "1"):
        from asyncframework_tpu.parallel.supervisor import ElasticSupervisor

        sup = ElasticSupervisor.from_conf(cfg.num_workers)
    # per-shard telemetry endpoint (async.metrics.port; -1 = off): the
    # scrape label set carries the shard index so per-shard series do not
    # collapse into one another in an aggregator
    from asyncframework_tpu.metrics.live import start_telemetry_from_conf

    start_telemetry_from_conf(
        f"ps-{'standby' if standby else 'shard'}-{index}",
        labels={"shard": str(index)})
    # fencing epoch: the controller passes the minted epoch (base 1 +
    # its lease-expiry fences for this slot); 0/absent defers to conf
    # (async.fence.enabled -> 1, off -> 0).  The PS restore additionally
    # bumps past the checkpointed epoch, so every incarnation -- even a
    # controller-less k8s pod restart -- runs at a fresh epoch.
    epoch_env = int(os.environ.get("ASYNC_SHARD_EPOCH") or 0)
    epochs_env = os.environ.get("ASYNC_SHARD_EPOCHS") or ""
    shard_epochs = json.loads(epochs_env) if epochs_env else None
    ps = ParameterServer(
        shard_cfg, hi - lo, n,
        port=int(os.environ.get("ASYNC_SHARD_BIND_PORT", "0")),
        algo=algo,
        checkpoint_path=os.environ.get("ASYNC_SHARD_CKPT") or None,
        supervisor=sup,
        shard_map=smap_wire, shard_index=index,
        epoch=epoch_env or None, shard_epochs=shard_epochs or None,
        standby=standby,
    )
    # adaptive asynchrony controller on the PRIMARY shard
    # (async.control.enabled, e.g. the k8s shard-0 pod's env): closes
    # the telemetry->knobs loop with decisions fanned to the other map
    # entries via CtrlFanout (no ShardGroup owns k8s children).
    # Started BEFORE ps.start() so the very first WELCOME served
    # already carries the CTRL payload -- a worker that HELLOs in the
    # gap would never build a ControlSink.
    controller = None
    ctrl_fanout = None
    from asyncframework_tpu.conf import CONTROL_ENABLED, global_conf

    if index == 0 and not standby and global_conf().get(CONTROL_ENABLED):
        from asyncframework_tpu.parallel.controller import AsyncController

        if smap_wire:
            ctrl_fanout = CtrlFanout(ps)
        controller = AsyncController(ps, group=ctrl_fanout).start()
    ps.start()
    sbs_env = os.environ.get("ASYNC_SHARD_STANDBYS") or ""
    if sbs_env and not standby:
        # launcher-known standby endpoints (the k8s path, where SETMAP
        # has no controller to send it): installs the map and starts
        # this primary's replication stream
        ps.set_standby_map(json.loads(sbs_env))
    print(json.dumps({"port": ps.port, "shard": index, "role": role,
                      "resumed_from": ps.resumed_from_k}), flush=True)
    print(f"shard {index} ({role}) serving on {ps.port}",
          file=sys.stderr, flush=True)
    ok = ps.wait_done(timeout_s=cfg.run_timeout_s)
    result = {
        "role": "ps-standby" if standby and not ps.promoted
        else "ps-shard", "shard": index, "done": bool(ok),
        "accepted": ps.accepted, "dropped": ps.dropped,
        "clock": ps._clock, "max_staleness": ps.max_staleness,
        "dedup_hits": ps.dedup_hits,
        "resumed_from": ps.resumed_from_k,
        "promoted": bool(ps.promoted),
        "epoch": ps.epoch,
        "fenced_rejects": ps.fenced_rejects,
        "accepted_by_wid": {str(w): c
                            for w, c in ps.accepted_by_wid.items()},
    }
    if index == 0 and (not standby or ps.promoted):
        # the primary's end-of-run eval plane -- a never-promoted
        # standby must not sit a collect_eval timeout for EVAL_RESULTs
        # that only ever go to the real primary
        nproc = int(os.environ.get("ASYNC_SHARD_WORKER_PROCS", "0"))
        traj = None
        if nproc > 0:
            total = ps.collect_eval(nproc, timeout_s=60.0)
            if total is not None:
                times, _W = ps.snapshot_stack()
                # worker-side assembled stacks are tail-aligned across
                # shards: loss rows pair with the TAIL of this primary's
                # snapshot times
                times = times[-len(total):]
                traj = [[t, float(l) / n] for t, l in zip(times, total)]
        result["trajectory"] = traj
        result["recovery"] = sup.counters() if sup is not None else None
    # one last durable save before exit: a graceful teardown leaves the
    # freshest possible restart point for the next life
    try:
        ps.save_checkpoint()
    except Exception:  # noqa: BLE001
        pass
    print(json.dumps(result), flush=True)
    print(f"shard {index} done: {result}", file=sys.stderr, flush=True)
    # post-done linger: serve the range until the controller says stop
    # (bounded so a controller that died without SIGTERM cannot strand
    # an orphan serving forever)
    term.wait(timeout=float(os.environ.get("ASYNC_SHARD_LINGER_S", "600")))
    if controller is not None:
        controller.stop()
    if ctrl_fanout is not None:
        ctrl_fanout.stop()
    ps.stop()
    return 0


if __name__ == "__main__":
    sys.exit(_child_main())
