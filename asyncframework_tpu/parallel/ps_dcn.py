"""Asynchronous parameter server across OS processes (the DCN channel).

Parity: the reference's whole point is async gradient flow from REMOTE
workers to the driver -- executor processes push task results over Netty
RPC to the driver's result queue
(``CoarseGrainedSchedulerBackend.scala:239-307``,
``CoarseGrainedExecutorBackend.scala:92``), where the updater thread applies
the tau-filter and gamma-schedule.  This module is that capability for the
TPU build: a **parameter-server process** owning the model on its device,
and **worker processes** owning data shards on theirs, joined by a thin
length-prefixed TCP protocol (the Netty-RPC analog; deliberately NOT
``jax.distributed`` collectives -- XLA collectives are lockstep SPMD, and
bounded-staleness asynchrony is precisely the regime where lockstep is
wrong.  Spark's channel is an RPC mesh for the same reason).

Semantics preserved from the single-process engine (solvers/asgd.py):

- logical clock = number of merged gradients; a model handed to a worker is
  stamped with the clock at send time; staleness at merge = clock - stamp;
  accept iff ``staleness <= taw`` else drop (worker is re-served either way)
  -- ``SparkASGDThread.scala:169,199-202``.
- accept applies ``w -= gamma/sqrt(k/P+1)/parRecs * g`` on the PS device via
  the SAME jitted ``make_asgd_apply`` executable the single-process updater
  uses.
- partial-barrier cohorts: with ``bucket_ratio > 0`` the PS releases PULL
  requests in waves -- it holds arriving pulls until
  ``floor(P * bucket_ratio)`` workers are simultaneously waiting, then
  serves all of them the same model version (``ASYNCbarrier`` +
  ``bucketRatio`` wait loop, ``SparkASGDThread.scala:230-234,282-283``).
- straggler injection: workers apply the DelayModel locally after the PS
  finishes calibration and broadcasts the measured average task time
  (``SparkASGDThread.scala:121-138,244-249``).

Wire protocol (one JSON header line + optional raw f32/npz payload, length
prefixed): PULL -> MODEL(k, w) | PUSH(ts, g) -> ACK(accepted) |
EVAL(W stack) -> LOSSES | DONE.  The PS cannot evaluate the loss trajectory
itself (it holds no data), so at end-of-run each worker scores the snapshot
stack against its shards and the PS sums -- the distributed analog of
``optVars`` evaluation (``SparkASGDThread.scala:386-401``).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_HDR = struct.Struct("!I")  # 4-byte big-endian frame length


# ------------------------------------------------------------------ framing
def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    head = json.dumps(header).encode()
    sock.sendall(_HDR.pack(len(head)) + head + _HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    (hlen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    header = json.loads(_recv_exact(sock, hlen))
    (plen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


# ----------------------------------------------------------------- PS side
class ParameterServer:
    """Driver-side PS: accept worker connections, run the updater semantics.

    One handler thread per worker connection (the reference's RPC dispatcher
    threads); the model/clock live behind one lock (single-writer updater
    discipline -- the TPU build's answer to the reference's benign races,
    SURVEY.md section 5).
    """

    def __init__(self, cfg, d: int, n: int, device=None, host: str = "0.0.0.0",
                 port: int = 0):
        import jax
        import jax.numpy as jnp

        from asyncframework_tpu.ops import steps

        self.cfg = cfg
        self.d, self.n = d, n
        self.device = device if device is not None else jax.devices()[0]
        self._apply = steps.make_asgd_apply(
            cfg.gamma, cfg.batch_rate, n, cfg.num_workers
        )
        self._w = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        self._k_dev = jax.device_put(jnp.float32(0.0), self.device)
        # warm the accept path before the clock starts (first-iteration
        # blocking parity) -- donated dummies, never live state
        zw = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        zg = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        zk = jax.device_put(jnp.float32(0.0), self.device)
        self._apply(zw, zg, zk)

        self._lock = threading.Lock()
        self._w_host: Optional[np.ndarray] = None  # host cache per version
        self._clock = 0          # merged gradients (ASYNCcontext.CurrentTime)
        self._k = 0              # accepted updates
        self.accepted = 0
        self.dropped = 0
        self.max_staleness = 0
        self._snapshots: List[Tuple[float, object]] = []
        self._t0: Optional[float] = None
        self._done = threading.Event()
        # calibration (SparkASGDThread.scala:174-183)
        self._cal_ms = 0.0
        self._cal_n = 0
        self.avg_delay_ms = 0.0
        self._pull_times: Dict[int, float] = {}
        # cohort wave gate (ASYNCbarrier + bucketRatio)
        self._wave_cv = threading.Condition()
        self._waiting: List[int] = []
        self._wave_id = 0

        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._eval_results: Dict[int, np.ndarray] = {}
        self._eval_cv = threading.Condition()
        self._stop = threading.Event()

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ParameterServer":
        self._t0 = time.monotonic()
        with self._lock:
            self._snapshots.append((0.0, np.asarray(self._w)))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ps-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1e3

    # ------------------------------------------------------------- protocol
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                header, payload = _recv_msg(conn)
                op = header["op"]
                if op == "PULL":
                    self._handle_pull(conn, int(header["wid"]))
                elif op == "PUSH":
                    self._handle_push(conn, header, payload)
                elif op == "SNAPSHOTS":
                    # only meaningful once the run is done; the stack is
                    # consistent either way (lock-copied)
                    times, W = self.snapshot_stack()
                    _send_msg(
                        conn,
                        {"op": "SNAPSHOTS", "times": times,
                         "shape": list(W.shape)},
                        np.ascontiguousarray(W, np.float32).tobytes(),
                    )
                elif op == "EVAL_RESULT":
                    arr = np.frombuffer(payload, np.float64).copy()
                    with self._eval_cv:
                        self._eval_results[int(header["wid"])] = arr
                        self._eval_cv.notify_all()
                    _send_msg(conn, {"op": "ACK"})
                elif op == "BYE":
                    _send_msg(conn, {"op": "ACK"})
                    return
                else:
                    _send_msg(conn, {"op": "ERR", "msg": f"bad op {op}"})
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _handle_pull(self, conn: socket.socket, wid: int) -> None:
        if self._done.is_set():
            _send_msg(conn, {"op": "DONE"})
            return
        threshold = max(self.cfg.bucket_threshold, 1)
        STARVATION_S = 1.0  # degraded-cohort release when peers are gone
        with self._wave_cv:
            self._waiting.append(wid)
            my_wave = self._wave_id
            if len(self._waiting) >= threshold:
                # release the cohort: everyone currently waiting rides this
                # wave (the partial barrier firing)
                self._wave_id += 1
                self._waiting.clear()
                self._wave_cv.notify_all()
            else:
                t_enter = time.monotonic()
                while (
                    my_wave == self._wave_id
                    and not self._done.is_set()
                    and not self._stop.is_set()
                ):
                    self._wave_cv.wait(timeout=0.05)
                    # starvation fallback: when fewer than threshold workers
                    # are still alive the wave can never fill -- after a
                    # full second of waiting, release whoever is here as a
                    # degraded cohort (the reference's wait loop assumes
                    # workers come back; dead ones never do)
                    if (
                        my_wave == self._wave_id
                        and time.monotonic() - t_enter > STARVATION_S
                    ):
                        self._wave_id += 1
                        self._waiting.clear()
                        self._wave_cv.notify_all()
                        break
        if self._done.is_set():
            _send_msg(conn, {"op": "DONE"})
            return
        with self._lock:
            ts = self._clock
            # one readback per model VERSION, not per pull: a whole cohort
            # reads the same bytes
            if self._w_host is None:
                self._w_host = np.asarray(self._w)
            w_host = self._w_host
            self._pull_times[wid] = self._now_ms()
            avg = self.avg_delay_ms
        _send_msg(
            conn,
            {"op": "MODEL", "ts": ts, "avg_delay_ms": avg,
             "calibrated": self._cal_n >= self.cfg.effective_calibration_iters()},
            w_host.astype(np.float32).tobytes(),
        )

    def _handle_push(self, conn: socket.socket, header: dict,
                     payload: bytes) -> None:
        import jax

        wid = int(header["wid"])
        ts = int(header["ts"])
        g_host = np.frombuffer(payload, np.float32)
        do_snapshot = False
        with self._lock:
            staleness = self._clock - ts
            self.max_staleness = max(self.max_staleness, staleness)
            task_ms = self._now_ms() - self._pull_times.get(wid, self._now_ms())
            if self._cal_n < self.cfg.effective_calibration_iters():
                self._cal_ms += task_ms
                self._cal_n += 1
                if self._cal_n >= self.cfg.effective_calibration_iters():
                    self.avg_delay_ms = self._cal_ms / max(self._cal_n, 1)
            accepted = (
                staleness <= self.cfg.taw
                and self._k < self.cfg.num_iterations
            )
            if accepted:
                g_dev = jax.device_put(g_host, self.device)
                self._w, self._k_dev = self._apply(self._w, g_dev, self._k_dev)
                self._w_host = None  # new version; next pull re-materializes
                self._k += 1
                self.accepted += 1
                if self._k % self.cfg.printer_freq == 0:
                    do_snapshot = True
                if self._k >= self.cfg.num_iterations:
                    self._done.set()
            else:
                self.dropped += 1
            self._clock += 1
            if do_snapshot:
                # host copy NOW: the snapshot must pin this version (the PS
                # has no immutable-handle trick across the wire anyway)
                self._snapshots.append((self._now_ms(), np.asarray(self._w)))
        with self._wave_cv:
            self._wave_cv.notify_all()  # a wave may now meet its threshold
        _send_msg(conn, {"op": "ACK", "accepted": bool(accepted),
                         "done": self._done.is_set()})

    # ------------------------------------------------------------ evaluation
    def wait_done(self, timeout_s: float) -> bool:
        return self._done.wait(timeout=timeout_s)

    def snapshot_stack(self) -> Tuple[List[float], np.ndarray]:
        with self._lock:
            final = (self._now_ms(), np.asarray(self._w))
            snaps = list(self._snapshots) + [final]
        times = [t for (t, _w) in snaps]
        W = np.stack([w for (_t, w) in snaps])
        return times, W

    def collect_eval(self, num_worker_procs: int, timeout_s: float
                     ) -> Optional[np.ndarray]:
        """Sum per-process snapshot losses pushed via EVAL_RESULT."""
        deadline = time.monotonic() + timeout_s
        with self._eval_cv:
            while len(self._eval_results) < num_worker_procs:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._eval_cv.wait(timeout=min(left, 0.2))
            total = None
            for arr in self._eval_results.values():
                total = arr if total is None else total + arr
            return total

    def stop(self) -> None:
        self._stop.set()
        self._done.set()
        with self._wave_cv:
            self._wave_cv.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass


# -------------------------------------------------------------- worker side
class PSClient:
    """One TCP connection to the PS (workers may hold several, one per
    logical worker id, or share one -- the protocol is synchronous per
    connection, like an RpcEndpointRef)."""

    def __init__(self, host: str, port: int, timeout_s: float = 120.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)

    def pull(self, wid: int) -> Optional[Tuple[int, np.ndarray, float, bool]]:
        """Returns (ts, w, avg_delay_ms, calibrated) or None when DONE."""
        _send_msg(self.sock, {"op": "PULL", "wid": wid})
        header, payload = _recv_msg(self.sock)
        if header["op"] == "DONE":
            return None
        w = np.frombuffer(payload, np.float32)
        return (int(header["ts"]), w, float(header["avg_delay_ms"]),
                bool(header["calibrated"]))

    def push(self, wid: int, ts: int, g: np.ndarray) -> Tuple[bool, bool]:
        """Returns (accepted, run_done)."""
        _send_msg(self.sock, {"op": "PUSH", "wid": wid, "ts": ts},
                  np.asarray(g, np.float32).tobytes())
        header, _ = _recv_msg(self.sock)
        return bool(header.get("accepted")), bool(header.get("done"))

    def snapshots(self) -> Tuple[List[float], np.ndarray]:
        _send_msg(self.sock, {"op": "SNAPSHOTS"})
        header, payload = _recv_msg(self.sock)
        W = np.frombuffer(payload, np.float32).reshape(header["shape"])
        return list(header["times"]), W

    def send_eval(self, wid: int, losses: np.ndarray) -> None:
        _send_msg(self.sock, {"op": "EVAL_RESULT", "wid": wid},
                  np.asarray(losses, np.float64).tobytes())
        _recv_msg(self.sock)

    def bye(self) -> None:
        try:
            _send_msg(self.sock, {"op": "BYE"})
            _recv_msg(self.sock)
        except (ConnectionError, OSError):
            pass
        self.sock.close()


def run_worker_process(
    host: str,
    port: int,
    wids: List[int],
    shards: Dict[int, object],
    cfg,
    d: int,
    n: int,
    eval_wid: Optional[int] = None,
    deadline_s: float = 600.0,
) -> Dict[int, int]:
    """Worker-process main loop: one thread per owned logical worker, each
    pulling models and pushing gradients until the PS says DONE.

    ``shards``: wid -> Shard (device-resident, this process's chips).
    Returns per-wid gradient counts.  When ``eval_wid`` is set, after DONE
    this process scores the PS's snapshot stack over ALL its shards and
    pushes one EVAL_RESULT (the distributed optVars evaluation).
    """
    import jax

    from asyncframework_tpu.engine.straggler import DelayModel
    from asyncframework_tpu.ops import steps

    step = steps.make_asgd_worker_step(cfg.batch_rate, cfg.loss)
    delay_model = DelayModel(cfg.coeff, cfg.num_workers, cfg.seed)
    counts = {wid: 0 for wid in wids}
    stop = threading.Event()
    calibrated_once = threading.Event()

    def worker_loop(wid: int) -> None:
        cl = PSClient(host, port)
        shard = shards[wid]
        dev = shard.X.device
        key = jax.device_put(
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid), dev
        )
        deadline = time.monotonic() + deadline_s
        try:
            while not stop.is_set() and time.monotonic() < deadline:
                got = cl.pull(wid)
                if got is None:
                    break
                ts, w_host, avg_ms, calibrated = got
                if calibrated and not calibrated_once.is_set():
                    delay_model.calibrate(avg_ms)
                    calibrated_once.set()
                dly = delay_model.delay_ms(wid) if calibrated else 0.0
                if dly > 0:
                    time.sleep(dly / 1e3)
                w_dev = jax.device_put(w_host, dev)
                g, new_key = step(shard.X, shard.y, w_dev, key)
                key = new_key
                g_host = np.asarray(g)  # the push IS a readback by design
                counts[wid] += 1
                _accepted, done = cl.push(wid, ts, g_host)
                if done:
                    break
        finally:
            cl.bye()

    threads = [
        threading.Thread(target=worker_loop, args=(w,), daemon=True)
        for w in wids
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline_s)
    if eval_wid is not None:
        # distributed optVars evaluation: score the PS's snapshot stack over
        # this process's shards, push one summed loss vector
        cl = PSClient(host, port)
        try:
            times, W = cl.snapshots()
            losses = evaluate_snapshots_on_shards(shards, times, W, cfg.loss)
            cl.send_eval(eval_wid, losses)
        finally:
            cl.bye()
    return counts


def evaluate_snapshots_on_shards(shards: Dict[int, object], times: List[float],
                                 W: np.ndarray, loss: str = "least_squares"
                                 ) -> np.ndarray:
    """Per-snapshot loss SUMS over this process's shards (caller divides by
    global N after summing across processes)."""
    import jax
    import jax.numpy as jnp

    from asyncframework_tpu.ops import steps

    ev = steps.make_trajectory_loss_eval(loss)
    total = np.zeros(W.shape[0], np.float64)
    for shard in shards.values():
        Wd = jax.device_put(jnp.asarray(W), shard.X.device)
        total += np.asarray(ev(shard.X, shard.y, Wd), np.float64)
    return total
