"""Asynchronous parameter server across OS processes (the DCN channel).

Parity: the reference's whole point is async gradient flow from REMOTE
workers to the driver -- executor processes push task results over Netty
RPC to the driver's result queue
(``CoarseGrainedSchedulerBackend.scala:239-307``,
``CoarseGrainedExecutorBackend.scala:92``), where the updater thread applies
the tau-filter and gamma-schedule.  This module is that capability for the
TPU build: a **parameter-server process** owning the model on its device,
and **worker processes** owning data shards on theirs, joined by a thin
length-prefixed TCP protocol (the Netty-RPC analog; deliberately NOT
``jax.distributed`` collectives -- XLA collectives are lockstep SPMD, and
bounded-staleness asynchrony is precisely the regime where lockstep is
wrong.  Spark's channel is an RPC mesh for the same reason).

Semantics preserved from the single-process engine (solvers/asgd.py):

- logical clock = number of merged gradients; a model handed to a worker is
  stamped with the clock at send time; staleness at merge = clock - stamp;
  accept iff ``staleness <= taw`` else drop (worker is re-served either way)
  -- ``SparkASGDThread.scala:169,199-202``.
- accept applies ``w -= gamma/sqrt(k/P+1)/parRecs * g`` on the PS device via
  the SAME jitted ``make_asgd_apply`` executable the single-process updater
  uses.
- partial-barrier cohorts: with ``bucket_ratio > 0`` the PS releases PULL
  requests in waves -- it holds arriving pulls until
  ``floor(P * bucket_ratio)`` workers are simultaneously waiting, then
  serves all of them the same model version (``ASYNCbarrier`` +
  ``bucketRatio`` wait loop, ``SparkASGDThread.scala:230-234,282-283``).
- straggler injection: workers apply the DelayModel locally after the PS
  finishes calibration and broadcasts the measured average task time
  (``SparkASGDThread.scala:121-138,244-249``).

Wire protocol (one JSON header line + optional raw f32/npz payload, length
prefixed): PULL -> MODEL(k, w) | PUSH(ts, g) -> ACK(accepted) |
EVAL(W stack) -> LOSSES | DONE.  The PS cannot evaluate the loss trajectory
itself (it holds no data), so at end-of-run each worker scores the snapshot
stack against its shards and the PS sums -- the distributed analog of
``optVars`` evaluation (``SparkASGDThread.scala:386-401``).

Extensions past the ASGD-dense core:

- **ASAGA** (``algo="asaga"``): the PS owns the per-sample scalar-history
  table and the sampling (``ScalarMap`` + ``sampledMap``,
  ``SparkASAGAThread.scala:114,280-294``).  PULL carries the worker's shard
  size; MODEL ships capacity-padded ``(idx, alpha[idx])`` with the model;
  PUSH returns the gradient plus candidate scalars, which the PS commits
  only on accept (the driver-controlled ScalarMap merge) before the
  three-term update ``w -= gamma*(g/parRecs + alpha_bar)``,
  ``alpha_bar += g/N`` (``:210-213``).
- **Sparse gradients** (``enc="sparse"``): rcv1-class pushes ship
  ``(idx u32, val f32)`` pairs when that beats the dense ``d*4`` bytes; the
  PS scatters into dense before its (dense) apply.  Workers decide per push
  -- a near-dense gradient goes dense.

Data-plane throughput overhaul (version-cached replies, delta pulls,
vectored framing, batched apply):

- **Version-cached encoded replies**: the PS serializes the model ONCE per
  version (host array + payload bytes + CRC); an entire cohort pull of an
  unchanged version is a dict lookup plus a vectored socket write (the
  backing array is float32 -- the old per-pull ``astype(...).tobytes()``
  copy is gone).
- **Version-gated delta pulls** (``async.pull.mode=delta``): workers send
  ``have=<ts>``; the PS answers NOT_MODIFIED (zero model payload -- common
  under wave gating and straggler re-pulls), a byte-exact XOR sparse delta
  against a recent cached version (``net/wiredelta.py``,
  ``async.pull.delta.versions``), or the full model, whichever is
  smallest.  Every non-full reply carries the current version's CRC32; a
  client-side mismatch or basis-cache miss falls back to a full pull --
  the path can degrade to the legacy wire, never to a wrong model.  A
  pull WITHOUT ``have`` gets the legacy reply, byte-identical.
- **Batched gradient apply** (``async.push.merge``): pushes pending at
  model-lock acquisition coalesce into ONE fused device apply
  (``ops/steps.make_*_apply_merge`` -- a ``lax.scan`` over the serial
  apply expression, bit-identical to one-dispatch-per-push), with
  per-push accept/reject, dedup, and trace spans preserved per item.

Pipelined update loop (``async.pipeline.depth``):

- **Lock-free PULL serving**: the PS publishes a per-version
  :class:`_ModelSnap` ``(ts, host array, payload bytes, CRC)`` via atomic
  reference swap; ``_handle_pull`` serves full/NOT_MODIFIED/delta replies
  from the published snapshot without ever touching the model lock (only
  the wave gate and small bookkeeping locks remain on the pull path), so
  a cohort pull never queues behind a merge drain and vice versa.  The
  debug lock watchdog (``net/lockwatch.py``, ``async.debug.lockwatch``)
  asserts the claim at the frame choke points.
- **Prefetched pulls + decoupled pushes** (worker side, depth >= 1): a
  prefetch thread on a SECOND PSClient connection pulls model v(k+1)
  while step k computes (delta-mode ``have=`` pulls make an unchanged
  version nearly free), and pushes are handed to a bounded in-flight
  sender so the next compute starts before the push ACK returns.
  Staleness stays bounded: the PS's taw admission prices the extra
  in-flight steps, and a taw REJECTION makes the worker discard its
  prefetched model and re-pull fresh (counted as a stale-prefetch
  discard).  Exactly-once push semantics ride the session/dedup
  machinery unchanged; adoption orders and RELEASED/DONE work on both
  connections.  Depth 0 (the default outside ``async-cluster``) is the
  classic serial loop, byte- and step-identical.
"""

from __future__ import annotations

import io
import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from asyncframework_tpu.metrics import flightrec as _flight
from asyncframework_tpu.metrics import profiler as _prof
from asyncframework_tpu.metrics import trace as _trace
from asyncframework_tpu.net import ClientSession, DedupWindow, RetryPolicy
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import shmring as _shmring
from asyncframework_tpu.net import wirecodec, wiredelta
from asyncframework_tpu.parallel import supervisor as supervisor_mod
from asyncframework_tpu.parallel.supervisor import ElasticSupervisor

# ------------------------------------------------------------------ framing
# The framing moved to net/frame.py (one choke point for the whole control
# + data plane, with fault-injection hooks); these aliases keep the
# historical import site alive for everything that learned it here.
_send_msg = _frame.send_msg
_recv_exact = _frame.recv_exact
_recv_msg = _frame.recv_msg


# ------------------------------------------------- pipeline counters
# Process-global pipelined-loop totals (live UI "pipeline" section).  The
# worker loops accumulate locally (one _PipelineStats per worker process
# run) and ship deltas on PUSH/BYE headers; the PS folds them here -- so
# the counters land in the process that serves the dashboard whether the
# workers are threads in this process or real OS processes across a DCN.
_pl_lock = threading.Lock()
_pl_totals: Dict[str, int] = {}


def pipeline_totals() -> Dict[str, int]:
    """Pipelined update-loop counters: prefetch_hits (model was already
    waiting when the loop asked), prefetch_waits (the loop blocked on the
    prefetch), stale_discards (prefetched model thrown away after a taw
    rejection), pushes_async (pushes sent by the decoupled sender),
    push_errors (pushes whose whole retry budget was spent),
    inflight_max (max unacked pushes observed)."""
    with _pl_lock:
        return dict(_pl_totals)


def reset_pipeline_totals() -> None:
    """Zero the process-global pipeline counters (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    with _pl_lock:
        _pl_totals.clear()


def _pl_fold(delta: Dict[str, int]) -> None:
    """Fold a wire-shipped counter delta; ``inflight_max`` is a high-water
    mark (max-merged), everything else a monotone count."""
    if not delta:
        return
    with _pl_lock:
        for k, v in delta.items():
            try:
                v = int(v)
            except (TypeError, ValueError):
                continue
            if k == "inflight_max":
                if v > _pl_totals.get(k, 0):
                    _pl_totals[k] = v
            else:
                _pl_totals[k] = _pl_totals.get(k, 0) + v


def _cv_fold(wire, clock: int = 0,
             wall_ms: Optional[float] = None) -> None:
    """Fold piggybacked convergence samples (the ``cv`` PUSH/BYE header
    entry: ``[[version, loss, grad_norm], ...]``) into the process-global
    :class:`~asyncframework_tpu.metrics.timeseries.ConvergenceHistory`,
    stamped with the PS run clock's wallclock and the staleness the PS
    observes (merge clock minus the sample's model version).  Dedup'd
    PUSH retries never reach the handlers, so a sample folds exactly
    once -- the span/pipeline-counter discipline."""
    if not wire:
        return
    from asyncframework_tpu.metrics import timeseries as _ts

    conv = _ts.convergence()
    now_ms = wall_ms if wall_ms is not None else time.time() * 1e3
    for item in wire:
        try:
            version = int(item[0])
            loss = item[1]
            gnorm = item[2] if len(item) > 2 else None
        except (TypeError, ValueError, IndexError):
            continue  # junk from the wire must not kill the handler
        conv.add(now_ms, version, loss=loss, grad_norm=gnorm,
                 staleness=max(0, clock - version) if clock else None)


class _PipelineStats:
    """Per-worker-process pipeline counters, shipped to the PS as deltas
    on PUSH headers (``pl``) and on BYE -- the same piggyback discipline
    as trace spans, so the PS-side live UI sees them even when the worker
    is a separate OS process.  A delta taken for a push that terminally
    fails is merged back so the counts ride the next attempt."""

    __slots__ = ("_lock", "_counts", "_shipped_inflight_max")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._shipped_inflight_max = 0

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def high_water(self, key: str, v: int) -> None:
        with self._lock:
            if v > self._counts.get(key, 0):
                self._counts[key] = v

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def take_wire(self) -> Dict[str, int]:
        """Unshipped counter delta (empty dict = nothing to ship, no
        header field, no wire bytes)."""
        with self._lock:
            out = {k: v for k, v in self._counts.items()
                   if k != "inflight_max" and v}
            hw = self._counts.get("inflight_max", 0)
            if hw > self._shipped_inflight_max:
                out["inflight_max"] = hw
                self._shipped_inflight_max = hw
            for k in out:
                if k != "inflight_max":
                    self._counts[k] = 0
            return out

    def merge_back(self, delta: Dict[str, int]) -> None:
        with self._lock:
            for k, v in delta.items():
                if k == "inflight_max":
                    continue  # the high-water mark survives locally
                self._counts[k] = self._counts.get(k, 0) + v


class _ModelSnap:
    """One published model version: the host float32 array, its serialized
    payload bytes, and the CRC32 integrity stamp -- immutable once built,
    swapped in by atomic reference assignment so ``_handle_pull`` can
    serve any reply shape without the model lock."""

    __slots__ = ("ts", "w_host", "wire", "crc", "gen")

    def __init__(self, ts: int, w_host: np.ndarray, wire: bytes, crc: int,
                 gen: int):
        self.ts = ts
        self.w_host = w_host
        self.wire = wire
        self.crc = crc
        #: model GENERATION the build basis carried (bumped on every
        #: accepted push): the send-time clock re-stamp in _handle_pull
        #: is allowed only while the generation is unchanged -- dropped
        #: pushes tick the clock but not the generation, accepted ones
        #: tick both, so gen equality proves "same bytes, newer clock"
        self.gen = gen


class WaitDone:
    """Result of :meth:`ParameterServer.wait_done`: truthy iff the run
    finished; otherwise carries the per-worker progress diagnostic (old
    callers that only truth-test keep working, new callers can print WHY
    the run did not finish)."""

    __slots__ = ("done", "diagnostic")

    def __init__(self, done: bool, diagnostic: Optional[str]):
        self.done = bool(done)
        self.diagnostic = diagnostic

    def __bool__(self) -> bool:
        return self.done

    def __repr__(self) -> str:
        return "WaitDone(done)" if self.done else (
            f"WaitDone(not done)\n{self.diagnostic}"
        )

    def __str__(self) -> str:
        return "done" if self.done else (self.diagnostic or "not done")


class _PendingPush:
    """One decoded PUSH waiting in the PS merge queue.

    The handler thread decodes the payload OUTSIDE the model lock, enqueues
    this record, and whoever holds the lock next drains every pending push
    into one fused device apply (``_drain_merge_locked``) -- per-push
    accept/reject, dedup, calibration, and trace bookkeeping all happen
    per item in FIFO order, exactly as the serial path did; only the
    device dispatch is coalesced."""

    __slots__ = ("wid", "ts", "g_host", "diff", "header", "payload_len",
                 "tc", "t_queue0", "done", "ack", "accepted", "staleness",
                 "task_ms", "t_apply0", "t_done", "k_at_merge",
                 "do_snapshot", "damp")

    def __init__(self, wid: int, ts: int, g_host, diff, header: dict,
                 payload_len: int, tc, t_queue0: float):
        self.wid, self.ts = wid, ts
        self.g_host, self.diff = g_host, diff
        self.header, self.payload_len = header, payload_len
        self.tc, self.t_queue0 = tc, t_queue0
        self.done = False
        self.ack: dict = {}
        self.accepted = False
        self.staleness = 0
        self.task_ms = 0.0
        self.t_apply0 = 0.0
        self.t_done = 0.0
        self.k_at_merge = 0
        self.do_snapshot = False
        # delay-adaptive step-DAMP factor, decided per item at drain
        # time from the installed CTRL law (1.0 = undamped, the only
        # value with control off -- bit-identical legacy apply)
        self.damp = 1.0


# ----------------------------------------------------------------- PS side
class ParameterServer:
    """Driver-side PS: accept worker connections, run the updater semantics.

    One handler thread per worker connection (the reference's RPC dispatcher
    threads); the model/clock live behind one lock (single-writer updater
    discipline -- the TPU build's answer to the reference's benign races,
    SURVEY.md section 5).
    """

    def __init__(self, cfg, d: int, n: int, device=None, host: str = "0.0.0.0",
                 port: int = 0, algo: str = "asgd",
                 checkpoint_path: Optional[str] = None,
                 supervisor: Optional[ElasticSupervisor] = None,
                 bus=None, shard_map=None, shard_index: int = 0,
                 epoch: Optional[int] = None, shard_epochs=None,
                 standby: bool = False):
        import jax
        import jax.numpy as jnp

        from asyncframework_tpu.ops import steps

        if algo not in ("asgd", "asaga"):
            raise ValueError(f"unknown PS algo {algo!r}")
        self.cfg = cfg
        self.d, self.n = d, n
        self.algo = algo
        # fencing epoch (async.fence.enabled): 0 = fencing off, the
        # byte-identical legacy wire (no ep header keys anywhere).  > 0 =
        # this server incarnation's minted epoch; every PULL/PUSH/
        # SUBSCRIBE stamped with a DIFFERENT epoch is answered
        # REJECT_FENCED (admission in _fence_reject), so a deposed client
        # replaying buffered pushes -- or any op routed at a deposed
        # incarnation of this range -- can never double-apply against the
        # current owner's state.  Restoring from checkpoint bumps past
        # the persisted epoch (every incarnation is a new epoch), and a
        # controller (shardgroup.ShardGroup) passes an explicit epoch
        # that already counts its lease-expiry fences.
        if epoch is None:
            from asyncframework_tpu.conf import FENCE_ENABLED
            from asyncframework_tpu.conf import global_conf as _gc

            epoch = 1 if _gc().get(FENCE_ENABLED) else 0
        self.epoch = int(epoch)
        #: per-shard epochs of the whole group (index-aligned with
        #: shard_map); installed by SETMAP / the launcher so WELCOME can
        #: hand workers the full epoch vector next to the map
        self.shard_epochs = ([int(e) for e in shard_epochs]
                             if shard_epochs else None)
        #: highest foreign epoch seen ABOVE ours: once a client proves a
        #: successor exists for this range, this incarnation is a zombie
        #: and refuses every stamped op (even same-epoch ones) -- "never
        #: mutate or serve a range it no longer owns"
        self._fenced_above = 0
        self.fenced_rejects = 0
        # sharded PS group (parallel/shardgroup.py): when this server is one
        # range of a shard group, ``shard_map`` is the group's wire map
        # (per-shard [host, port, lo, hi]) and ``shard_index`` names this
        # server's range.  The map is what HELLO's WELCOME reply hands
        # workers so they resolve the group with no side channel; it may
        # also be installed after construction (SETMAP, or attribute
        # assignment before start).  None/0 = the classic single PS --
        # WELCOME omits the key and the wire stays byte-identical.
        self.shard_map = [list(e) for e in shard_map] if shard_map else None
        self.shard_index = int(shard_index)
        # hot-standby replication (parallel/replication.py, ISSUE 13).
        # standby=True: this server is a WARM STANDBY -- it refuses the
        # training plane (PULL/PUSH answer ERR; it is not in the shard
        # map), applies its primary's replicated merge batches
        # (REPL_SYNC bootstrap + REPL_APPEND stream) through the same
        # jitted kernel, and serves SUBSCRIBE/SHARDMAP reads from the
        # mirrored snapshot (staleness priced by replication lag).  A
        # PROMOTE order flips it to range primary under the minted
        # epoch.  standby_map names every shard's standby endpoint
        # ([host, port] | None per range, installed via SETMAP or the
        # launcher); a PRIMARY whose own entry is set runs a
        # ReplicationStream (self.repl) to it.
        self._standby = bool(standby)
        self.standby_map: Optional[List] = None
        self.repl = None
        self.promoted = False
        self.checkpoint_path = checkpoint_path
        self.resumed_from_k: Optional[int] = None
        self.device = device if device is not None else jax.devices()[0]
        self._w = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        self._k_dev = jax.device_put(jnp.float32(0.0), self.device)
        zw = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        zg = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
        if algo == "asaga":
            # ScalarMap semantics (SparkASAGAThread.scala:114,280-294): the
            # PS owns the per-sample history table AND the sampling -- it
            # draws each worker's Bernoulli(b) rows, ships (idx, alpha[idx])
            # with the model, and commits returned scalars only on accept.
            # delta == g is EXACT here (unlike the single-process engine,
            # which recomputes the delta -- see make_saga_table_delta): a
            # worker's samples live in its own shard, no other worker can
            # touch those table entries, and the per-connection pull->push
            # protocol serializes the worker against its own commits, so the
            # alpha the gradient was built against IS the alpha at commit.
            # donate_g=False: the same device buffer is passed as g and delta.
            self._apply = steps.make_saga_apply(
                cfg.gamma, cfg.batch_rate, n, cfg.num_workers, donate_g=False
            )
            self._ab = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
            self._table: Dict[int, np.ndarray] = {}   # wid -> shard scalars
            self._rngs: Dict[int, np.random.Generator] = {}
            self._pending_idx: Dict[int, np.ndarray] = {}  # outstanding pull
            # guards table/rng structure + contents against the checkpoint
            # writer's iteration (lock order: _lock -> _saga_lock); pulls
            # hold it WITHOUT _lock so sampling never queues the apply path
            self._saga_lock = threading.Lock()
            zab = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
            self._apply(zw, zab, zg, zg)
        else:
            self._apply = steps.make_asgd_apply(
                cfg.gamma, cfg.batch_rate, n, cfg.num_workers
            )
            # warm the accept path before the clock starts (first-iteration
            # blocking parity) -- donated dummies, never live state
            zk = jax.device_put(jnp.float32(0.0), self.device)
            self._apply(zw, zg, zk)

        # debug lock watchdog (net/lockwatch.py, async.debug.lockwatch):
        # the model lock becomes a watched lock -- any socket send/recv
        # under it raises at the frame choke point, continuously checking
        # the lock-free PULL-serving claim in chaos/soak runs.  The other
        # contended PS locks ride named_lock too, feeding the lock-order
        # race detector acquisition edges (a cycle among ps.model /
        # ps.stats / ps.versions / supervisor.members is a potential
        # deadlock caught at the first nested hold, not in production).
        from asyncframework_tpu.net import lockwatch as _lockwatch

        self._lock = _lockwatch.named_lock("ps.model")
        # ---- data plane: version-cached encoded PULL replies + deltas.
        # One readback AND one encode per model version, published as an
        # immutable _ModelSnap (host float32 array + serialized payload
        # bytes + CRC) via ATOMIC REFERENCE SWAP: _handle_pull serves
        # full/NOT_MODIFIED/delta replies from the published snapshot
        # without touching the model lock -- a whole cohort pull of an
        # unchanged version is an attribute read + a socket write, and
        # PULL serving never queues behind a merge drain.  An accepted
        # push clears the reference; the next pull rebuilds (readback +
        # encode happen OUTSIDE the model lock, under _snap_build_lock so
        # a cohort triggers one build, not P).  _w_versions keeps recent
        # versions' host arrays (bounded, version-age eviction, its own
        # small lock) so a worker pulling with ``have=<ts>`` can be
        # served a byte-exact XOR delta (net/wiredelta.py).
        self._snap: Optional[_ModelSnap] = None
        # the build BASIS: (clock, device array) captured atomically at
        # the end of every applying drain (O(1) tuple write under the
        # lock the drain already holds).  A snapshot rebuild reads this
        # reference instead of taking the model lock -- the pull path
        # stays off the model lock even while rebuilding, so a merge
        # convoy (continuous decoupled pushes keep handlers cycling the
        # lock) cannot add its queueing delay to pull latency.
        # model generation: +1 per ACCEPTED push (under the model lock,
        # BEFORE its clock tick).  Snapshot re-stamping and publishing
        # key off it -- see _ModelSnap.gen / _model_snap.
        self._model_gen = 0
        self._snap_basis: Tuple[int, object, int] = (0, self._w, 0)
        self._snap_build_lock = _lockwatch.named_lock("ps.snap_build")
        self._versions_lock = _lockwatch.named_lock("ps.versions")
        # pull-path bookkeeping (reply-shape counters, pull timestamps,
        # last-contact) keeps its own lock: read-modify-write safety
        # without ever touching the model lock from the pull path
        self._stats_lock = _lockwatch.named_lock("ps.stats")
        from collections import OrderedDict as _OD2
        from asyncframework_tpu.conf import (
            PULL_DELTA_VERSIONS,
            PUSH_MERGE,
            global_conf as _gconf,
        )

        self._w_versions: "_OD2[int, np.ndarray]" = _OD2()
        # an un-overridden cache depth auto-scales with the worker count: a
        # worker's basis is typically ~P versions old by its next pull (P
        # peers each merged once in between, plus clock ticks from drops),
        # so a cache shallower than that never hits.  Cost is host RAM
        # only: depth * d * 4 bytes of version arrays.
        if _gconf().contains(PULL_DELTA_VERSIONS.key):
            self._delta_versions = max(
                0, int(_gconf().get(PULL_DELTA_VERSIONS))
            )
        else:
            self._delta_versions = max(
                int(_gconf().get(PULL_DELTA_VERSIONS)),
                4 * cfg.num_workers + 2,
            )
        # the version cache is only maintained once a delta-capable client
        # shows up (first pull carrying ``have``): a full-mode deployment
        # pays zero cache RAM and zero per-pull cache work
        self._delta_clients_seen = False
        # pull-reply shape counters (bench/tests: the "zero payload bytes
        # per unchanged-version pull" claim is read off these)
        self.pull_replies: Dict[str, int] = {"full": 0, "nm": 0,
                                             "xdelta": 0}
        self.pull_model_bytes = 0  # model-part payload bytes sent via PULL
        # serving plane (asyncframework_tpu/serving/): SUBSCRIBE reply
        # shapes + bytes, counted apart from PULL so the training data
        # plane's bench numbers stay clean of read traffic
        self.subscribe_replies: Dict[str, int] = {"full": 0, "nm": 0,
                                                  "xdelta": 0}
        self.subscribe_model_bytes = 0
        # relaycast root offer path (asyncframework_tpu/relaycast/): a
        # SUBSCRIBE whose header carries ``rport`` registers the
        # subscriber as a direct relay child (the shared ChildRegistry:
        # bounded by async.relay.fanout with LRU eviction, so a deep
        # node that root-subscribed once cannot squat a slot a planned
        # direct child keeps renewing), and a lazy offer thread
        # announces each new version via RELAY_OFFER so depth-1 nodes
        # fetch event-driven instead of poll-bounded.  Offers are
        # advisory: a lost one costs nothing (the child's refresh loop
        # still polls).
        from asyncframework_tpu.conf import RELAY_FANOUT as _RF

        self._relay_fanout = max(1, int(_gconf().get(_RF)))
        self._relay_registry = None  # built with the first rport seen
        self._relay_lock = threading.Lock()
        self._relay_thread: Optional[threading.Thread] = None
        self._relay_offered = -1  # newest clock already offered
        self.relay_offers = 0
        # version birth times (bounded): ts -> run-clock ms at which that
        # model version was PUBLISHED by an applying drain.  Feeds the
        # freshness-lag-in-ms answer on SUBSCRIBE replies: the age of a
        # served version is "how long ago did a NEWER version appear",
        # which is 0 while the served version is still current (dropped
        # pushes tick the clock without changing the model, and leave no
        # entry here -- correctly aging nothing).
        self._born_lock = threading.Lock()
        from collections import OrderedDict as _ODB

        self._ver_born: "_ODB[int, float]" = _ODB()
        # ---- data plane: batched gradient apply (merge queue).  All
        # pushes pending at lock acquisition coalesce into ONE fused
        # device apply (ops/steps.make_*_apply_merge -- bit-identical to
        # the serial order); per-push semantics stay per item.
        merge = getattr(cfg, "push_merge", None)
        self._merge_max = max(1, int(merge if merge is not None
                                     else _gconf().get(PUSH_MERGE)))
        from collections import deque as _deque

        self._merge_q: "_deque[_PendingPush]" = _deque()
        self._apply_merge = None
        # drain-time scratch (single writer under _lock; device_put copies
        # host->device eagerly, so reusing the buffers across drains is
        # safe and keeps the lock hold free of O(m*d) allocations)
        self._merge_G: Optional[np.ndarray] = None
        self._merge_mask: Optional[np.ndarray] = None
        if self._merge_max > 1:
            self._merge_G = np.empty((self._merge_max, d), np.float32)
            self._merge_mask = np.empty(self._merge_max, np.float32)
            zG = jax.device_put(
                jnp.zeros((self._merge_max, d), jnp.float32), self.device
            )
            zm = jax.device_put(
                jnp.zeros(self._merge_max, jnp.float32), self.device
            )
            # donate_model: the fused drain writes w' into the dead
            # input's buffer -- zero steady-state allocation.  The drain
            # only routes a batch through this kernel when the outgoing
            # version is already HOST-published (its _ModelSnap exists),
            # so nothing can ever need the donated device buffer again;
            # otherwise it falls back to the serial per-item applies
            # (asserted bit-identical).  Warm dummies are donated too --
            # zw/zk2/zab2 are dead after this call by construction.
            if algo == "asaga":
                self._apply_merge = steps.make_saga_apply_merge(
                    cfg.gamma, cfg.batch_rate, n, cfg.num_workers,
                    donate_model=True,
                )
                zab2 = jax.device_put(jnp.zeros(d, jnp.float32), self.device)
                self._apply_merge(zw, zab2, zG, zm)
            else:
                self._apply_merge = steps.make_asgd_apply_merge(
                    cfg.gamma, cfg.batch_rate, n, cfg.num_workers,
                    donate_model=True,
                )
                zk2 = jax.device_put(jnp.float32(0.0), self.device)
                self._apply_merge(zw, zG, zm, zk2)
        self.merge_batches = 0    # fused drains that applied >= 1 push
        self.merge_merged = 0     # pushes applied through fused drains
        self.merge_batch_max = 0  # largest single fused batch
        self._clock = 0          # merged gradients (ASYNCcontext.CurrentTime)
        self._k = 0              # accepted updates
        self.accepted = 0
        self.dropped = 0
        self.push_bytes = 0      # wire payload bytes received via PUSH
        self.max_staleness = 0
        self._snapshots: List[Tuple[float, object]] = []
        self._t0: Optional[float] = None
        self._done = threading.Event()
        # calibration (SparkASGDThread.scala:174-183)
        self._cal_ms = 0.0
        self._cal_n = 0
        self.avg_delay_ms = 0.0
        self._pull_times: Dict[int, float] = {}
        # cohort wave gate (ASYNCbarrier + bucketRatio)
        self._wave_cv = threading.Condition()
        self._waiting: List[int] = []
        self._wave_id = 0

        # elastic membership (parallel/supervisor.py); None = the classic
        # fixed-membership PS (old callers see no behavior change)
        self.supervisor = supervisor
        # per-worker ledgers, tracked unconditionally: they feed wait_done's
        # progress diagnostic AND the acceptance coverage assert (every
        # shard's samples contributed), and they survive a PS restart
        self._last_contact: Dict[int, float] = {}
        self.pushes_by_wid: Dict[int, int] = {}
        self.accepted_by_wid: Dict[int, int] = {}
        # per-worker straggler stats (cluster observer input surface):
        # merge-time facts (staleness, push inter-arrival EWMA) land at
        # drain, latency facts (compute / push.rtt EWMAs) land when this
        # worker's piggybacked spans fold.  Own lock: span folds run on
        # connection handler threads, outside the model lock by design.
        self._wstats_lock = threading.Lock()
        self._wstats: Dict[int, Dict[str, float]] = {}
        self.membership_rejects = 0  # pushes from deposed shard servers
        # exactly-once-applied PUSH: a retried (sid, seq) re-sends the
        # cached ACK instead of merging the gradient twice (net/session.py).
        # Constructed BEFORE a restore so a checkpointed window lands here
        # -- that is what keeps retries exactly-once ACROSS a kill -9 +
        # restart, not just across a lost reply.
        from asyncframework_tpu.conf import NET_DEDUP_WINDOW, global_conf

        self._dedup = DedupWindow(window=global_conf().get(NET_DEDUP_WINDOW))

        # adaptive control plane (parallel/controller.py): the installed
        # CTRL payload (None = control off, byte-identical legacy wire
        # everywhere) + its parsed effective values.  Installed by the
        # local AsyncController (primary), by SETMAP (shard secondaries
        # and promoted standbys -- decisions SURVIVE promotion because
        # the group re-announces its stored ctrl), and served to workers
        # on WELCOME and on PULL replies whose ``cs`` stamp is stale.
        # _ctrl_lock guards the swap; the drain reads the parsed fields
        # via one attribute read each (GIL-atomic reference swaps).
        self._ctrl_lock = threading.Lock()
        self.ctrl: Optional[dict] = None
        self._ctrl_b = 0            # cohort override (0 = conf value)
        self._ctrl_merge = 0        # effective merge budget (0 = conf)
        self._ctrl_damp: Optional[Tuple[float, float, float]] = None
        self._ctrl_wdamp: Dict[int, float] = {}
        self.ctrl_stale_rejects = 0  # stale (ep, seq) installs refused
        self._apply_damped = None    # built on first damped install

        # distributed tracing (metrics/trace.py): server-side spans for
        # traced updates (the frame carried a ``tc`` header) plus spans
        # piggybacked on PUSH/BYE are folded into the process-global
        # aggregator and -- when a ListenerBus is given -- posted as
        # TraceSpan events (-> event log -> live UI -> history server), so
        # a worker's spans survive its death.
        self.bus = bus
        self._trace_agg = _trace.aggregator()
        self.trace_spans = 0  # spans folded (own + piggybacked)
        # folds happen on per-connection handler threads, outside _lock by
        # design (telemetry must not queue the apply path) -- the counter
        # needs its own lock like every other process counter.  Piggyback
        # folds dedup by span_id (bounded LRU) -- see _fold_wire_spans.
        self._trace_lock = threading.Lock()
        from collections import OrderedDict as _OD

        self._seen_span_ids: "_OD[str, None]" = _OD()

        self._elapsed_offset_ms = 0.0  # wall already spent before a resume
        # a STANDBY never boot-restores: its state arrives over the wire
        # (REPL_SYNC) at the epoch its primary streams, and a stale
        # checkpoint restore here would mint an epoch ABOVE the stream's
        # and wrongly fence it out.  The path is still kept: once
        # promoted, this server checkpoints its range there.
        if (checkpoint_path and os.path.exists(checkpoint_path)
                and not self._standby):
            self._restore(checkpoint_path)

        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_trigger = threading.Event()
        self._eval_results: Dict[int, np.ndarray] = {}
        self._eval_cv = threading.Condition()
        self._stop = threading.Event()

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ParameterServer":
        self._t0 = time.monotonic() - self._elapsed_offset_ms / 1e3
        with self._lock:
            if self.resumed_from_k is None:
                self._snapshots.append((0.0, np.array(self._w, np.float32)))
            if self._k >= self.cfg.num_iterations:
                self._done.set()  # checkpoint was already past the finish
                if self.supervisor is not None:
                    self.supervisor.freeze()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ps-accept", daemon=True
        )
        self._accept_thread.start()
        if self.checkpoint_path:
            # async checkpoint writer: the push handler only SIGNALS the
            # cadence; serialization happens under the lock on this thread
            # and the disk write happens off every worker's request path
            self._ckpt_thread = threading.Thread(
                target=self._ckpt_loop, name="ps-checkpoint", daemon=True
            )
            self._ckpt_thread.start()
        if self.supervisor is not None:
            self.supervisor.start()
        # continuous telemetry (metrics/timeseries.py): this PS's core
        # scalars become the ``ps.*`` time series every sampler tick --
        # the updates/s-floor SLO (rate(ps.accepted)) and the adaptive
        # controller's input surface.  Last registration wins, matching
        # "the live PS owns the dashboard"; stop() unhooks only itself.
        from asyncframework_tpu.metrics import timeseries as _ts

        self._ts_source = self._telemetry_source
        _ts.register_source("ps", self._ts_source)
        # per-worker stats on /api/status (``ps_workers`` section): the
        # cluster observer's straggler scoring reads it -- same
        # last-registration-wins + identity-gated-unregister discipline
        # as the ``ps`` series source
        from asyncframework_tpu.metrics import live as _live

        self._workers_section = self.worker_stats
        _live.register_status_section("ps_workers", self._workers_section)
        _ts.ensure_started()
        return self

    def worker_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-worker straggler inputs (JSON-able; the ``ps_workers``
        /api/status section): accepted/dropped counts, last observed
        staleness, push inter-arrival EWMA, and -- when this worker's
        spans fold here -- compute and push-RTT EWMAs."""
        with self._wstats_lock:
            return {str(w): dict(st) for w, st in self._wstats.items()}

    _EWMA_A = 0.3  # per-worker EWMA weight (a few pushes to converge)

    def _wstat_merge(self, wid: int, staleness: int,
                     accepted: bool) -> None:
        """Merge-time per-worker facts; called at drain (model lock
        held) -- a dict update, same cost class as accepted_by_wid."""
        now_ms = time.monotonic() * 1e3
        with self._wstats_lock:
            st = self._wstats.setdefault(int(wid), {})
            st["accepted"] = st.get("accepted", 0) + int(accepted)
            st["dropped"] = st.get("dropped", 0) + int(not accepted)
            st["staleness"] = int(staleness)
            last = st.get("last_seen_ms")
            if last is not None and now_ms > last:
                iv = now_ms - last
                prev = st.get("interval_ms")
                st["interval_ms"] = round(
                    iv if prev is None
                    else self._EWMA_A * iv + (1 - self._EWMA_A) * prev, 3)
            st["last_seen_ms"] = now_ms

    def _wstat_span(self, span: "_trace.Span") -> None:
        """Latency facts from a folded span (compute / push.rtt).

        Only updates entries :meth:`_wstat_merge` already created: spans
        fold at PUSH receive (handler threads), merges at drain -- a
        span-only entry would carry a one-sample EWMA with no
        ``accepted`` count, bypassing the observer's warm-up guard and
        flagging a booting worker on its very first sample."""
        if span.worker_id is None or span.dur_ms is None:
            return
        if span.stage == _trace.COMPUTE:
            key = "compute_ms"
        elif span.stage == _trace.PUSH_RTT:
            key = "rtt_ms"
        else:
            return
        with self._wstats_lock:
            st = self._wstats.get(int(span.worker_id))
            if st is None:
                return
            prev = st.get(key)
            st[key] = round(
                span.dur_ms if prev is None
                else self._EWMA_A * span.dur_ms
                + (1 - self._EWMA_A) * prev, 3)

    def _telemetry_source(self) -> Dict[str, float]:
        """Flat scalars the time-series sampler records as ``ps.<key>``
        (lock-free reads of ints: a tick may see a torn multi-field view,
        but each individual series stays monotone/correct)."""
        out = {
            "clock": self._clock,
            "k": self._k,
            "accepted": self.accepted,
            "dropped": self.dropped,
            "push_bytes": self.push_bytes,
            "max_staleness": self.max_staleness,
            # merge-queue backlog at this instant: the observer prices
            # it against the push rate (queue growing faster than the
            # drain = the apply plane is the bottleneck)
            "queue_depth": len(self._merge_q),
            "done": int(self._done.is_set()),
        }
        repl = self.repl
        if repl is not None:
            # the standby's replication lag in merge units -- the
            # ps.standby_lag series the default standby_lag SLO rule
            # watches (read staleness on the standby is priced by it)
            out["standby_lag"] = float(repl.lag_versions())
            out["standby_synced"] = 1.0 if repl.synced else 0.0
        if self._standby:
            out["standby"] = 1.0
        return out

    # ---------------------------------------------------------- checkpointing
    def _checkpoint_state(self) -> dict:
        """Snapshot everything a restarted PS needs, caller holds the lock.
        ``_pending_idx`` is deliberately NOT saved: in-flight pulls die with
        the process, and a post-restart push referencing one is dropped
        (stale by construction)."""
        meta = {
            "algo": self.algo,
            "clock": self._clock,
            "k": self._k,
            "accepted": self.accepted,
            "dropped": self.dropped,
            "push_bytes": self.push_bytes,
            "max_staleness": self.max_staleness,
            "cal_ms": self._cal_ms,
            "cal_n": self._cal_n,
            "avg_delay_ms": self.avg_delay_ms,
            "elapsed_ms": self._now_ms() if self._t0 is not None else 0.0,
            "snap_times": [t for (t, _w) in self._snapshots],
            # session dedup windows ride the checkpoint: a PUSH applied in
            # this life and retried against the NEXT life must be answered
            # from cache, not merged again.  Captured under the same lock
            # as the model, so window and weights can never disagree about
            # which pushes are "in".
            "dedup": self._dedup.state(),
            "pushes_by_wid": {
                str(w): c for w, c in self.pushes_by_wid.items()
            },
            "accepted_by_wid": {
                str(w): c for w, c in self.accepted_by_wid.items()
            },
            "membership_rejects": self.membership_rejects,
            # fencing: the epoch rides the checkpoint so a restart can
            # never come back BELOW a fence (the restore bumps past it),
            # and the reject count survives incarnations for the
            # acceptance assertions / metrics
            "epoch": self.epoch,
            "fenced_rejects": self.fenced_rejects,
        }
        # owned copies, never device-buffer views: a later donated drain
        # overwrites the model buffer in place
        arrays = {"w": np.array(self._w, np.float32)}
        if self._snapshots:
            arrays["snap_stack"] = np.stack(
                [np.asarray(w) for (_t, w) in self._snapshots]
            )
        if self.algo == "asaga":
            arrays["ab"] = np.array(self._ab, np.float32)
            with self._saga_lock:  # consistent table + RNG capture
                for wid, table in self._table.items():
                    arrays[f"table_{wid}"] = table.copy()
                meta["rng_states"] = {
                    str(wid): rng.bit_generator.state
                    for wid, rng in self._rngs.items()
                }
        return {"meta": meta, "arrays": arrays}

    def save_checkpoint(self) -> None:
        """Atomic on-disk PS checkpoint (Master.scala:41 recovery semantics
        applied to the run itself, per SURVEY section 7 stage 5: model +
        history table + RNG + clock).  Serialize under the lock, write
        outside it."""
        if not self.checkpoint_path:
            return
        with self._lock:
            state = self._checkpoint_state()
        buf = io.BytesIO()
        np.savez(buf, __meta__=json.dumps(state["meta"]), **state["arrays"])
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
        # fsync file + rename + fsync directory: the save survives host
        # power loss, not just process death (checkpoint.durable_replace)
        from asyncframework_tpu.checkpoint import durable_replace

        durable_replace(tmp, self.checkpoint_path)

    def _ckpt_loop(self) -> None:
        while not self._stop.is_set():
            if not self._ckpt_trigger.wait(timeout=0.2):
                continue
            self._ckpt_trigger.clear()
            try:
                self.save_checkpoint()
            except Exception:  # noqa: BLE001 - the writer must outlive
                # any one failed save (disk hiccup, transient device
                # fault): a dead checkpoint thread would silently void
                # the restart guarantees for the rest of the run
                pass

    def _restore(self, path: str) -> None:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            if meta["algo"] != self.algo:
                raise ValueError(
                    f"checkpoint algo {meta['algo']!r} != PS algo "
                    f"{self.algo!r}"
                )
            self._install_state(z, meta)
            if self.epoch > 0:
                # every incarnation is a NEW epoch: a restart from this
                # checkpoint must dominate anything the previous life
                # stamped or accepted (a controller-passed epoch that
                # already counts more fences wins via max)
                self.epoch = max(self.epoch,
                                 int(meta.get("epoch", 0)) + 1)
            self.fenced_rejects = int(meta.get("fenced_rejects", 0))
        self.resumed_from_k = self._k
        supervisor_mod.bump_total("ps_resumes")

    def _install_state(self, z, meta: dict) -> None:
        """Install a checkpoint image's model + bookkeeping (shared by
        the boot-time restore and the standby's REPL_SYNC applier).
        Deliberately does NOT touch the fencing epoch or the fenced-
        reject counter: incarnation identity belongs to the caller --
        a restore bumps past the persisted epoch, a standby sync keeps
        the epoch its stream runs at."""
        import jax

        # generation bump FIRST: a lock-free reader mid-build (a live
        # standby keeps serving SUBSCRIBE through a re-sync) must fail
        # its publish guard, or it would cache the PRE-install snapshot
        # after the install and serve it until the next accepted apply
        # happened to bump the generation.  The _snap clear comes LAST,
        # after every other field, so a reader that re-reads the basis
        # builds the NEW state.  (The guard's compare-then-store is not
        # atomic -- the residual preemption window is the same one the
        # drain path has always had, and the next invalidation clears
        # it.)
        self._model_gen += 1
        self._w = jax.device_put(z["w"], self.device)
        self._w_versions.clear()
        with self._born_lock:
            self._ver_born.clear()  # prior-life ages are meaningless
        self._snap_basis = (int(meta["clock"]), self._w,
                            self._model_gen)
        self._clock = int(meta["clock"])
        self._k = int(meta["k"])
        # the DEVICE step counter must follow k: the ASGD step-size
        # schedule reads it (gamma/sqrt(k/P+1)), so leaving it at this
        # life's old value would replay the installed state's future
        # updates at the wrong step sizes -- a silent divergence between
        # a mirror and its primary (and, before this, between a
        # restarted shard and the run it resumed)
        import jax.numpy as jnp

        self._k_dev = jax.device_put(jnp.float32(self._k), self.device)
        self.accepted = int(meta["accepted"])
        self.dropped = int(meta["dropped"])
        self.push_bytes = int(meta["push_bytes"])
        self.max_staleness = int(meta["max_staleness"])
        self._cal_ms = float(meta["cal_ms"])
        self._cal_n = int(meta["cal_n"])
        self.avg_delay_ms = float(meta["avg_delay_ms"])
        self._elapsed_offset_ms = float(meta["elapsed_ms"])
        if "snap_stack" in z:
            stack = z["snap_stack"]
            self._snapshots = [
                (t, stack[i].copy())
                for i, t in enumerate(meta["snap_times"])
            ]
        else:
            self._snapshots = []
        if self.algo == "asaga":
            self._ab = jax.device_put(z["ab"], self.device)
            self._table = {
                int(k.split("_", 1)[1]): z[k].copy()
                for k in z.files if k.startswith("table_")
            }
            for wid_s, state in meta.get("rng_states", {}).items():
                rng = np.random.default_rng()
                rng.bit_generator.state = state
                self._rngs[int(wid_s)] = rng
        self._dedup.load_state(meta.get("dedup"))
        self.pushes_by_wid = {
            int(w): int(c)
            for w, c in meta.get("pushes_by_wid", {}).items()
        }
        self.accepted_by_wid = {
            int(w): int(c)
            for w, c in meta.get("accepted_by_wid", {}).items()
        }
        self.membership_rejects = int(meta.get("membership_rejects", 0))

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"ps-conn-{conn.fileno()}", daemon=True
            )
            t.start()
            # reap on append: a long-running elastic PS accepts a fresh
            # connection per worker reconnect/retry -- without pruning,
            # finished handler threads accumulate for the life of the
            # process (one Thread object + name per connection ever made)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1e3

    # -------------------------------------------------------------- tracing
    def _bus_time_ms(self) -> float:
        return self._now_ms() if self._t0 is not None else 0.0

    def _fold_span(self, span: "_trace.Span") -> None:
        """One span into the aggregator + (when attached) the event bus."""
        with self._trace_lock:
            self.trace_spans += 1
        self._trace_agg.add(span)
        self._wstat_span(span)
        if self.bus is not None:
            self.bus.post(_trace.span_event(span, self._bus_time_ms()))

    def _fold_wire_spans(self, wire_spans) -> None:
        """Spans piggybacked on a worker's PUSH/BYE header.

        Deduped by span_id: the (sid, seq) window covers same-stamp
        retries, but a push that was DELIVERED and then spent its whole
        retry budget re-queues its piggyback onto the next push under a
        fresh stamp -- without this, exactly the fault windows tracing
        exists to explain would double-count their spans."""
        if not wire_spans:
            return
        for d in wire_spans:
            try:
                span = _trace.Span.from_wire(d)
                with self._trace_lock:
                    if span.span_id in self._seen_span_ids:
                        continue
                    self._seen_span_ids[span.span_id] = None
                    while len(self._seen_span_ids) > 8192:
                        self._seen_span_ids.popitem(last=False)
                self._fold_span(span)
            except Exception:  # noqa: BLE001 - junk from the wire must not
                pass           # kill the connection handler

    # ------------------------------------------------------------- protocol
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                header, payload = _recv_msg(conn)
                op = header["op"]
                # PULL_SAGA/PUSH_SAGA are the same handlers under their own
                # verbs so fault schedules (net/faults.py) can target the
                # ASAGA stream without also counting ASGD ops
                if op in ("PULL", "PULL_SAGA"):
                    if self._standby:
                        # a standby is a READ replica: SUBSCRIBE serves
                        # from its mirrored snapshot, but the training
                        # plane (wave gate, membership, merges) belongs
                        # to the range primary alone -- it is not in
                        # the shard map, and a client that lands here
                        # anyway must re-resolve, not train against a
                        # mirror
                        _send_msg(conn, {"op": "ERR", "msg": "standby"})
                        continue
                    if self._fence_reject(conn, header):
                        continue
                    self._handle_pull(conn, header)
                elif op == "SUBSCRIBE":
                    # serving-tier snapshot subscription: a read-only,
                    # wave-gate-free pull that keeps answering after DONE
                    # (standbys serve it too -- the read-replica face of
                    # hot-standby replication, staleness priced by lag)
                    if self._fence_reject(conn, header):
                        continue
                    self._handle_subscribe(conn, header)
                elif op in ("PUSH", "PUSH_SAGA"):
                    if self._standby:
                        _send_msg(conn, {"op": "ERR", "msg": "standby"})
                        continue
                    cached = self._dedup.check(header)
                    if cached is not None:
                        # duplicate of an already-applied push (the ACK was
                        # lost on the wire): re-send it, merge nothing.
                        # Dedup wins over the fence check: an op this
                        # incarnation ALREADY applied must re-answer its
                        # cached verdict, not invent a new one.
                        _send_msg(conn, cached[0])
                    elif not self._fence_reject(conn, header, record=True):
                        self._handle_push(conn, header, payload)
                elif op == "HELLO":
                    # a worker process introducing itself (elastic plane):
                    # proc token + logical worker ids + pid/host (+ the
                    # pid's /proc start time, pid-reuse protection)
                    if self.supervisor is not None:
                        self.supervisor.register(
                            str(header.get("proc")),
                            [int(w) for w in header.get("wids", [])],
                            pid=header.get("pid"),
                            host=header.get("host"),
                            pid_start=header.get("pstart"),
                            mport=header.get("mport"),
                        )
                    welcome = {"op": "WELCOME",
                               "elastic": self.supervisor is not None}
                    if self.shard_map:
                        # the shard-map handshake: workers/replicas resolve
                        # the group here and fan every PULL/PUSH out per
                        # range (shardgroup.ShardedPSClient).  Key absent
                        # on an unsharded PS -- byte-identical legacy wire.
                        welcome["shards"] = self.shard_map
                        if self.shard_epochs:
                            welcome["epochs"] = self.shard_epochs
                    if self.epoch:
                        welcome["epoch"] = self.epoch
                    if self.ctrl is not None:
                        # adaptive control plane: a joining worker gets
                        # the current CTRL payload next to the map and
                        # epoch vector (absent with control off --
                        # byte-identical legacy wire)
                        welcome["ctrl"] = self.ctrl
                    _send_msg(conn, welcome)
                elif op == "SHARDMAP":
                    # shard-map query (group members, liveness probes,
                    # serving replicas): the classic single PS answers an
                    # empty list -- "no group here"
                    reply = {"op": "SHARDMAP",
                             "shards": self.shard_map or []}
                    if self.epoch:
                        reply["epoch"] = self.epoch
                        reply["fenced_rejects"] = self.fenced_rejects
                    if self.shard_epochs:
                        reply["epochs"] = self.shard_epochs
                    if self.standby_map:
                        # discovery surface for the read path: serving
                        # replicas / relaycast roots may subscribe to a
                        # range's standby instead of its primary
                        reply["standbys"] = self.standby_map
                    if self._standby:
                        reply["standby"] = True
                    if self.ctrl is not None:
                        reply["ctrl"] = self.ctrl
                    _send_msg(conn, reply)
                elif op == "SETMAP":
                    # group controller installing the assembled map on a
                    # freshly-spawned shard child (it cannot know its
                    # peers' ephemeral ports before they announce)
                    wire = header.get("shards") or None
                    self.shard_map = ([list(e) for e in wire]
                                      if wire else None)
                    if "index" in header:
                        self.shard_index = int(header["index"])
                    if header.get("epochs"):
                        # the controller's epoch vector (post-fence
                        # re-installs ride this too, so WELCOME hands new
                        # workers current epochs, not boot-time ones)
                        self.shard_epochs = [int(e)
                                             for e in header["epochs"]]
                    if "standbys" in header:
                        # the controller's standby endpoints: a primary
                        # whose own entry is set (re)targets its
                        # replication stream here -- promotion re-homes
                        # a NEW standby behind the promoted primary via
                        # the same install
                        self.set_standby_map(header.get("standbys"))
                    if "ctrl" in header:
                        # adaptive-control decisions ride SETMAP next to
                        # the map/epochs/standbys: shard secondaries
                        # damp/serve under the SAME decision the primary
                        # applies, and a promoted standby re-learns the
                        # current CTRL from the group's re-announce
                        # (monotone install; a deposed controller's
                        # stale stamp is refused)
                        self.set_control(header.get("ctrl"))
                    _send_msg(conn, {"op": "ACK"})
                elif op in ("REPL_APPEND", "REPL_SYNC"):
                    # primary->standby replication stream (parallel/
                    # replication.py).  Only a standby applies it, and
                    # the fence admission below is THE promotion-safety
                    # gate: a deposed primary's post-promotion appends
                    # carry its stale epoch and bounce REJECT_FENCED --
                    # including against the PROMOTED (ex-standby)
                    # server itself, whose minted epoch now dominates,
                    # which is how the zombie learns it was deposed.
                    if self._standby:
                        ep = header.get("ep")
                        if ep is not None and int(ep) > self.epoch:
                            # adopt-forward: the stream's source is
                            # authoritative for its standby (a primary
                            # relaunched from checkpoint streams at its
                            # bumped epoch); a STALE stamp still fails
                            # the admission below
                            self.epoch = int(ep)
                    if self._fence_reject(conn, header):
                        continue
                    if not self._standby:
                        _send_msg(conn, {"op": "ERR",
                                         "msg": "not a standby"})
                        continue
                    if op == "REPL_SYNC":
                        self._handle_repl_sync(conn, payload)
                    else:
                        self._handle_repl_append(conn, header, payload)
                elif op == "PROMOTE":
                    # controller order: this standby becomes its range's
                    # primary under the minted epoch (idempotent by
                    # monotone epoch compare)
                    self._handle_promote(conn, header)
                elif op == "FINISH":
                    # group-wide DONE broadcast: a secondary shard serves
                    # its range with an unbounded iteration budget and
                    # learns run completion only from the primary's DONE,
                    # fanned out here (worker BYE and the group controller
                    # both send it; idempotent by construction)
                    self._done.set()
                    if self.supervisor is not None:
                        self.supervisor.freeze()
                    with self._wave_cv:
                        self._wave_cv.notify_all()
                    _send_msg(conn, {"op": "ACK"})
                elif op == "SNAPSHOTS":
                    # only meaningful once the run is done; the stack is
                    # consistent either way (lock-copied)
                    times, W = self.snapshot_stack()
                    _send_msg(
                        conn,
                        {"op": "SNAPSHOTS", "times": times,
                         "shape": list(W.shape)},
                        np.ascontiguousarray(W, np.float32).tobytes(),
                    )
                elif op == "EVAL_RESULT":
                    arr = np.frombuffer(payload, np.float64).copy()
                    with self._eval_cv:
                        self._eval_results[int(header["wid"])] = arr
                        self._eval_cv.notify_all()
                    _send_msg(conn, {"op": "ACK"})
                elif op == "BYE":
                    # a departing worker's last completed spans (push.rtt
                    # of its final traced update has no later PUSH to ride)
                    # and its final pipeline-counter / convergence deltas
                    self._fold_wire_spans(header.get("spans"))
                    _pl_fold(header.get("pl"))
                    _cv_fold(header.get("cv"), clock=self._clock,
                             wall_ms=self._bus_time_ms())
                    _send_msg(conn, {"op": "ACK"})
                    return
                elif op == "SHM_OPEN":
                    # transport upgrade (net/shmring.py): attach to the
                    # colocated client's ring segments and keep serving
                    # the SAME framed protocol over them.  Everything
                    # above the transport -- dedup, fencing, CRC fields
                    # -- runs unchanged; only the byte path underneath
                    # _recv_msg/_send_msg moves.  A refused attach
                    # answered ERR and this TCP conversation continues.
                    upgraded = _shmring.serve_attach(conn, header)
                    if upgraded is not None:
                        conn = upgraded
                else:
                    _send_msg(conn, {"op": "ERR", "msg": f"bad op {op}"})
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _fence_reject(self, conn: socket.socket, header: dict,
                      record: bool = False) -> bool:
        """Epoch-fencing admission (async.fence.enabled): True when the
        op was answered REJECT_FENCED and must not be served.

        Rules (``ep`` = the op's stamped epoch, ``self.epoch`` = this
        incarnation's minted one):

        - fencing off (``self.epoch == 0``) or unstamped op (legacy
          client): serve -- the wire stays byte-identical and old
          clients keep their old semantics;
        - ``ep < self.epoch``: the CLIENT is deposed (it pulled its view
          from a fenced incarnation) -- reject, tell it the current
          epoch so it re-resolves and continues;
        - ``ep > self.epoch``: a successor exists, so THIS server is the
          zombie -- remember the foreign epoch and reject; from here on
          every stamped op is refused (a zombie must neither mutate nor
          serve its old range, even to same-epoch stragglers);
        - ``ep == self.epoch`` and not deposed: serve.

        The reply carries the highest epoch this server knows, so a
        fenced client self-heals: it adopts the epoch and its next op
        (stamped fresh) is admitted by the current owner.  Fenced PUSH
        verdicts are recorded in the dedup window (``record=True``) so a
        retry of the same stamp re-answers the fence instead of racing a
        fresh admission."""
        if not self.epoch:
            return False
        ep = header.get("ep")
        if ep is None:
            return False
        ep = int(ep)
        if ep > self.epoch:
            # lock-free int write: monotone max under the GIL; a racing
            # reader sees either value, both of which fence correctly
            if ep > self._fenced_above:
                self._fenced_above = ep
        elif ep == self.epoch and self._fenced_above <= self.epoch:
            return False
        rej = {"op": "REJECT_FENCED",
               "epoch": max(self.epoch, self._fenced_above)}
        with self._stats_lock:
            self.fenced_rejects += 1
        supervisor_mod.bump_total("fenced_rejects")
        if record:
            # PUSH: fold the piggybacked telemetry BEFORE rejecting --
            # the 'fold before any drop path' invariant (_handle_push).
            # Spans/counters/convergence samples around a failover are
            # exactly the telemetry the fence window must not eat, and
            # dedup-replayed fenced stamps never reach here (the cached
            # verdict answers them), so nothing double-folds.
            self._fold_wire_spans(header.get("spans"))
            _pl_fold(header.get("pl"))
            _cv_fold(header.get("cv"), clock=self._clock,
                     wall_ms=self._bus_time_ms())
            self._dedup.record(header, rej)
        _send_msg(conn, rej)
        return True

    def note_fenced_above(self, ep: int) -> None:
        """Fold a foreign successor epoch observed OUT of band (the
        replication stream's REJECT_FENCED reply): from here on every
        stamped op is refused, exactly as if a client had proven the
        successor -- which drives workers to re-resolve onto it."""
        ep = int(ep)
        if ep > self._fenced_above:
            self._fenced_above = ep

    # ------------------------------------------------- adaptive control
    def set_control(self, wire: Optional[dict]) -> bool:
        """Install a CTRL payload (parallel/controller.py decisions).

        Monotone by (epoch, seq) -- fence-stamped: a deposed
        controller's decision (stamped with a pre-promotion epoch below
        an already-installed one) is refused and counted, exactly like
        a zombie's write.  ``None`` clears control entirely (back to
        the byte-identical legacy path).  Returns True when installed.
        """
        from asyncframework_tpu.parallel.controller import ctrl_seq

        if wire is not None and self.algo == "asgd":
            damp = wire.get("damp")
            if damp and float(damp[0]) > 0:
                # build + warm the damped serial kernel BEFORE the law
                # is published: a single-item drain between install and
                # compile would otherwise fall through to the undamped
                # kernel while a contended (fused) drain damps -- the
                # applied step must never depend on queue contention
                self._ensure_apply_damped()
        with self._ctrl_lock:
            if wire is None:
                self.ctrl = None
                self._ctrl_b = 0
                self._ctrl_merge = 0
                self._ctrl_damp = None
                self._ctrl_wdamp = {}
                return True
            new, cur = ctrl_seq(wire), ctrl_seq(self.ctrl)
            if new == cur:
                # idempotent re-delivery (the group re-announces its
                # stored ctrl on every SETMAP sweep): not a fence event
                return False
            if new < cur:
                self.ctrl_stale_rejects += 1
                return False
            self.ctrl = dict(wire)
            self._ctrl_b = max(0, int(wire.get("b", 0) or 0))
            self._ctrl_merge = max(0, int(wire.get("merge", 0) or 0))
            damp = wire.get("damp")
            if damp and self.algo == "asgd":
                # [coeff, floor, free]: the bounded 1/(1+tau)-family
                # law the drain applies per accepted push.  ASAGA is
                # excluded by design: damping the gradient term alone
                # would break its alpha_bar == mean(table) invariant
                # (same exactness stance as the codec exclusion).
                c, fl, fr = (float(damp[0]), float(damp[1]),
                             float(damp[2]))
                self._ctrl_damp = (c, fl, fr) if c > 0 else None
            else:
                self._ctrl_damp = None
            wd = wire.get("wdamp") or {}
            try:
                self._ctrl_wdamp = {int(w): float(f)
                                    for w, f in wd.items()}
            except (TypeError, ValueError):
                self._ctrl_wdamp = {}
        return True

    def _ensure_apply_damped(self) -> None:
        """Build + warm the damped serial apply kernel once (ASGD only;
        called OFF the model lock -- from set_control before the law
        publishes, and from the replication receive path before a
        damped append takes the lock).  A benign double-build under a
        race compiles the identical function twice."""
        if self._apply_damped is not None or self.algo != "asgd":
            return
        from asyncframework_tpu.ops import steps as _steps
        import jax as _jax
        import jax.numpy as _jnp

        apply_damped = _steps.make_asgd_apply_damped(
            self.cfg.gamma, self.cfg.batch_rate, self.n,
            self.cfg.num_workers)
        zw = _jax.device_put(_jnp.zeros(self.d, _jnp.float32),
                             self.device)
        zg = _jax.device_put(_jnp.zeros(self.d, _jnp.float32),
                             self.device)
        zk = _jax.device_put(_jnp.float32(0.0), self.device)
        apply_damped(zw, zg, zk, np.float32(1.0))
        self._apply_damped = apply_damped

    def _item_damp(self, wid: int, staleness: int) -> float:
        """The per-item step-DAMP factor under the installed CTRL law:
        1/(1 + c*(tau - free)) past the free slack, floored, times the
        per-worker extra factor for observer-flagged stragglers.  1.0
        (exact) whenever control is off or the push is fresh enough."""
        law = self._ctrl_damp
        if law is None:
            return 1.0
        c, floor_, free = law
        damp = 1.0
        over = float(staleness) - free
        if over > 0.0:
            damp = max(floor_, 1.0 / (1.0 + c * over))
        wd = self._ctrl_wdamp.get(wid)
        if wd is not None:
            damp = max(floor_, damp * wd)
        # an ACCEPTED item's damp must stay strictly positive: the merge
        # kernel's keep bit is ``mask > 0``, and a zero factor (possible
        # only with a hand-crafted CTRL floor of 0) would silently turn
        # an accepted push into a dropped one
        return float(max(damp, 1e-6))

    def control_signals(self) -> Dict[str, float]:
        """PS-local scalars the adaptive controller reads each tick
        (lock-free int reads, same stance as ``_telemetry_source``)."""
        return {
            "clock": float(self._clock),
            "accepted": float(self.accepted),
            "dropped": float(self.dropped),
            "queue_depth": float(len(self._merge_q)),
            "max_staleness": float(self.max_staleness),
            "avg_delay_ms": float(self.avg_delay_ms),
            "done": float(self._done.is_set()),
        }

    # ----------------------------------------------- hot-standby replication
    def attach_standby(self, host: str, port: int) -> None:
        """(Re)point this PRIMARY's replication stream at its warm
        standby (parallel/replication.py).  Idempotent per endpoint.
        ASGD-only, like the sharded plane it serves: ASAGA's per-sample
        history table is not streamed."""
        if self.algo != "asgd":
            raise ValueError("standby replication is ASGD-only")
        if self._standby:
            raise ValueError("a standby does not stream to a standby")
        from asyncframework_tpu.parallel.replication import (
            ReplicationStream,
        )

        cur = self.repl
        if (cur is not None and not cur.fenced
                and (cur.host, cur.port) == (host, int(port))):
            return
        if cur is not None:
            cur.stop()
        self.repl = ReplicationStream(self, host, int(port))

    def set_standby_map(self, wire) -> None:
        """Install the group's standby endpoints (``[host, port]`` |
        None per range, SETMAP/launcher-supplied) and reconcile this
        server's own stream: a primary whose entry is set streams to
        it; an entry gone stops the stream."""
        self.standby_map = ([list(e) if e else None for e in wire]
                            if wire else None)
        if self._standby:
            return
        mine = None
        if (self.standby_map
                and self.shard_index < len(self.standby_map)):
            mine = self.standby_map[self.shard_index]
        if mine:
            self.attach_standby(str(mine[0]), int(mine[1]))
        elif self.repl is not None:
            self.repl.stop()
            self.repl = None

    def _handle_repl_sync(self, conn: socket.socket,
                          payload: bytes) -> None:
        """Standby side of REPL_SYNC: install the primary's checkpoint
        image as this mirror's state.  Idempotent -- re-installing the
        same image converges to the same state; a newer image simply
        supersedes.  The epoch is NOT taken from the image: the stream's
        ``ep`` stamp (adopt-forward in the dispatch) is the incarnation
        authority."""
        from asyncframework_tpu.parallel import replication as _repl

        try:
            with np.load(io.BytesIO(payload), allow_pickle=False) as z:
                meta = json.loads(str(z["__meta__"]))
                if meta["algo"] != self.algo:
                    raise ValueError(
                        f"sync algo {meta['algo']!r} != {self.algo!r}")
                with self._lock:
                    self._install_state(z, meta)
                    clock = self._clock
        except (ValueError, KeyError, OSError) as e:
            _send_msg(conn, {"op": "ERR", "msg": f"bad sync: {e}"})
            return
        if self._t0 is not None:
            # align this process's run clock with the primary's elapsed
            # wall, so mirrored version births / snapshot times price
            # freshness on the primary's timeline, not this process's
            self._t0 = time.monotonic() - self._elapsed_offset_ms / 1e3
        _repl.bump("sync_installs")
        _send_msg(conn, {"op": "ACK", "clock": clock})

    def _handle_repl_append(self, conn: socket.socket, header: dict,
                            payload: bytes) -> None:
        """Standby side of REPL_APPEND: apply one replicated merge batch
        exactly as the primary judged it -- same accept verdicts through
        the same jitted kernel in the same order, same ``(sid, seq)``
        dedup records (so a promoted standby re-answers replayed worker
        pushes from the REPLICATED window, never by re-applying), same
        snapshot cadence (the promoted trajectory continues seamlessly).

        Idempotence is the clock compare: a batch entirely at-or-below
        the applied clock is a duplicate delivery and re-ACKs; a batch
        starting exactly AT the clock applies; anything else is a gap --
        refused with ``resync`` so the stream re-bootstraps.  Never
        applied twice, never applied out of order."""
        import jax

        from asyncframework_tpu.parallel import replication as _repl

        if self.algo != "asgd":
            _send_msg(conn, {"op": "ERR", "msg": "replication is "
                                                 "ASGD-only"})
            return
        items = header.get("items") or []
        pre = int(header.get("pre", -1))
        cal = header.get("cal")
        if any(len(it) > 7 and float(it[7]) != 1.0 for it in items):
            # delay-adaptive damped items in this batch: compile the
            # damped kernel BEFORE taking the model lock (one-time)
            self._ensure_apply_damped()
        with self._lock:
            if pre + len(items) <= self._clock:
                reply = {"op": "ACK", "clock": self._clock, "dup": True}
            elif pre != self._clock:
                _repl.bump("resyncs_requested")
                reply = {"op": "ERR", "resync": True,
                         "clock": self._clock}
            else:
                off = 0
                for it in items:
                    wid, ts = int(it[0]), int(it[1])
                    acc = bool(it[2])
                    sid, seq, ack = it[3], it[4], it[5]
                    st = int(it[6])
                    # per-item step-DAMP (absent on a pre-damping
                    # primary's stream: 1.0 = the exact legacy apply)
                    damp = float(it[7]) if len(it) > 7 else 1.0
                    if sid is not None:
                        self._dedup.record({"sid": sid, "seq": seq},
                                           dict(ack))
                    self.pushes_by_wid[wid] = (
                        self.pushes_by_wid.get(wid, 0) + 1)
                    if st > self.max_staleness:
                        self.max_staleness = st
                    if acc:
                        g = np.frombuffer(
                            payload[off:off + 4 * self.d], np.float32)
                        off += 4 * self.d
                        # same unpublish-before-tick discipline as the
                        # drain: lock-free SUBSCRIBE readers must never
                        # pair a new clock with old bytes
                        self._model_gen += 1
                        self._snap = None
                        g_dev = jax.device_put(g, self.device)
                        if damp != 1.0 and self._apply_damped is not None:
                            # the primary damped this push: the mirror
                            # applies the IDENTICAL expression (serial
                            # damped kernel == damped merge body, bit
                            # for bit) so its state stays the primary's
                            self._w, self._k_dev = self._apply_damped(
                                self._w, g_dev, self._k_dev,
                                np.float32(damp))
                        else:
                            self._w, self._k_dev = self._apply(
                                self._w, g_dev, self._k_dev)
                        self._k += 1
                        self.accepted += 1
                        self.accepted_by_wid[wid] = (
                            self.accepted_by_wid.get(wid, 0) + 1)
                        if self._k % self.cfg.printer_freq == 0:
                            # the primary's snapshot cadence, mirrored:
                            # an owned host copy, never a buffer view
                            self._snapshots.append((
                                self._now_ms()
                                if self._t0 is not None else 0.0,
                                np.array(self._w, np.float32),
                            ))
                        if self._k >= self.cfg.num_iterations:
                            self._done.set()
                    else:
                        self.dropped += 1
                    self._clock += 1
                if cal:
                    self._cal_ms = float(cal[0])
                    self._cal_n = int(cal[1])
                    self.avg_delay_ms = float(cal[2])
                self._snap_basis = (self._clock, self._w,
                                    self._model_gen)
                if self._t0 is not None:
                    with self._born_lock:
                        self._ver_born[self._clock] = self._now_ms()
                        while len(self._ver_born) > 1024:
                            self._ver_born.popitem(last=False)
                _repl.bump("appends_applied")
                _repl.bump("append_items", len(items))
                reply = {"op": "ACK", "clock": self._clock}
        # deliberately NO checkpoint trigger here: durability is the
        # PRIMARY's job (a dead mirror is respawned and re-synced,
        # nothing to restore), and a mirror writing the shard's durable
        # files would race the acting primary's checkpoint thread on a
        # shared path.  Once PROMOTED, this server checkpoints through
        # the normal push path.
        _send_msg(conn, reply)

    def _handle_promote(self, conn: socket.socket,
                        header: dict) -> None:
        """PROMOTE: this standby becomes its range's primary at the
        controller-minted epoch.  Idempotent by monotone compare; the
        deposed primary needs no teardown order -- its next stream
        append (or any worker op, once note_fenced_above folds the
        bounce back) is REJECT_FENCED by the epoch installed here."""
        from asyncframework_tpu.parallel import replication as _repl

        ep = int(header.get("epoch", 0) or 0)
        with self._lock:
            if self._standby and ep <= self.epoch:
                # a STALE order against a fresh mirror (a late operator
                # retry, a re-delivered PROMOTE after this standby was
                # respawned): flipping would orphan it from its
                # primary's stream -- refuse, loudly.  An already-
                # promoted server re-ACKs below (idempotent).
                stale_ep, cur_ep = ep, self.epoch
                was_standby = None
            else:
                if ep > self.epoch:
                    self.epoch = ep
                was_standby = self._standby
                self._standby = False
                # an already-promoted server re-ACKs a DUPLICATE order
                # (ep == epoch: same map, install idempotent by value)
                # but must NOT install a STALE one (ep < epoch: a late
                # re-delivery from before a LATER failover would regress
                # the map/epoch vector this server hands out)
                stale_order = ep < self.epoch
            clock, k = self._clock, self._k
        if was_standby is None:
            _send_msg(conn, {"op": "ERR",
                             "msg": f"stale promote: epoch {stale_ep} "
                                    f"<= standby epoch {cur_ep}"})
            return
        if not stale_order:
            wire = header.get("shards") or None
            if wire:
                self.shard_map = [list(e) for e in wire]
            if "index" in header:
                self.shard_index = int(header["index"])
            if header.get("epochs"):
                self.shard_epochs = [int(e) for e in header["epochs"]]
            if "standbys" in header:
                # the fresh standby spawned behind THIS promoted primary
                self.set_standby_map(header.get("standbys"))
        if was_standby:
            self.promoted = True
            _repl.bump("promotions")
        _send_msg(conn, {"op": "ACK", "clock": clock, "k": k,
                         "epoch": self.epoch})

    def _release_wave_locked(self) -> None:
        """Fire the partial barrier: everyone currently waiting rides this
        wave.  Caller holds ``_wave_cv``."""
        self._wave_id += 1
        self._waiting.clear()
        self._wave_cv.notify_all()

    def _cohort_threshold(self) -> int:
        """Partial-barrier ``b``, clamped to live membership: when the
        supervisor knows only L workers are alive, a wave of min(b, L)
        keeps flowing immediately instead of leaning on the starvation
        fallback every round (ASAP's membership-as-staleness stance).

        The adaptive controller's cohort override (CTRL ``b``) takes
        precedence over the configured ``bucket_threshold`` -- its
        decision already respects the declared tunable bounds, and a
        re-clamped wave is how one DELAYed worker stops gating every
        round -- but live membership still caps it."""
        b_ctrl = self._ctrl_b
        threshold = (b_ctrl if b_ctrl > 0
                     else max(self.cfg.bucket_threshold, 1))
        threshold = max(threshold, 1)
        if self.supervisor is not None:
            threshold = max(1, min(threshold,
                                   self.supervisor.live_worker_count()))
        return threshold

    def _model_snap(self) -> _ModelSnap:
        """The published snapshot of the current model version, built on
        demand.  The fast path is one attribute read -- no locks at all.
        A rebuild (first pull after an accepted push) reads the
        atomically-published build basis and does the O(d) readback +
        serialize + CRC without touching the model lock either;
        ``_snap_build_lock`` makes a cohort trigger one build, not P."""
        snap = self._snap
        if snap is not None:
            return snap
        with self._snap_build_lock:
            snap = self._snap
            if snap is not None:
                return snap
            # the basis reference is written atomically by the drain (a
            # tuple swap under the model lock); reading it here needs NO
            # lock at all -- the build's only waits are the device
            # readback and peer builders on _snap_build_lock
            basis = self._snap_basis
            ts, w_dev, gen = basis
            # device readback without any lock.  The fused drain DONATES
            # the model buffer (in-place apply), so two disciplines:
            # (1) w_host must be an owned COPY, never a view of device
            # memory (np.asarray of a CPU jax array aliases the buffer);
            # (2) a donated drain can invalidate the basis buffer between
            # our tuple read and the readback -- it redirects the basis
            # (to the outgoing version's host copy) BEFORE the donating
            # dispatch, so one re-read always lands on valid memory.
            try:
                w_host = np.array(w_dev, np.float32)
            except Exception:
                basis = self._snap_basis
                ts, w_dev, gen = basis
                w_host = np.array(w_dev, np.float32)
            wire = w_host.tobytes()
            snap = _ModelSnap(int(ts), w_host, wire, wiredelta.crc(wire),
                              int(gen))
            # publish only while the model GENERATION is unchanged: a
            # drain may be mid-apply right now (it bumped _model_gen in
            # its accept branch, but writes the new basis only at drain
            # end), and publishing a stale snap then would let the
            # send-time re-stamp below pair the new clock with old
            # bytes.  Serving the unpublished snap is still correct --
            # it is stamped with ITS ts and staleness is priced.
            if self._model_gen == gen:
                self._snap = snap
            return snap

    def _negotiated_model(self, have) -> Tuple[int, int, dict, bytes]:
        """The LOCK-FREE model-serving core shared by PULL and SUBSCRIBE:
        everything here reads the published :class:`_ModelSnap` (atomic
        reference) -- the model lock is never taken (net/lockwatch.py
        asserts it in debug runs), so serving never queues behind a merge
        drain and a drain never stalls behind a slow reader's socket.

        Returns ``(ts, clock, model_hdr, model_part)``: the send-time
        version stamp, the raw clock read, the negotiated reply header
        fields (empty for a legacy no-``have`` reply, byte-identical to
        the pre-delta wire), and the model payload bytes.  Encoding
        happens OUTSIDE any lock (the O(d) xor must not queue the apply
        path); the version caches pin every array/bytes object needed."""
        if have is not None:
            self._delta_clients_seen = True  # one-way flag, GIL-atomic
        snap = self._model_snap()
        ts, w_host, w_wire, w_crc = snap.ts, snap.w_host, snap.wire, snap.crc
        # the clock may have ticked past the snapshot on DROPPED pushes
        # (they advance the clock but not the model).  An accepted push
        # bumps the model GENERATION before its clock tick, so if the
        # generation still matches this snapshot's after an atomic clock
        # read, every tick in between was a drop -- same bytes, newer
        # version: stamp the current clock (send-time parity with the
        # serial path).  A lost race just serves snap.ts, which only
        # over-prices staleness, never mispairs version and bytes.
        cur = self._clock
        if cur != ts and self._model_gen == snap.gen:
            ts = cur
        basis = None
        if have is not None and self._delta_versions > 0:
            # recent-version cache for delta encoding, maintained only
            # once a delta client exists; ts is monotone, so insertion
            # order IS version age and eviction pops the oldest
            with self._versions_lock:
                if snap.ts not in self._w_versions:
                    self._w_versions[snap.ts] = w_host
                    while len(self._w_versions) > self._delta_versions:
                        self._w_versions.popitem(last=False)
                if ts != snap.ts and ts not in self._w_versions:
                    self._w_versions[ts] = w_host  # same bytes, newer ts
                    while len(self._w_versions) > self._delta_versions:
                        self._w_versions.popitem(last=False)
        if have is not None:
            if int(have) == ts:
                # exact-version match needs no cache: the basis IS the
                # current version, so this encodes to NOT_MODIFIED
                # (the reply CRC still guards a cross-PS-life clash)
                basis = w_host
            elif self._delta_versions > 0:
                with self._versions_lock:
                    basis = self._w_versions.get(int(have))
        model_hdr: dict = {}
        model_part: bytes = w_wire
        if have is not None:
            wenc, enc_payload, nnz = wiredelta.encode(
                w_host, basis, cur_bytes=w_wire
            )
            model_hdr = {"wenc": wenc, "crc": w_crc}
            if wenc == wiredelta.XDELTA:
                model_hdr["nnz"] = nnz
            model_part = enc_payload
            model_hdr["wlen"] = len(model_part)
        return ts, cur, model_hdr, model_part

    def _handle_pull(self, conn: socket.socket, header: dict) -> None:
        wid = int(header["wid"])
        proc = header.get("proc")
        if self._t0 is not None:
            with self._stats_lock:
                self._last_contact[wid] = self._now_ms()
        sup = self.supervisor
        if sup is not None:
            if not sup.owns(proc, wid):
                # a deposed surrogate (the real owner rejoined): stand down
                _send_msg(conn, {"op": "RELEASED"})
                return
            sup.touch(wid, proc)
            sup.ack_adoption(proc, wid)
        if self._done.is_set():
            _send_msg(conn, {"op": "DONE"})
            return
        # traced update: time spent in the partial-barrier wave gate below
        # is THE server-side pull latency (pull.wait).  Untraced pulls (no
        # tc header -- sampling off or unsampled update) do no trace work.
        tc = _trace.TraceContext.from_wire(header["tc"]) \
            if "tc" in header else None
        t_wait0 = _trace.now_ms() if tc is not None else 0.0
        STARVATION_S = 1.0  # degraded-cohort release when peers are gone
        with self._wave_cv:
            self._waiting.append(wid)
            my_wave = self._wave_id
            if len(self._waiting) >= self._cohort_threshold():
                # the partial barrier fires
                self._release_wave_locked()
            else:
                t_enter = time.monotonic()
                while (
                    my_wave == self._wave_id
                    and not self._done.is_set()
                    and not self._stop.is_set()
                ):
                    self._wave_cv.wait(timeout=0.05)
                    # membership may have shrunk while we waited: the
                    # clamped threshold can release this wave NOW
                    if (
                        my_wave == self._wave_id
                        and len(self._waiting) >= self._cohort_threshold()
                    ):
                        self._release_wave_locked()
                        break
                    # starvation fallback: when fewer than threshold workers
                    # are still alive the wave can never fill -- after a
                    # full second of waiting, release whoever is here as a
                    # degraded cohort (the reference's wait loop assumes
                    # workers come back; dead ones never do)
                    if (
                        my_wave == self._wave_id
                        and time.monotonic() - t_enter > STARVATION_S
                    ):
                        self._release_wave_locked()
                        break
        t_wait1 = _trace.now_ms() if tc is not None else 0.0
        if self._done.is_set():
            _send_msg(conn, {"op": "DONE"})
            return
        extra_hdr: dict = {}
        extra_payload = b""
        if self.algo == "asaga":
            # PS-side seeded sampling (the reference driver's sampledMap
            # draw): per-wid RNG chain, Bernoulli(b) over the worker's
            # shard rows, padded to the static step capacity.  Deliberately
            # OUTSIDE the global lock: per-wid state (rng/table/pending) is
            # only ever touched by this wid's connection thread (pull and
            # push are serialized per connection, and no push can arrive
            # before this MODEL is sent), and O(n_p) sampling must not
            # queue other workers' pulls or the push/apply hot path.
            from asyncframework_tpu.ops.steps import sparse_step_capacity

            n_p = int(header["n_p"])
            with self._saga_lock:  # vs the checkpoint writer's snapshot
                table = self._table.get(wid)
                if table is None or table.shape[0] != n_p:
                    table = np.zeros(n_p, np.float32)
                    self._table[wid] = table
                rng = self._rngs.get(wid)
                if rng is None:
                    rng = np.random.default_rng([self.cfg.seed, wid])
                    self._rngs[wid] = rng
                cap = sparse_step_capacity(self.cfg.batch_rate, n_p)
                idx = np.nonzero(rng.random(n_p) < self.cfg.batch_rate)[0]
                if idx.size > cap:  # ~1e-9/draw: drop the excess (parity
                    idx = idx[:cap]  # with the device steps' capacity rule)
                idx_pad = np.zeros(cap, np.uint32)
                idx_pad[: idx.size] = idx
                alpha_sel = table[idx_pad].astype(np.float32)
                self._pending_idx[wid] = idx.astype(np.int64)
            extra_hdr = {"cap": cap, "n_valid": int(idx.size)}
            extra_payload = idx_pad.tobytes() + alpha_sel.tobytes()
        have = header.get("have")
        ts, _clock, model_hdr, model_part = self._negotiated_model(have)
        with self._stats_lock:
            self._pull_times[wid] = self._now_ms()
            shape = model_hdr.get("wenc", "full")
            self.pull_replies[shape] = self.pull_replies.get(shape, 0) + 1
            self.pull_model_bytes += len(model_part)
        avg = self.avg_delay_ms
        if tc is not None:
            # exactly the wave-gate wait (barrier cost), not the model
            # readback; folded here because the served version ts is only
            # known under the lock
            self._fold_span(_trace.Span(
                stage=_trace.PULL_WAIT, trace_id=tc.trace_id,
                span_id=_trace._new_id(8), parent_id=tc.span_id,
                worker_id=wid, model_version=ts, start_ms=t_wait0,
                dur_ms=max(0.0, t_wait1 - t_wait0),
            ))
        if sup is not None:
            # adoption orders ride the PULL reply (no extra RTT, no side
            # channel): re-delivered until the adopter's first pull FOR the
            # orphan lands, so a lost reply cannot lose a shard
            orders = sup.orders_for(proc)
            if orders:
                extra_hdr["adopt"] = orders
        ctrl = self.ctrl
        if ctrl is not None:
            # adaptive-control decisions ride PULL replies the same way
            # adoption orders do: re-delivered until the client's ``cs``
            # stamp catches up with the decision's FULL (epoch, seq)
            # stamp -- a restarted controller under a minted epoch
            # starts seq over, and a bare-seq compare would strand
            # every surviving worker on the deposed decisions.  A lost
            # reply cannot lose a decision and a settled cluster pays
            # zero extra bytes per pull.  Absent with control off.
            cs = header.get("cs")
            if cs is None:
                stamp = (0, -1)
            elif isinstance(cs, (list, tuple)) and len(cs) == 2:
                stamp = (int(cs[0]), int(cs[1]))
            else:  # legacy bare-seq stamp: pair it with OUR epoch
                stamp = (int(ctrl.get("ep", 0) or 0), int(cs))
            from asyncframework_tpu.parallel.controller import ctrl_seq

            if stamp < ctrl_seq(ctrl):
                extra_hdr["ctrl"] = ctrl
        # vectored zero-copy framing: the cached model bytes and the ASAGA
        # extra payload go out as one kernel-gathered iovec -- the payload
        # is never copied into a fresh frame buffer
        if self.epoch:
            # fencing on: replies advertise the current epoch so a
            # client that joined before a fence converges without a
            # REJECT_FENCED round trip (absent with fencing off --
            # byte-identical legacy wire)
            extra_hdr["ep"] = self.epoch
        _frame.send_msg_vectored(
            conn,
            {"op": "MODEL", "ts": ts, "avg_delay_ms": avg,
             "calibrated":
                 self._cal_n >= self.cfg.effective_calibration_iters(),
             **model_hdr, **extra_hdr},
            (model_part, extra_payload) if extra_payload
            else (model_part,),
        )

    def _version_age_ms(self, ts: int, clock: int) -> float:
        """Freshness age of model version ``ts``: ms since the first NEWER
        version was published (0 while ``ts`` is still the current model).
        Bounded scan of the birth ring -- entries are clock-ascending, so
        the first key past ``ts`` is the moment ``ts`` stopped being the
        latest; an evicted birth (very stale subscriber) under-reports
        rather than guessing."""
        if ts >= clock or self._t0 is None:
            return 0.0
        now = self._now_ms()
        with self._born_lock:
            for v, born in self._ver_born.items():
                if v > ts:
                    return max(0.0, now - born)
        return 0.0

    def _register_relay_child(self, host: str, port: int) -> None:
        """Record a relaycast direct child (SUBSCRIBE carried ``rport``)
        and lazily start the offer thread.  The shared ChildRegistry
        (relaycast/offers.py) bounds the set at the tree fanout with
        LRU eviction: direct children renew their slot on every
        subscribe, so a stale registrant (a deep node that re-homed
        here once) is displaced, never a live one."""
        start = False
        with self._relay_lock:
            if self._relay_registry is None:
                from asyncframework_tpu.relaycast.offers import (
                    ChildRegistry,
                )

                self._relay_registry = ChildRegistry(self._relay_fanout)
            if self._relay_thread is None:
                from asyncframework_tpu.utils.threads import guarded

                self._relay_thread = threading.Thread(
                    target=guarded(self._relay_offer_loop,
                                   "ps-relay-offer"),
                    name="ps-relay-offer", daemon=True,
                )
                start = True
        self._relay_registry.register(host, port)
        if start:
            self._relay_thread.start()

    def _relay_offer_loop(self) -> None:
        """The root offer path: watch the merge clock and announce each
        new published version (RELAY_OFFER: ts + CRC + epoch) to the
        registered direct children via the shared ChildRegistry fan-out.
        Entirely off the hot path -- the snapshot build it may trigger
        is the same one the next pull would pay, and sends happen
        outside every lock with short timeouts."""
        while not self._stop.is_set():
            self._stop.wait(0.02)
            clock = self._clock
            if clock == self._relay_offered:
                continue
            registry = self._relay_registry
            if registry is None or not registry.children():
                self._relay_offered = clock
                continue
            snap = self._model_snap()
            hdr = {"op": "RELAY_OFFER", "ts": snap.ts, "crc": snap.crc}
            if self.epoch:
                hdr["ep"] = self.epoch
            self.relay_offers += registry.offer(hdr)
            self._relay_offered = clock

    def _handle_subscribe(self, conn: socket.socket, header: dict) -> None:
        """Serving-tier snapshot subscription (serving/replica.py).

        Same ``have=``-negotiated NOT_MODIFIED / XDELTA / FULL reply
        shapes as PULL -- the replica cache-invalidation protocol IS the
        delta-pull protocol -- but deliberately WITHOUT the partial-
        barrier wave gate (a read must never wait for a training cohort
        to fill), without membership/ownership discipline (replicas are
        not shard servers), and still answering after DONE (training
        finishing must not take the read path down).  Entirely lock-free
        on the model lock, like ``_handle_pull``.  The reply additionally
        carries the PS merge clock, the accepted-update count, the served
        version's age in ms, and the done flag, so replicas can price
        their own freshness lag in versions AND ms."""
        rp = header.get("rport")
        if rp is not None:
            # relaycast: the subscriber runs a relay node on this port --
            # register it for the root offer path
            try:
                peer = conn.getpeername()[0]
            except OSError:
                peer = None
            if peer is not None:
                self._register_relay_child(peer, int(rp))
        have = header.get("have")
        ts, cur, model_hdr, model_part = self._negotiated_model(have)
        shape = model_hdr.get("wenc", "full")
        with self._stats_lock:
            self.subscribe_replies[shape] = (
                self.subscribe_replies.get(shape, 0) + 1
            )
            self.subscribe_model_bytes += len(model_part)
        if self.epoch:
            model_hdr["ep"] = self.epoch
        _frame.send_msg_vectored(
            conn,
            {"op": "MODEL", "ts": ts, "clock": cur, "k": self._k,
             "done": self._done.is_set(),
             "age_ms": round(self._version_age_ms(ts, cur), 3),
             **model_hdr},
            (model_part,),
        )

    def _handle_push(self, conn: socket.socket, header: dict,
                     payload: bytes) -> None:
        wid = int(header["wid"])
        ts = int(header["ts"])
        proc = header.get("proc")
        # completed client-side spans ride the PUSH header (the piggyback
        # that makes spans survive worker death); fold them before any
        # drop path so a membership-stale push still delivers its telemetry
        self._fold_wire_spans(header.get("spans"))
        # pipelined-loop counter deltas piggyback the same way (only
        # present when the worker runs the pipelined loop): dedup'd
        # retries never reach this handler, so a delta folds exactly once
        _pl_fold(header.get("pl"))
        # convergence samples (conf-gated, async.convergence.sample):
        # (version, loss, grad_norm) tuples fold into the loss-vs-wallclock
        # / loss-vs-version curves, stamped with THIS PS's run clock and
        # the staleness it observes right now
        _cv_fold(header.get("cv"), clock=self._clock,
                 wall_ms=self._bus_time_ms())
        tc = _trace.TraceContext.from_wire(header["tc"]) \
            if "tc" in header else None
        t_queue0 = _trace.now_ms() if tc is not None else 0.0
        sup = self.supervisor
        if sup is not None and not sup.owns(proc, wid):
            # membership-stale push: the shard was re-homed (rejoin deposed
            # this surrogate) -- drop it like any other too-stale gradient,
            # but do not tick the merge clock (nothing was considered)
            with self._lock:
                self.membership_rejects += 1
                ack = {"op": "ACK", "accepted": False, "released": True,
                       "done": self._done.is_set()}
                self._dedup.record(header, ack)
            _send_msg(conn, ack)
            return
        if sup is not None:
            sup.touch(wid, proc)
        diff = None
        if header.get("gq") is not None:
            # quantized gradient (net/wirecodec.py, async.codec.push):
            # fp16/int8 payload back to dense f32.  The worker's error-
            # feedback accumulator already folded this push's
            # quantization residual into its NEXT gradient, so the
            # server applies the dequantized value as-is -- stateless
            # here by design.  ASAGA never quantizes (exact history
            # scalars), so diff stays None.
            try:
                g_host = wirecodec.decode_grad(header, payload, self.d)
            except ValueError as e:
                _send_msg(conn, {"op": "ERR",
                                 "msg": f"bad quantized push: {e}"})
                return
        elif header.get("enc") == "sparse":
            # (idx, val) pair gradient (rcv1-class): scatter into dense on
            # host -- the PS's apply path is dense either way
            nnz = int(header["nnz"])
            idx_g = np.frombuffer(payload[: 4 * nnz], np.uint32)
            val_g = np.frombuffer(payload[4 * nnz: 8 * nnz], np.float32)
            g_host = np.zeros(self.d, np.float32)
            g_host[idx_g] = val_g
            if self.algo == "asaga":
                diff = np.frombuffer(payload[8 * nnz:], np.float32)
        else:
            raw = np.frombuffer(payload, np.float32)
            if self.algo == "asaga":
                g_host, diff = raw[: self.d], raw[self.d:]
            else:
                g_host = raw
        # merge queue: the payload was decoded OUTSIDE the lock; whoever
        # holds the model lock next coalesces every pending push into one
        # fused device apply.  Per-push accept/reject, dedup, clock, and
        # calibration bookkeeping stay per item (FIFO), exactly as the
        # serial path ordered them -- only the device dispatch is batched.
        item = _PendingPush(wid, ts, g_host, diff, header, len(payload),
                            tc, t_queue0)
        self._merge_q.append(item)
        with self._lock:
            while not item.done:
                self._drain_merge_locked()
        # pre-warm the pull snapshot for the version this drain produced,
        # OFF the model lock, on this (push) thread: the next cohort pull
        # finds it published and pays zero build latency.  A no-op when a
        # peer already built it; worst case under heavy churn the build
        # races a newer drain and is skipped at publish (CRC-gated
        # fallback keeps even the raciest interleaving degrade-to-full,
        # never wrong).
        if item.accepted:
            self._model_snap()
        if tc is not None:
            # staleness in TIME (ASAP's quantity): age of the model basis
            # this gradient was computed on = now - that version's pull.
            # merge.queue covers decode + wait for the single-writer model
            # lock; merge.apply covers the drain this push rode (tau
            # filter + fused apply dispatch) under the lock.
            self._fold_span(_trace.Span(
                stage=_trace.MERGE_QUEUE, trace_id=tc.trace_id,
                span_id=_trace._new_id(8), parent_id=tc.span_id,
                worker_id=wid, model_version=ts, start_ms=t_queue0,
                dur_ms=max(0.0, item.t_apply0 - t_queue0),
            ))
            self._fold_span(_trace.Span(
                stage=_trace.MERGE_APPLY, trace_id=tc.trace_id,
                span_id=_trace._new_id(8), parent_id=tc.span_id,
                worker_id=wid, model_version=ts, start_ms=item.t_apply0,
                dur_ms=max(0.0, item.t_done - item.t_apply0),
                staleness=int(item.staleness),
                staleness_ms=float(item.task_ms),
                accepted=bool(item.accepted),
            ))
        if self.bus is not None:
            from asyncframework_tpu.metrics.bus import GradientMerged

            self.bus.post(GradientMerged(
                self._bus_time_ms(), worker_id=wid,
                staleness=int(item.staleness),
                accepted=bool(item.accepted),
                iteration=item.k_at_merge,
            ))
        with self._wave_cv:
            self._wave_cv.notify_all()  # a wave may now meet its threshold
        _send_msg(conn, item.ack)
        if item.do_snapshot:
            # printer_freq cadence: signal the async checkpoint thread --
            # nobody's next message waits behind the disk write
            self._ckpt_trigger.set()

    @_prof.zoned("merge.drain")
    def _drain_merge_locked(self) -> None:
        """Caller holds ``_lock``.  Drain up to ``_merge_max`` pending
        pushes in FIFO order -- per-push accept/reject, dedup, clock, and
        calibration bookkeeping identical to the serial path -- then run
        ONE fused device apply for all accepted gradients
        (``ops/steps.make_*_apply_merge``, bit-identical to the serial
        apply order).  A push landing on the printer_freq snapshot
        boundary closes its batch so the host copy below pins exactly
        that version."""
        import jax

        drained: List[_PendingPush] = []
        batch: List[Tuple[_PendingPush, Optional[np.ndarray]]] = []
        # replication stream (parallel/replication.py): the standby
        # applies from exactly this clock, so capture it before any
        # item ticks it
        pre_clock = self._clock
        # donation guard, captured BEFORE any accept mutates gen/_snap:
        # the fused kernel donates the model buffer (in-place apply), so
        # it may only run when the OUTGOING version already exists as a
        # host-side _ModelSnap -- then no rebuild, checkpoint, or delta
        # encode can ever need the donated device buffer again.  The
        # accepted-push pre-warm (_model_snap right after each drain)
        # makes this the overwhelmingly common case.
        prev_snap = self._snap
        prev_gen = self._model_gen
        # adaptive control: the EFFECTIVE merge budget moves within
        # [1, _merge_max] (the compiled kernel bound; padding makes any
        # smaller batch exact).  0 = no override = the configured bound.
        budget = self._ctrl_merge or self._merge_max
        budget = max(1, min(budget, self._merge_max))
        while self._merge_q and len(drained) < budget:
            item = self._merge_q.popleft()
            drained.append(item)
            item.t_apply0 = _trace.now_ms() if item.tc is not None else 0.0
            self.push_bytes += item.payload_len
            if self._t0 is not None:
                self._last_contact[item.wid] = self._now_ms()
            self.pushes_by_wid[item.wid] = (
                self.pushes_by_wid.get(item.wid, 0) + 1
            )
            staleness = self._clock - item.ts
            self.max_staleness = max(self.max_staleness, staleness)
            task_ms = self._now_ms() - self._pull_times.get(
                item.wid, self._now_ms()
            )
            if self._cal_n < self.cfg.effective_calibration_iters():
                self._cal_ms += task_ms
                self._cal_n += 1
                if self._cal_n >= self.cfg.effective_calibration_iters():
                    self.avg_delay_ms = self._cal_ms / max(self._cal_n, 1)
            idx = None
            if self.algo == "asaga":
                # ASAGA's filter quirk: accept iff k - staleness <= taw
                # (SparkASAGAThread.scala:184; the ASGD driver tests
                # staleness <= taw).  A push whose pull-time sample the PS
                # no longer holds (restart) cannot commit -- drop it.
                idx = self._pending_idx.pop(item.wid, None)
                accepted = (
                    self._k - staleness <= self.cfg.taw
                    and self._k < self.cfg.num_iterations
                    and idx is not None
                )
            else:
                accepted = (
                    staleness <= self.cfg.taw
                    and self._k < self.cfg.num_iterations
                )
            if accepted:
                # bump the model generation and unpublish the snapshot
                # BEFORE the clock tick: a concurrent lock-free pull
                # that reads this item's new clock must see the new
                # generation too and keep the snapshot's own (older)
                # version stamp -- never pair new version, old bytes.
                # Dropped pushes tick the clock WITHOUT bumping: the
                # model is unchanged, so the snapshot stays valid.
                self._model_gen += 1
                self._snap = None
                batch.append((item, idx))
                self._k += 1
                self.accepted += 1
                self.accepted_by_wid[item.wid] = (
                    self.accepted_by_wid.get(item.wid, 0) + 1
                )
                if self._k % self.cfg.printer_freq == 0:
                    item.do_snapshot = True
                if self._k >= self.cfg.num_iterations:
                    self._done.set()
                    if self.supervisor is not None:
                        # run complete: pin membership -- post-done silence
                        # (evaluation, teardown) is not death
                        self.supervisor.freeze()
            else:
                self.dropped += 1
            self._clock += 1
            item.staleness = staleness
            item.task_ms = task_ms
            item.accepted = accepted
            if accepted:
                # delay-adaptive step damping (CTRL law; 1.0 = exact
                # undamped legacy whenever control is off): decided per
                # item at drain time from ITS observed staleness, so a
                # dedup-replayed stamp -- which never reaches a second
                # drain -- keeps exactly the factor it was applied with
                item.damp = self._item_damp(item.wid, staleness)
            item.k_at_merge = self._k
            self._wstat_merge(item.wid, staleness, accepted)
            ack = {"op": "ACK", "accepted": bool(accepted),
                   "done": self._done.is_set()}
            # record INSIDE the lock, before any send: (1) a retry after a
            # lost ACK must find the (sid, seq) applied; (2) the checkpoint
            # writer serializes state under this same lock, so a saved
            # model can never be missing the dedup entry of a push it
            # already contains (that gap would re-apply the push after a
            # restart)
            self._dedup.record(item.header, ack)
            item.ack = ack
            if item.do_snapshot:
                # close the batch at the snapshot boundary: the pinned
                # host copy must be exactly version k, not a later one
                break
        if batch:
            donate_ok = (prev_snap is not None
                         and prev_snap.gen == prev_gen)
            if len(batch) == 1 or self._apply_merge is None:
                self._apply_one(batch[0][0], batch[0][1])
            elif not donate_ok:
                # outgoing version not host-published (two drains raced
                # faster than the off-lock pre-warm): the fused kernel
                # would donate a device buffer the next rebuild still
                # needs.  Apply serially instead -- the merge kernel is
                # bit-identical to this order by contract, so the model
                # cannot tell which path ran.
                for it, idx2 in batch:
                    self._apply_one(it, idx2)
            else:
                # donation window: until this drain publishes its new
                # basis below, point rebuilds at the HOST copy of the
                # outgoing version -- the device buffer dies the moment
                # the donated dispatch below runs
                self._snap_basis = (prev_snap.ts, prev_snap.w_host,
                                    prev_snap.gen)
                # ONE fused device dispatch for the whole drained batch:
                # padded to the static merge bound so the kernel compiles
                # once, masked so padding slots are no-ops.  The scratch is
                # reused (no per-drain allocation) and padding rows keep
                # whatever a previous drain left: the scan's
                # `where(mask > 0, ...)` discards their w2 elementwise, so
                # they never touch the result
                G, mask = self._merge_G, self._merge_mask
                for j, (it, _idx) in enumerate(batch):
                    G[j] = it.g_host
                    # a mask slot carries the per-item step-DAMP factor
                    # (1.0 = classic keep bit, exact; 0 below = skip)
                    mask[j] = it.damp
                mask[len(batch):] = 0.0
                G_dev = jax.device_put(G, self.device)
                m_dev = jax.device_put(mask, self.device)
                if self.algo == "asaga":
                    self._w, self._ab = self._apply_merge(
                        self._w, self._ab, G_dev, m_dev
                    )
                    with self._saga_lock:  # vs checkpoint table copies
                        for it, idx2 in batch:
                            self._table[it.wid][idx2] = (
                                it.diff[: idx2.size]
                            )
                else:
                    self._w, self._k_dev = self._apply_merge(
                        self._w, G_dev, m_dev, self._k_dev
                    )
            # publish the new build basis (O(1) tuple swap under the lock
            # this drain already holds): the next snapshot rebuild reads
            # it lock-free instead of queueing on the model lock
            self._snap_basis = (self._clock, self._w, self._model_gen)
            # version birth (serving plane): this drain PUBLISHED a new
            # model version -- stamp its clock with the wall time so
            # SUBSCRIBE replies can price freshness age in ms (O(1), its
            # own small lock; never the pull path's).
            if self._t0 is not None:
                with self._born_lock:
                    self._ver_born[self._clock] = self._now_ms()
                    while len(self._ver_born) > 1024:
                        self._ver_born.popitem(last=False)
            self.merge_batches += 1
            self.merge_merged += len(batch)
            self.merge_batch_max = max(self.merge_batch_max, len(batch))
        if self.repl is not None and drained:
            # hot-standby replication: hand the WHOLE drained batch --
            # verdicts, (sid, seq) stamps, staleness, and the accepted
            # gradients' host arrays -- to the stream.  O(items) list
            # work under the lock; serialization and I/O happen on the
            # sender thread.  Dropped items ride too: they tick the
            # standby's clock and land their dedup verdicts, so a
            # promoted standby re-answers EVERY replayed stamp.
            items = []
            grads = []
            for it in drained:
                # the per-item step-DAMP factor rides the stream: the
                # mirror must apply EXACTLY the step the primary did or
                # its model silently diverges (and a promotion would
                # serve the divergent copy)
                items.append([it.wid, it.ts, 1 if it.accepted else 0,
                              it.header.get("sid"), it.header.get("seq"),
                              it.ack, int(it.staleness),
                              float(it.damp)])
                if it.accepted:
                    grads.append(it.g_host)
            self.repl.enqueue(pre_clock, items, grads,
                              [self._cal_ms, self._cal_n,
                               self.avg_delay_ms])
        if drained:
            # flight-recorder breadcrumb (metrics/flightrec.py): one
            # event per drain so a SIGKILLed PS's dump ends with its
            # last applied batch (no-op when no recorder is installed)
            _flight.note("merge", clock=self._clock, k=self._k,
                         batch=len(drained),
                         accepted=self.accepted, dropped=self.dropped)
        for item in drained:
            if item.do_snapshot:
                # host copy NOW: the snapshot must pin this version (the
                # boundary item closed its batch above, so _w is exactly
                # the k it rode in on).  Owned copy, not a buffer view:
                # a later donated drain overwrites the device memory
                self._snapshots.append(
                    (self._now_ms(), np.array(self._w, np.float32))
                )
            if item.tc is not None:
                item.t_done = _trace.now_ms()
            item.done = True

    def _apply_one(self, item: _PendingPush,
                   idx: Optional[np.ndarray]) -> None:
        """Serial single-push apply (the classic one-dispatch path; caller
        holds ``_lock``)."""
        import jax

        g_dev = jax.device_put(item.g_host, self.device)
        if self.algo == "asaga":
            # three-term update + alpha_bar advance (delta == g is exact
            # over DCN; see __init__); then the ScalarMap merge -- commit
            # this push's candidate scalars
            self._w, self._ab = self._apply(self._w, self._ab, g_dev, g_dev)
            with self._saga_lock:  # vs checkpoint table copies
                self._table[item.wid][idx] = item.diff[: idx.size]
        elif item.damp != 1.0 and self._apply_damped is not None:
            # delay-adaptive damped apply: the SAME expression as the
            # damped merge-kernel body, so serial and fused drains stay
            # bit-identical at every damp value
            self._w, self._k_dev = self._apply_damped(
                self._w, g_dev, self._k_dev, np.float32(item.damp))
        else:
            self._w, self._k_dev = self._apply(self._w, g_dev, self._k_dev)

    # ------------------------------------------------------------ evaluation
    def wait_done(self, timeout_s: float,
                  progress_timeout_s: Optional[float] = None) -> "WaitDone":
        """Progress-aware wait for the run to finish.

        Returns a truthy :class:`WaitDone` on completion.  On timeout --
        or, with ``progress_timeout_s``, as soon as NO worker has contacted
        the PS and the merge clock has not moved for that long -- returns a
        falsy ``WaitDone`` carrying the per-worker last-contact +
        contribution-bitmap diagnostic instead of a bare ``False``, so a
        stalled run names its silent workers instead of hanging mute for
        the full timeout.
        """
        deadline = time.monotonic() + timeout_s
        last_progress = time.monotonic()
        seen_clock = -1
        seen_contact = -1.0
        while True:
            left = deadline - time.monotonic()
            if self._done.wait(timeout=max(0.0, min(0.2, left))):
                return WaitDone(True, None)
            now = time.monotonic()
            with self._lock:
                clock = self._clock
                contact = max(self._last_contact.values(), default=-1.0)
            if clock != seen_clock or contact != seen_contact:
                seen_clock, seen_contact = clock, contact
                last_progress = now
            stalled = (
                progress_timeout_s is not None
                and now - last_progress > progress_timeout_s
            )
            if stalled or now >= deadline:
                return WaitDone(False, self.progress_diagnostic(
                    stalled="stalled" if stalled else "timeout"
                ))

    def progress_diagnostic(self, stalled: str = "timeout") -> str:
        """Per-worker last-contact ages, push/accept counts, and the
        contribution bitmap -- everything needed to see WHO went silent."""
        with self._lock:
            now = self._now_ms() if self._t0 is not None else 0.0
            k, clock = self._k, self._clock
            contact = dict(self._last_contact)
            pushes = dict(self.pushes_by_wid)
            accepted = dict(self.accepted_by_wid)
        member = (self.supervisor.membership()
                  if self.supervisor is not None else {})
        nw = self.cfg.num_workers
        bitmap = "".join(
            "1" if accepted.get(w, 0) > 0 else "0" for w in range(nw)
        )
        lines = [
            f"PS {stalled}: k={k}/{self.cfg.num_iterations} "
            f"clock={clock} contributed-bitmap={bitmap}",
        ]
        for w in range(nw):
            age = contact.get(w)
            age_s = "never" if age is None else f"{now - age:8.0f}ms ago"
            extra = ""
            m = member.get(w)
            if m:
                extra = f" state={m['state']} owner={m['owner']}"
            lines.append(
                f"  wid {w:3d}: last-contact {age_s:>14}  "
                f"pushes={pushes.get(w, 0):<6d} "
                f"accepted={accepted.get(w, 0):<6d}{extra}"
            )
        return "\n".join(lines)

    def snapshot_stack(self) -> Tuple[List[float], np.ndarray]:
        with self._lock:
            final = (self._now_ms(), np.array(self._w, np.float32))
            snaps = list(self._snapshots) + [final]
        times = [t for (t, _w) in snaps]
        W = np.stack([w for (_t, w) in snaps])
        return times, W

    def collect_eval(self, num_worker_procs: int, timeout_s: float
                     ) -> Optional[np.ndarray]:
        """Sum per-process snapshot losses pushed via EVAL_RESULT.

        With the supervisor, the expected count is clamped to processes
        that were still ALIVE when the run finished: a crashed worker's
        EVAL never comes, but its adopted shards are scored by their
        adopter -- the union still covers the full dataset, so waiting
        for the dead process would only trade the objective for a
        timeout."""
        deadline = time.monotonic() + timeout_s
        with self._eval_cv:
            while True:
                expected = num_worker_procs
                if self.supervisor is not None:
                    # clamp only when processes actually registered (an
                    # unelastic client set leaves the roster empty)
                    live = self.supervisor.live_proc_count()
                    if live > 0:
                        expected = min(expected, live)
                if len(self._eval_results) >= expected:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._eval_cv.wait(timeout=min(left, 0.2))
            total = None
            for arr in self._eval_results.values():
                total = arr if total is None else total + arr
            return total

    @property
    def dedup_hits(self) -> int:
        """Retried PUSHes answered from the dedup window (each one is a
        gradient that would have merged twice before net/session.py)."""
        return self._dedup.hits

    def stop(self) -> None:
        self._stop.set()
        self._done.set()
        if self.repl is not None:
            self.repl.stop()
        if getattr(self, "_ts_source", None) is not None:
            from asyncframework_tpu.metrics import timeseries as _ts

            # identity-gated: a stopped PS must not unhook its replacement
            _ts.unregister_source("ps", self._ts_source)
        if getattr(self, "_workers_section", None) is not None:
            from asyncframework_tpu.metrics import live as _live

            _live.unregister_status_section("ps_workers",
                                            self._workers_section)
        if self.supervisor is not None:
            self.supervisor.stop()
        with self._wave_cv:
            self._wave_cv.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass
        # reap on stop: drop every finished handler thread (live ones are
        # daemons draining their last reply; they exit with the sockets)
        self._threads = [x for x in self._threads if x.is_alive()]


class FencedError(ConnectionError):
    """The server refused this client's ops under epoch fencing and the
    client cannot self-heal by adopting a newer epoch -- the server
    itself is at (or below) the client's epoch, i.e. the client is
    talking to a deposed zombie.  Subclasses ConnectionError so worker
    loops treat it like any other dead endpoint: pace, re-dial, and
    land on the current owner."""


# -------------------------------------------------------------- worker side
class PSClient:
    """One TCP connection to the PS (workers may hold several, one per
    logical worker id, or share one -- the protocol is synchronous per
    connection, like an RpcEndpointRef).

    Transport faults are the retry layer's problem now: every RPC routes
    through a :class:`~asyncframework_tpu.net.RetryPolicy` (backoff +
    jitter + per-endpoint circuit breaker), reconnecting between attempts.
    Mutating ops (PUSH) are stamped with this client's session ``(sid,
    seq)`` so a retry after a lost ACK is answered from the PS's dedup
    window instead of merging the gradient twice."""

    def __init__(self, host: str, port: int, timeout_s: float = 120.0,
                 retry: Optional[RetryPolicy] = None,
                 session: Optional[ClientSession] = None,
                 proc: Optional[str] = None,
                 recorder: Optional["_trace.TraceRecorder"] = None,
                 pull_mode: Optional[str] = None,
                 pl_stats: Optional[_PipelineStats] = None,
                 cv_buf=None, epoch: int = 0,
                 push_codec: Optional[str] = None, ctrl_sink=None,
                 shm: Optional[bool] = None):
        self.host, self.port = host, int(port)
        # adaptive control plane: a ControlSink (parallel/controller.py)
        # shared by this worker process's clients.  PULL requests stamp
        # the sink's decision seq (``cs``) and PULL replies carrying a
        # newer CTRL payload install into it (monotone by (ep, seq)).
        # None (every non-controlled client) = no header field,
        # byte-identical wire.
        self.ctrl_sink = ctrl_sink
        self.endpoint = f"{host}:{self.port}"
        # fencing epoch this client stamps on every PULL/PUSH/SUBSCRIBE
        # (``ep`` header key; 0 = fencing off, no key, byte-identical
        # legacy wire).  Seeded from the WELCOME handshake and advanced
        # by MODEL replies / REJECT_FENCED verdicts -- a fenced client
        # adopts the minted epoch and its NEXT op is admitted; entries
        # already stamped (the windowed push pipe replays verbatim) keep
        # their old epoch and are rejected exactly once each, which is
        # the point: a deposed incarnation's buffered writes never land.
        self.epoch = int(epoch)
        self.fenced_replies = 0
        self.retry = retry if retry is not None else RetryPolicy.from_conf(
            attempt_timeout_s=timeout_s
        )
        self.session = session if session is not None else ClientSession()
        # version-gated delta pulls (net/wiredelta.py): in 'delta' mode the
        # client advertises its basis version (``have=<ts>``) on every
        # PULL and keeps the last successfully decoded model per wid so a
        # NOT_MODIFIED / XDELTA reply can reconstruct byte-exactly.  Any
        # decode mismatch or cache miss falls back to a full pull -- the
        # basis is only ever replaced by a CRC-validated reconstruction or
        # an authoritative full payload, never left wrong.
        if pull_mode is None:
            from asyncframework_tpu.conf import PULL_MODE, global_conf

            pull_mode = str(global_conf().get(PULL_MODE))
        self.pull_mode = pull_mode
        # gradient quantization (net/wirecodec.py, async.codec.push):
        # 'off' (default) ships raw f32 -- byte-identical legacy wire;
        # fp16/int8 quantize each dense ASGD push and keep the residual
        # in a per-wid error-feedback accumulator folded into the next
        # push, so the model's deviation from the uncompressed
        # trajectory is bounded by ONE step's quantization error.
        if push_codec is None:
            from asyncframework_tpu.conf import CODEC_PUSH, global_conf

            push_codec = str(global_conf().get(CODEC_PUSH))
        self.push_codec = push_codec
        self._ef: Dict[int, np.ndarray] = {}  # wid -> carried residual
        # wid -> (ts, float32 basis array, crc of its bytes)
        self._basis: Dict[int, Tuple[int, np.ndarray, int]] = {}
        self.pull_wenc: Dict[str, int] = {"full": 0, "nm": 0, "xdelta": 0}
        self.pull_model_bytes = 0  # model-part payload bytes received
        self.delta_fallbacks = 0   # decode mismatch/cache miss full re-pulls
        # distributed tracing: completed spans from this process's recorder
        # piggyback on PUSH (and BYE) headers -- the PS folds them into its
        # event stream, so spans survive this worker's death.  None =
        # tracing off for this client, zero extra wire bytes.
        self.recorder = recorder
        # pipelined-loop counters (prefetch hits / stale discards /
        # in-flight depth): deltas piggyback on PUSH and BYE headers the
        # same way spans do.  None (every non-pipelined client) = no
        # header field, byte-identical wire.
        self.pl_stats = pl_stats
        # convergence telemetry (metrics/timeseries.ConvergenceBuffer):
        # buffered (version, loss, grad_norm) samples ride PUSH/BYE
        # headers as the ``cv`` entry, same discipline as spans and
        # pipeline counters.  None (the default) = no header field,
        # byte-identical wire.
        self.cv_buf = cv_buf
        # elastic membership: the worker PROCESS token stamped on every
        # PULL/PUSH so the PS supervisor knows who serves which shard;
        # None = classic fixed-membership client
        self.proc = proc
        self.released = False    # the PS deposed this client's wid
        self._orders: List[int] = []  # adoption orders from PULL replies
        # windowed push pipe (push_start/push_finish): sent-but-unACKed
        # entries, oldest first -- replayed wholesale on reconnect.  The
        # window lock serializes senders against the reaper's
        # reconnect+replay; receives happen outside it (full duplex).
        from collections import deque as _dq
        self._push_window: "_dq[list]" = _dq()
        self._win_lock = threading.Lock()
        # the one in-flight prefetched PULL (pull_start/pull_finish)
        self._pending_pull: Optional[tuple] = None
        # shared-memory transport (net/shmring.py): when enabled AND the
        # PS is colocated (loopback peer), each (re)dial opportunistically
        # upgrades the fresh TCP connection to a ring pair -- same framed
        # protocol, fewer copies, no GIL on the byte path.  A ring-level
        # failure latches _shm_failed so the NEXT dial stays on plain
        # TCP: the degrade is one reconnect away and never loops.
        if shm is None:
            from asyncframework_tpu.conf import SHM_ENABLED, global_conf

            shm = bool(global_conf().get(SHM_ENABLED))
        self.shm = bool(shm)
        self._shm_failed = False
        self._sock: Optional[socket.socket] = None
        self.bytes_pushed = 0  # payload bytes shipped by push/push_saga
        # eager first dial (historical behavior: constructing a client to a
        # dead PS raises) -- but through the policy, so a PS mid-restart is
        # ridden out instead of surfaced
        self._call_raw(connect_only=True)

    @property
    def sock(self) -> Optional[socket.socket]:
        return self._sock

    def _drop_sock(self) -> None:
        if self._sock is not None:
            if isinstance(self._sock, _shmring.ShmSocket):
                # a dropped ring transport is never resurrected blind:
                # the next dial stays on plain TCP (the upgrade is
                # opportunistic, the degrade is sticky per client --
                # reconnect-and-retry loops must converge, not oscillate
                # between a wedged ring and the socket)
                self._shm_failed = True
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _dial(self):
        """Fresh connection under this client's transport policy: the
        TCP dial, then the opportunistic shm-ring upgrade (colocated
        peer + conf gate + not previously degraded)."""
        sock = _frame.connect((self.host, self.port),
                              timeout=self.retry.attempt_timeout_s)
        if self.shm and not self._shm_failed:
            sock, _ = _shmring.maybe_upgrade(sock)
        return sock

    def _call_raw(self, header: Optional[dict] = None, payload: bytes = b"",
                  connect_only: bool = False) -> Tuple[dict, bytes]:
        """One stamped-or-not request/reply under the retry policy.  The
        header is REUSED verbatim across attempts -- a stamped op keeps its
        (sid, seq) so the server can dedup."""

        def attempt() -> Tuple[dict, bytes]:
            try:
                if self._sock is None:
                    self._sock = self._dial()
                if connect_only:
                    return {}, b""
                _send_msg(self._sock, header, payload)
                return _recv_msg(self._sock)
            except OSError:
                # dead/poisoned connection: never reuse it for the retry
                # (and _drop_sock pins a failed ring transport to TCP)
                self._drop_sock()
                raise

        return self.retry.call(attempt, endpoint=self.endpoint)

    def _proc_hdr(self, hdr: dict) -> dict:
        if self.proc is not None:
            hdr["proc"] = self.proc
        if self.epoch:
            hdr["ep"] = self.epoch
        return hdr

    def _note_orders(self, header: dict) -> None:
        if "adopt" in header:
            self._orders.extend(int(w) for w in header["adopt"])
        if self.ctrl_sink is not None and "ctrl" in header:
            # adaptive-control decisions ride replies like adoption
            # orders; the sink's monotone install discards stale ones
            self.ctrl_sink.install(header["ctrl"])

    def take_orders(self) -> List[int]:
        """Adoption orders received so far (drained)."""
        out, self._orders = self._orders, []
        return out

    def hello(self, proc: str, wids: List[int],
              pid: Optional[int] = None) -> dict:
        """Introduce this worker process to the PS (elastic registration;
        a fixed-membership PS just says WELCOME and ignores it).  Carries
        this process's /proc start time next to its pid so the
        supervisor's liveness probe can tell a recycled pid from the
        registered member."""
        import socket as _socket

        hdr = {
            "op": "HELLO", "proc": proc, "wids": [int(w) for w in wids],
            "pid": pid, "host": _socket.gethostname(),
        }
        if pid is not None:
            pstart = supervisor_mod.proc_start_time(pid)
            if pstart is not None:
                hdr["pstart"] = pstart
        # advertise this process's telemetry endpoint (when one serves):
        # the supervisor records it per member and the cluster observer
        # discovers worker scrape targets from the membership instead of
        # needing static endpoints.  Absent when telemetry is off -- the
        # byte-identity suites' wire is unchanged.
        from asyncframework_tpu.metrics import live as _live

        mport = _live.telemetry_port()
        if mport:
            hdr["mport"] = int(mport)
        header, _ = self._call_raw(hdr)
        return header

    def _traced_call(self, tr, stage: str, header: dict,
                     payload: bytes = b"") -> Tuple[dict, bytes]:
        """One RPC under an optional update trace: installs the ambient
        context (frame.send_msg stamps the ``tc`` header from it) for the
        call's duration and records the client-observed round-trip span.
        With ``tr=None`` this is exactly ``_call_raw``."""
        if tr is None:
            return self._call_raw(header, payload)
        token = tr.rpc_begin(stage)
        try:
            out = self._call_raw(header, payload)
        except BaseException:
            _trace.set_current(None)  # never leak the context on failure
            raise
        # wire cost of the RPC that just completed (frame bytes, both
        # directions) rides the rtt span -- latency AND volume decompose
        # per stage (net/frame.py counts at the choke point)
        tr.rpc_end(token, bytes=_frame.last_io_bytes())
        return out

    def _have_hdr(self, wid: int, hdr: dict) -> dict:
        """Advertise this wid's basis version on a PULL (delta mode),
        and the installed CTRL decision seq (``cs``) when this client
        rides a control sink -- the PS re-delivers the CTRL payload
        only while the stamp lags its newest decision."""
        if self.pull_mode == "delta":
            basis = self._basis.get(wid)
            if basis is not None:
                hdr["have"] = basis[0]
        if self.ctrl_sink is not None:
            hdr["cs"] = self.ctrl_sink.stamp
        return hdr

    def _decode_model(self, wid: int, header: dict, payload: bytes,
                      extra_len: int) -> Optional[np.ndarray]:
        """The model part of a MODEL reply -> float32 array, maintaining
        the basis cache.  ``extra_len`` is the trailing non-model payload
        (ASAGA's idx/alpha block).  Returns None on decode mismatch or
        basis cache miss -- the caller MUST fall back to a full pull; the
        basis is only ever replaced by a CRC-validated reconstruction or
        an authoritative full payload, never left wrong."""
        ts = int(header["ts"])
        wenc = header.get("wenc")
        if wenc is None or wenc == wiredelta.FULL:
            if wenc is None:  # legacy reply: model part is the payload head
                end = len(payload) - extra_len
                model_part = payload[:end] if extra_len else payload
            else:
                model_part = payload[: int(header.get("wlen", 0))]
            w = np.frombuffer(model_part, np.float32)
            if self.pull_mode == "delta":
                crc_hdr = header.get("crc")
                self._basis[wid] = (
                    ts, w,
                    int(crc_hdr) if crc_hdr is not None
                    else wiredelta.crc(model_part),
                )
            self.pull_wenc["full"] += 1
            self.pull_model_bytes += len(model_part)
            return w
        model_part = payload[: int(header.get("wlen", 0))]
        basis = self._basis.get(wid)
        crc_hdr = header.get("crc")
        w = wiredelta.decode(
            wenc, model_part, int(header.get("nnz", 0)),
            basis[1] if basis is not None else None,
            int(crc_hdr) if crc_hdr is not None else None,
            basis[2] if basis is not None else None,
        )
        if w is None:
            return None
        self._basis[wid] = (ts, w, int(crc_hdr))
        self.pull_wenc[wenc] = self.pull_wenc.get(wenc, 0) + 1
        self.pull_model_bytes += len(model_part)
        return w

    def _note_fenced(self, header: dict) -> bool:
        """Fold one REJECT_FENCED verdict: adopt the minted epoch when it
        is NEWER than ours (we were deposed and can self-heal -- the next
        op, stamped fresh, will be admitted) and return True; False means
        the SERVER is the stale party (a zombie) and cannot serve us."""
        self.fenced_replies += 1
        srv_ep = int(header.get("epoch", 0))
        if srv_ep > self.epoch:
            self.epoch = srv_ep
            return True
        return False

    def _process_pull_reply(self, wid: int, header: dict, payload: bytes,
                            make_hdr, extra_len_of, tr
                            ) -> Optional[Tuple[dict, bytes, np.ndarray]]:
        """Shared back half of a model pull: RELEASED/DONE handling,
        REJECT_FENCED self-healing, adoption orders, and decode with the
        ONE-full-re-pull fallback (basis cache miss, CRC disagreement --
        a full reply always decodes; never a wrong model).  Returns
        (header, payload, w), or None on RELEASED/DONE (``self.released``
        distinguishes them)."""
        fence_left = True
        fallback_left = True
        while True:
            op = header["op"]
            if op == "RELEASED":
                self.released = True
                return None
            if op == "DONE":
                return None
            if op == "ERR":
                # a refusing endpoint (a hot STANDBY answers the
                # training plane this way): surface as a dead endpoint
                # so loops pace and sharded facades re-resolve the map
                raise ConnectionError(
                    f"{self.endpoint} refused: {header.get('msg')!r}")
            if op == "REJECT_FENCED":
                # deposed basis: adopt the minted epoch and re-pull ONCE
                # with the fresh stamp (the current owner admits it); a
                # second fence, or a server whose epoch does not exceed
                # ours, is a zombie endpoint -- surface it
                if self._note_fenced(header) and fence_left:
                    fence_left = False
                    header, payload = self._traced_call(
                        tr, _trace.PULL_RTT,
                        self._proc_hdr(self._have_hdr(wid, make_hdr())),
                    )
                    continue
                raise FencedError(
                    f"fenced by {self.endpoint} at epoch "
                    f"{int(header.get('epoch', 0))} (client epoch "
                    f"{self.epoch})"
                )
            srv_ep = header.get("ep")
            if srv_ep is not None and int(srv_ep) > self.epoch:
                # replies advertise the server's current epoch: track it
                # so our next op is stamped current without a fence trip
                self.epoch = int(srv_ep)
            self._note_orders(header)
            w = self._decode_model(wid, header, payload,
                                   extra_len_of(header))
            if w is not None:
                return header, payload, w
            if not fallback_left:  # pragma: no cover - full always decodes
                break
            fallback_left = False
            self._basis.pop(wid, None)
            self.delta_fallbacks += 1
            header, payload = self._traced_call(
                tr, _trace.PULL_RTT, self._proc_hdr(make_hdr())
            )
        raise ConnectionError("PULL: full reply failed to decode")

    def _pull_model_rpc(self, wid: int, make_hdr, extra_len_of, tr
                        ) -> Optional[Tuple[dict, bytes, np.ndarray]]:
        """One negotiated model pull (request + reply + fallback)."""
        header, payload = self._traced_call(
            tr, _trace.PULL_RTT,
            self._proc_hdr(self._have_hdr(wid, make_hdr())),
        )
        return self._process_pull_reply(wid, header, payload, make_hdr,
                                        extra_len_of, tr)

    # ---------------------------------------------------- prefetched pull
    # The pipelined loop's pull prefetch: pull_start SENDS the next
    # PULL and returns (the request parks in the PS wave gate and the
    # reply accumulates in this socket's kernel buffer while the caller
    # computes); pull_finish receives and decodes it.  Single-threaded
    # by design -- the overlap lives in the socket, not in a thread --
    # and safe to retry: a PULL is idempotent and unstamped, so a
    # reconnect simply re-sends it.

    def pull_start(self, wid: int, tr=None) -> None:
        """Send the next PULL without waiting for the reply."""
        hdr = self._proc_hdr(self._have_hdr(wid, {"op": "PULL",
                                                  "wid": wid}))
        token = tr.rpc_begin(_trace.PULL_RTT) if tr is not None else None
        if tr is not None:
            _trace.set_current(None)
        # trailing slot: sent frame bytes, captured at send (see the
        # push-window entries)
        pending = [hdr, tr, token, 0]
        self._pending_pull = pending
        try:
            if self._sock is None:
                self._sock = self._dial()
            if tr is not None:
                _trace.set_current(tr.ctx)
            try:
                _send_msg(self._sock, hdr)
                pending[3] = _frame.last_sent_bytes()
            finally:
                if tr is not None:
                    _trace.set_current(None)
        except OSError:
            self._drop_sock()  # deferred: pull_finish re-dials + re-sends

    def pull_ready(self) -> bool:
        """True when the prefetched reply's first bytes are already in
        the kernel buffer (the prefetch fully hid the pull)."""
        if self._sock is None:
            return False
        if isinstance(self._sock, _shmring.ShmSocket):
            # ring bytes never show on the retained TCP fd; ask the
            # ring's counters instead (same zero-wait semantics)
            return self._sock.readable()
        import select

        try:
            return bool(select.select([self._sock], [], [], 0.0)[0])
        except (OSError, ValueError):
            return False

    def pull_finish(self, wid: int
                    ) -> Optional[Tuple[int, np.ndarray, float, bool]]:
        """Receive the prefetched PULL's reply; same returns as
        :meth:`pull`.  A dead connection re-dials and re-sends the
        pending request under the retry policy."""
        pending = self._pending_pull
        if pending is None:
            raise RuntimeError("pull_finish without pull_start")
        hdr, tr, token = pending[0], pending[1], pending[2]

        def attempt() -> Tuple[dict, bytes]:
            try:
                if self._sock is None:
                    self._sock = self._dial()
                    if tr is not None:
                        _trace.set_current(tr.ctx)
                    try:
                        _send_msg(self._sock, hdr)
                        pending[3] = _frame.last_sent_bytes()
                    finally:
                        if tr is not None:
                            _trace.set_current(None)
                return _recv_msg(self._sock)
            except OSError:
                self._drop_sock()
                raise

        try:
            header, payload = self.retry.call(attempt,
                                              endpoint=self.endpoint)
        finally:
            self._pending_pull = None
        if tr is not None and token is not None:
            tr.rpc_end(token,
                       bytes=pending[3] + _frame.last_recv_bytes())
        got = self._process_pull_reply(
            wid, header, payload,
            lambda: {"op": "PULL", "wid": wid}, lambda _h: 0, tr,
        )
        if got is None:
            return None
        header, _payload, w = got
        if tr is not None:
            tr.set_model_version(int(header["ts"]))
        return (int(header["ts"]), w, float(header["avg_delay_ms"]),
                bool(header["calibrated"]))

    def pull(self, wid: int, tr=None
             ) -> Optional[Tuple[int, np.ndarray, float, bool]]:
        """Returns (ts, w, avg_delay_ms, calibrated); None when DONE or
        when this client's wid was RELEASED (check ``self.released``).
        ``tr`` (an UpdateTrace) records this pull's round trip as a
        pull.rtt span and propagates the trace context on the wire.

        In ``delta`` pull mode the request advertises the cached basis
        version (``have``) and the reply may be NOT_MODIFIED (zero model
        payload) or a byte-exact XOR delta; a decode mismatch or basis
        cache miss re-pulls FULL -- never a wrong model."""
        got = self._pull_model_rpc(
            wid, lambda: {"op": "PULL", "wid": wid}, lambda _h: 0, tr
        )
        if got is None:
            return None
        header, _payload, w = got
        if tr is not None:
            tr.set_model_version(int(header["ts"]))
        return (int(header["ts"]), w, float(header["avg_delay_ms"]),
                bool(header["calibrated"]))

    def subscribe(self, wid: int = 0, extra: Optional[dict] = None
                  ) -> Optional[Tuple[int, np.ndarray, int, int,
                                      float, bool]]:
        """Serving-tier snapshot subscription: one ``have=``-negotiated
        SUBSCRIBE round trip (NOT_MODIFIED / XDELTA / FULL, CRC-gated,
        full-pull fallback -- the same basis-cache machinery as delta
        PULLs, keyed by ``wid``; replicas pass their replica id).

        Returns ``(ts, w, clock, k, age_ms, done)``: the served version
        and model, the PS merge clock and accepted-update count at reply
        time, the served version's freshness age in ms (0 while it is
        still the current model), and whether training has finished.
        Unlike :meth:`pull` this never parks in the wave gate and keeps
        working after DONE.  ``extra`` merges additional header fields
        into every attempt (relaycast advertises its relay port as
        ``rport`` here, which registers it for the PS's offer path)."""
        def mk() -> dict:
            hdr = {"op": "SUBSCRIBE", "wid": wid}
            if extra:
                hdr.update(extra)
            return hdr

        got = self._pull_model_rpc(wid, mk, lambda _h: 0, None)
        if got is None:
            return None  # RELEASED/DONE headers never come from SUBSCRIBE
        header, _payload, w = got
        ts = int(header["ts"])
        return (ts, w, int(header.get("clock", ts)),
                int(header.get("k", 0)),
                float(header.get("age_ms", 0.0)),
                bool(header.get("done", False)))

    @staticmethod
    def _sparse_grad_enc(g: np.ndarray) -> Optional[Tuple[int, bytes]]:
        """(idx u32, val f32) pair encoding when it beats the dense d*4
        bytes (rcv1-class gradients touch only the sampled rows' columns);
        None when dense is smaller."""
        (nz,) = np.nonzero(g)
        if nz.size * 8 >= g.shape[0] * 4:
            return None
        return nz.size, (nz.astype(np.uint32).tobytes()
                         + g[nz].astype(np.float32).tobytes())

    def _encode_push(self, wid: int, ts: int, g: np.ndarray,
                     sparse: bool, diff: Optional[np.ndarray], tr
                     ) -> Tuple[dict, bytes, List[dict], dict, List[list]]:
        """Shared encode/stamp front half of :meth:`push` and
        :meth:`push_start`: returns ``(header, payload, spans, pl_delta,
        cv_wire)`` with the piggybacks already attached to the header."""
        t_enc0 = _trace.now_ms() if tr is not None else 0.0
        g = np.asarray(g, np.float32)
        # ASAGA pushes ride their own verb so fault schedules can tell the
        # two solvers' streams apart (the PS treats both identically)
        op = "PUSH_SAGA" if diff is not None else "PUSH"
        enc = self._sparse_grad_enc(g) if sparse else None
        if enc is not None:
            nnz, payload = enc
            hdr = {"op": op, "wid": wid, "ts": ts,
                   "enc": "sparse", "nnz": nnz}
        else:
            hdr, payload = {"op": op, "wid": wid, "ts": ts}, None
            if diff is None and self.push_codec != wirecodec.OFF:
                # quantize with error feedback (dense ASGD only: sparse
                # already beat dense above, and ASAGA's history scalars
                # must be exact).  encode_grad returns None for any
                # input it cannot encode safely (non-finite, fp16
                # overflow) -- that push ships raw and the residual
                # simply rides to the next quantized one.
                q = wirecodec.encode_grad(g, self.push_codec,
                                          self._ef.get(wid))
                if q is not None:
                    qhdr, payload, new_err = q
                    self._ef[wid] = new_err
                    hdr.update(qhdr)
            if payload is None:
                payload = g.tobytes()
        if diff is not None:
            payload += np.asarray(diff, np.float32).tobytes()
        self.bytes_pushed += len(payload)
        if tr is not None:
            tr.add(_trace.PUSH_WAIT, t_enc0, _trace.now_ms())
        spans: List[dict] = []
        if self.recorder is not None:
            # the PUSH piggyback: completed spans (a previous traced
            # update's push.rtt, this one's pull.rtt/compute/push.wait)
            # ship in the header -- one drain per logical push; retries
            # re-send the same header, and the PS dedup window keeps a
            # re-applied push from double-folding them
            spans = self.recorder.drain_wire()
            if spans:
                hdr["spans"] = spans
        pl_delta: dict = {}
        if self.pl_stats is not None:
            # pipeline-counter piggyback, same discipline as spans: ship
            # the unshipped delta; the PS folds it once (dedup'd retries
            # never reach the handler)
            pl_delta = self.pl_stats.take_wire()
            if pl_delta:
                hdr["pl"] = pl_delta
        cv_wire: List[list] = []
        if self.cv_buf is not None:
            # convergence-sample piggyback: drain the unshipped tail (a
            # bounded slice; the rest rides later pushes)
            cv_wire = self.cv_buf.take_wire()
            if cv_wire:
                hdr["cv"] = cv_wire
        return hdr, payload, spans, pl_delta, cv_wire

    def _requeue_piggybacks(self, spans: List[dict], pl_delta: dict,
                            cv_wire: Optional[List[list]] = None) -> None:
        """A push whose whole retry budget was spent must not silently eat
        its piggybacked telemetry: spans, counter deltas, and convergence
        samples go back to ride the next push/BYE."""
        if spans and self.recorder is not None:
            self.recorder.requeue(spans)
        if pl_delta and self.pl_stats is not None:
            self.pl_stats.merge_back(pl_delta)
        if cv_wire and self.cv_buf is not None:
            self.cv_buf.merge_back(cv_wire)

    def push(self, wid: int, ts: int, g: np.ndarray,
             sparse: bool = False, diff: Optional[np.ndarray] = None,
             tr=None) -> Tuple[bool, bool]:
        """Returns (accepted, run_done).  ``diff`` (ASAGA candidate history
        scalars) rides after the gradient when given.  ``tr`` records this
        push's encode time (push.wait) and round trip (push.rtt); any
        completed spans in the client's recorder piggyback on the header
        either way."""
        hdr, payload, spans, pl_delta, cv_wire = self._encode_push(
            wid, ts, g, sparse, diff, tr
        )
        # stamp ONCE: retries re-send the same (sid, seq), so a push whose
        # ACK was lost is answered from the PS dedup window, not re-applied
        try:
            header, _ = self._traced_call(
                tr, _trace.PUSH_RTT,
                self.session.stamp(self._proc_hdr(hdr)), payload,
            )
        except BaseException:
            self._requeue_piggybacks(spans, pl_delta, cv_wire)
            raise
        if header.get("op") == "REJECT_FENCED":
            # this gradient was computed under a deposed epoch: it is
            # DROPPED (the same loss as a taw rejection), and with the
            # adopted epoch the next round is admitted
            if self._note_fenced(header):
                return False, False
            raise FencedError(
                f"push fenced by zombie {self.endpoint} (epoch "
                f"{int(header.get('epoch', 0))} <= ours {self.epoch})"
            )
        if header.get("op") == "ERR":
            # a refusing endpoint (standby / malformed push): dead-
            # endpoint semantics, same as the pull path
            raise ConnectionError(
                f"push refused by {self.endpoint}: "
                f"{header.get('msg')!r}")
        if header.get("released"):
            self.released = True
        return bool(header.get("accepted")), bool(header.get("done"))

    # ------------------------------------------------- windowed push pipe
    # The pipelined sender's wire window: push k+1 goes OUT before push
    # k's ACK returns, so per-update push cost drops from a full RTT to
    # the send itself.  The server already supports this shape -- its
    # per-connection loop handles frames in order and replies in order --
    # so ACKs pair with pushes FIFO.  Exactly-once survives every fault:
    # each entry is stamped once, and on any connection error the whole
    # unacked window is REPLAYED on the fresh socket (the PS dedup window
    # re-ACKs already-applied entries instead of re-merging them).  These
    # concurrency contract: any number of calls from ONE sending thread
    # (push_start) plus ONE reaping thread (push_finish/push_abandon);
    # the window lock serializes sends and reconnect/replay, receives
    # run outside it (TCP full duplex).

    def push_start(self, wid: int, ts: int, g: np.ndarray,
                   sparse: bool = False,
                   diff: Optional[np.ndarray] = None, tr=None) -> None:
        """Encode, stamp, window, and SEND one push without waiting for
        its ACK.  A send error (or an already-dead socket) is deferred:
        the entry stays in the window and :meth:`push_finish`'s
        reconnect replays it."""
        hdr, payload, spans, pl_delta, cv_wire = self._encode_push(
            wid, ts, g, sparse, diff, tr
        )
        token = tr.rpc_begin(_trace.PUSH_RTT) if tr is not None else None
        if tr is not None:
            _trace.set_current(None)  # _send_entry scopes the context
        # trailing slot: this entry's sent frame bytes (captured at send,
        # so the rtt span's `bytes` pairs OUR send with OUR reply even
        # though the single-threaded loop interleaves other frames)
        entry = [self.session.stamp(self._proc_hdr(hdr)), payload, tr,
                 token, spans, pl_delta, cv_wire, 0]
        with self._win_lock:
            self._push_window.append(entry)
            if self._sock is not None:
                try:
                    self._send_entry(entry)
                except OSError:
                    self._drop_sock()  # reaper reconnects and replays

    def _send_entry(self, entry) -> None:
        hdr, payload, tr = entry[0], entry[1], entry[2]
        if tr is not None:
            _trace.set_current(tr.ctx)  # the tc header for THIS push
        try:
            _send_msg(self._sock, hdr, payload)
            entry[7] = _frame.last_sent_bytes()
        finally:
            if tr is not None:
                _trace.set_current(None)

    def _replay_window(self) -> None:
        """Re-send every unacked push on the (fresh) socket, oldest
        first, same stamps: applied-but-unACKed entries are answered from
        the PS dedup window, lost ones are applied now -- FIFO ACK
        pairing is preserved either way."""
        for entry in self._push_window:
            self._send_entry(entry)

    def inflight_pushes(self) -> int:
        return len(self._push_window)

    def push_finish(self) -> Tuple[bool, bool]:
        """Receive the OLDEST in-flight push's ACK (FIFO), under the
        retry policy: a dead connection is re-dialed and the unacked
        window replayed before the next receive attempt.  Returns
        (accepted, run_done)."""

        def attempt() -> Tuple[dict, bytes]:
            try:
                with self._win_lock:
                    sock = self._sock
                    if sock is None:
                        sock = self._sock = self._dial()
                        self._replay_window()
                # recv OUTSIDE the window lock: the sender keeps sending
                # while this blocks (full duplex)
                return _recv_msg(sock)
            except OSError:
                self._drop_sock()
                raise

        header, _ = self.retry.call(attempt, endpoint=self.endpoint)
        entry = self._push_window.popleft()
        _hdr, _payload, tr, token, _spans, _pl, _cv, sent_bytes = entry
        if tr is not None and token is not None:
            tr.rpc_end(token,
                       bytes=sent_bytes + _frame.last_recv_bytes())
        if header.get("op") == "REJECT_FENCED":
            # a windowed entry stamped under a deposed epoch (typically a
            # replay onto a fenced range's replacement): dropped, epoch
            # adopted -- later push_start calls stamp the current epoch.
            # Judge against THIS ENTRY'S stamp, not self.epoch: with >= 2
            # stale entries in flight, the first fence already advanced
            # self.epoch, and comparing the second reply against the
            # advanced value would misread the healthy replacement as a
            # zombie (each stale entry is rejected exactly once, that is
            # the design -- only a server whose epoch does not exceed
            # what WE stamped on the op is actually stale itself).
            self.fenced_replies += 1
            srv_ep = int(header.get("epoch", 0))
            if srv_ep > self.epoch:
                self.epoch = srv_ep
            if srv_ep > int(entry[0].get("ep", 0) or 0):
                return False, False
            raise FencedError(
                f"push fenced by zombie {self.endpoint} (epoch "
                f"{srv_ep} <= op stamp {entry[0].get('ep')})"
            )
        if header.get("op") == "ERR":
            raise ConnectionError(
                f"windowed push refused by {self.endpoint}: "
                f"{header.get('msg')!r}")
        if header.get("released"):
            self.released = True
        return bool(header.get("accepted")), bool(header.get("done"))

    def push_abandon(self) -> int:
        """Drop every in-flight push (the window's whole retry budget is
        spent -- the serial loop's error path loses its round the same
        way), requeueing piggybacked telemetry.  Returns the number of
        pushes abandoned."""
        with self._win_lock:
            n = len(self._push_window)
            while self._push_window:
                entry = self._push_window.popleft()
                self._requeue_piggybacks(entry[4], entry[5], entry[6])
            self._drop_sock()
        return n

    def pull_saga(self, wid: int, n_p: int, tr=None) -> Optional[
        Tuple[int, np.ndarray, np.ndarray, np.ndarray, int, float, bool]
    ]:
        """ASAGA pull: the PS samples this worker's rows and ships their
        current history scalars with the model (the reference's sampledMap).
        Returns (ts, w, idx, alpha_sel, n_valid, avg_delay_ms, calibrated)
        or None when DONE."""
        got = self._pull_model_rpc(
            wid, lambda: {"op": "PULL_SAGA", "wid": wid, "n_p": n_p},
            lambda h: 8 * int(h["cap"]), tr,
        )
        if got is None:
            return None
        header, payload, w = got
        if tr is not None:
            tr.set_model_version(int(header["ts"]))
        # the ASAGA extra block (idx, alpha) always rides AFTER the model
        # part, whatever its encoding; its offset is the payload tail
        cap = int(header["cap"])
        tail = len(payload) - 8 * cap
        idx = np.frombuffer(payload[tail: tail + 4 * cap], np.uint32)
        alpha_sel = np.frombuffer(payload[tail + 4 * cap:], np.float32)
        return (int(header["ts"]), w, idx, alpha_sel, int(header["n_valid"]),
                float(header["avg_delay_ms"]), bool(header["calibrated"]))

    def push_saga(self, wid: int, ts: int, g: np.ndarray, diff: np.ndarray,
                  sparse: bool = False, tr=None) -> Tuple[bool, bool]:
        """ASAGA push: gradient + candidate history scalars for the sampled
        rows (committed by the PS only on accept).  Returns (accepted, done).
        """
        return self.push(wid, ts, g, sparse=sparse, diff=diff, tr=tr)

    def snapshots(self) -> Tuple[List[float], np.ndarray]:
        header, payload = self._call_raw({"op": "SNAPSHOTS"})
        W = np.frombuffer(payload, np.float32).reshape(header["shape"])
        return list(header["times"]), W

    def send_eval(self, wid: int, losses: np.ndarray) -> None:
        self._call_raw(self.session.stamp({"op": "EVAL_RESULT", "wid": wid}),
                       np.asarray(losses, np.float64).tobytes())

    def bye(self) -> None:
        try:
            if self._pending_pull is not None:
                # a prefetched PULL is still parked in the PS wave gate:
                # its MODEL reply would arrive (possibly after a ~1 s
                # starvation-fallback wait) ahead of any BYE ACK.  Just
                # drop the connection -- the PS treats EOF as goodbye,
                # and this client's telemetry rides its sibling push
                # connection's BYE.
                self._drop_sock()
                return
            if self._sock is not None:
                hdr: dict = {"op": "BYE"}
                if self.recorder is not None:
                    # last drain: the final traced update's push.rtt has no
                    # later PUSH to ride, so it leaves with the goodbye
                    spans = self.recorder.drain_wire()
                    if spans:
                        hdr["spans"] = spans
                if self.pl_stats is not None:
                    pl_delta = self.pl_stats.take_wire()
                    if pl_delta:
                        hdr["pl"] = pl_delta
                if self.cv_buf is not None:
                    # the final unshipped convergence samples leave with
                    # the goodbye, like the last traced update's spans
                    cv_wire = self.cv_buf.take_wire()
                    if cv_wire:
                        hdr["cv"] = cv_wire
                _send_msg(self._sock, hdr)
                _recv_msg(self._sock)
        except (ConnectionError, OSError):
            pass
        self._drop_sock()


def run_worker_process(
    host: str,
    port: int,
    wids: List[int],
    shards: Dict[int, object],
    cfg,
    d: int,
    n: int,
    eval_wid: Optional[int] = None,
    deadline_s: float = 600.0,
    algo: str = "asgd",
    shard_factory=None,
    proc_token: Optional[str] = None,
) -> Dict[int, int]:
    """Worker-process main loop: one thread per owned logical worker, each
    pulling models and pushing gradients until the PS says DONE.

    ``shards``: wid -> Shard (device-resident, this process's chips).
    Returns per-wid gradient counts.  When ``eval_wid`` is set, after DONE
    this process scores the PS's snapshot stack over ALL its shards and
    pushes one EVAL_RESULT (the distributed optVars evaluation).

    ``algo="asaga"``: the PS samples and ships (idx, alpha) with each model
    (it owns the history table); the worker runs the history-corrected
    gradient step and pushes candidate scalars back with the gradient.

    Elastic plane (``parallel/supervisor.py``): this process HELLOs the PS
    with ``proc_token`` + its wids + pid, and every PULL/PUSH carries the
    token.  When the PS's supervisor re-homes a dead peer's shard here, the
    adoption order arrives on a PULL reply; ``shard_factory(wid)`` builds
    the orphan shard locally (datasets are seed-deterministic or disk-
    loadable, the DCN analog of lineage recomputation) and a fresh loop
    thread starts serving it.  A thread whose wid is reclaimed by a
    rejoining process is told RELEASED and stands down.  With
    ``shard_factory=None`` adoption orders are ignored (classic behavior).

    Pipelining (``async.pipeline.depth`` / ``SolverConfig.pipeline_depth``):
    depth 0 runs the classic serial loop below, byte- and step-identical;
    depth >= 1 runs :func:`pipelined_worker_loop` -- prefetched pulls on a
    second connection, a bounded in-flight push sender, and the
    host<->device transfers staged off the compute thread.  ASAGA always
    runs serial (PS-side sampling requires pull->push alternation).
    """
    import jax

    from asyncframework_tpu.engine.straggler import DelayModel
    from asyncframework_tpu.ops import steps

    proc_token = proc_token or f"{socket.gethostname()}-{os.getpid()}"
    # distributed tracing (metrics/trace.py): one sampling recorder + span
    # ring per worker process, shared by its loop threads.  With
    # async.trace.sample = 0 the recorder is None and the hot path does no
    # tracing work at all (and frames stay byte-identical).
    _rec = _trace.TraceRecorder()
    recorder = _rec if _rec.enabled else None
    sparse = any(hasattr(s, "cols") for s in shards.values())
    if algo == "asaga":
        step = (steps.make_saga_dcn_sparse_worker_step(d) if sparse
                else steps.make_saga_dcn_worker_step())
    else:
        step = (steps.make_sparse_asgd_worker_step(cfg.batch_rate, d)
                if sparse
                else steps.make_asgd_worker_step(cfg.batch_rate, cfg.loss))
    delay_model = DelayModel(cfg.coeff, cfg.num_workers, cfg.seed)
    counts = {wid: 0 for wid in wids}
    stop = threading.Event()
    calibrated_once = threading.Event()
    # pipelined update loop (async.pipeline.depth): 0 = the classic
    # serial pull -> compute -> push loop below, untouched (byte- and
    # step-identical); >= 1 = prefetched pulls on a second connection +
    # a bounded in-flight push sender (at most `depth` unacked pushes).
    pipe_depth = getattr(cfg, "pipeline_depth", None)
    if pipe_depth is None:
        from asyncframework_tpu.conf import PIPELINE_DEPTH, global_conf

        pipe_depth = global_conf().get(PIPELINE_DEPTH)
    pipe_depth = max(0, int(pipe_depth))
    if algo == "asaga":
        # the PS samples per pull and holds ONE pending (idx, alpha) slot
        # per wid: a prefetched pull would clobber the slot the in-flight
        # push must commit against.  ASAGA keeps the strict pull->push
        # alternation; pipelining is an ASGD-path capability.
        pipe_depth = 0
    pl_stats = _PipelineStats() if pipe_depth > 0 else None
    # mesh compute plane (async.mesh.devices / SolverConfig.mesh_devices):
    # 0 = the classic single-device gradient step below, byte- and step-
    # identical; >= 2 = each logical worker computes its mini-batch
    # gradient batch-parallel over a LOCAL dp mesh -- shard rows are
    # padded+sharded into HBM once per run (pad_and_shard), per-device
    # partial gradients psum-reduce on the mesh, and the loop still
    # pushes ONE fused gradient per step (the wire cannot tell).  A conf
    # asking for more chips than the rig has clamps (make_mesh clamp=
    # True, logged); fewer than 2 effective devices, or sparse
    # (padded-ELL) shards, degrade to the serial path -- an operator
    # overshooting a knob must cost a warning, never the worker daemon.
    mesh_devices = getattr(cfg, "mesh_devices", None)
    if mesh_devices is None:
        from asyncframework_tpu.conf import MESH_DEVICES, global_conf

        mesh_devices = global_conf().get(MESH_DEVICES)
    mesh_devices = max(0, int(mesh_devices))
    worker_mesh = None
    mesh_step = None
    mesh_replicated = None
    if mesh_devices:
        import logging as _logging

        _mlog = _logging.getLogger(__name__)
        from asyncframework_tpu.parallel.mesh import (
            make_mesh,
            replicated_sharding,
        )

        if sparse:
            _mlog.warning(
                "async.mesh.devices=%d ignored: sparse (padded-ELL) "
                "shards run the single-device step", mesh_devices,
            )
        else:
            # make_mesh owns the clamp: an over-ask logs the documented
            # "requested N but only M available; clamping" warning there
            mesh = make_mesh(mesh_devices, clamp=True)
            if mesh.devices.size < 2:
                _mlog.warning(
                    "async.mesh.devices=%d yields a %d-device mesh; "
                    "running the single-device step", mesh_devices,
                    mesh.devices.size,
                )
            else:
                worker_mesh = mesh
                mesh_replicated = replicated_sharding(worker_mesh)
                if algo == "asaga":
                    mesh_step = steps.make_mesh_saga_dcn_worker_step(
                        worker_mesh
                    )
                else:
                    mesh_step = steps.make_mesh_asgd_worker_step(
                        cfg.batch_rate, worker_mesh, cfg.loss
                    )
    # one-time per-wid mesh placement (HBM-resident across the run);
    # built lazily under its own lock so adopted shards place too
    mesh_lock = threading.Lock()
    mesh_placed: Dict[int, tuple] = {}

    def mesh_place(wid: int, shard):
        """Row-shard this wid's batch over the worker mesh ONCE."""
        if worker_mesh is None:
            return None
        with mesh_lock:
            got = mesh_placed.get(wid)
        if got is not None:
            return got
        from asyncframework_tpu.parallel.mesh import pad_and_shard

        Xs, ys, vs, _n = pad_and_shard(
            worker_mesh, np.asarray(shard.X), np.asarray(shard.y)
        )
        with mesh_lock:
            return mesh_placed.setdefault(wid, (Xs, ys, vs))
    # convergence telemetry (async.convergence.sample /
    # SolverConfig.conv_sample): every Nth update per logical worker
    # evaluates the shard's mean loss (one extra jitted eval against the
    # model the gradient was computed on) plus the gradient norm, and
    # buffers the (version, loss, grad_norm) sample for the next PUSH
    # header's ``cv`` entry -- the PS folds them into the process-global
    # loss-vs-wallclock / loss-vs-version curves (metrics/timeseries.py).
    # 0 = off: no eval, no header field, byte-identical wire.
    conv_every = getattr(cfg, "conv_sample", None)
    if conv_every is None:
        from asyncframework_tpu.conf import CONV_SAMPLE, global_conf

        conv_every = global_conf().get(CONV_SAMPLE)
    conv_every = max(0, int(conv_every))
    cv_buf = None
    conv_eval = None
    if conv_every > 0:
        from asyncframework_tpu.metrics.timeseries import ConvergenceBuffer

        cv_buf = ConvergenceBuffer()
        conv_eval = (steps.make_sparse_trajectory_loss_eval() if sparse
                     else steps.make_trajectory_loss_eval(
                         getattr(cfg, "loss", "least_squares")))

    def conv_sample(shard, w_dev, ts, g_host: np.ndarray) -> None:
        """One convergence sample: shard mean loss at the pulled model +
        gradient norm, buffered for the PUSH piggyback.  Telemetry must
        never break the update loop.  Against a sharded PS group ``ts``
        is the version VECTOR -- the sample is stamped with the primary's
        component (its clock drives the convergence curves)."""
        try:
            if sparse:
                sums = conv_eval(shard.cols, shard.vals, shard.y,
                                 w_dev[None, :])
            else:
                sums = conv_eval(shard.X, shard.y, w_dev[None, :])
            loss = (float(np.asarray(sums)[0])
                    / max(1, int(shard.y.shape[0])))
            ver = int(ts[0]) if isinstance(ts, (tuple, list)) else int(ts)
            cv_buf.add(ver, loss, float(np.linalg.norm(g_host)))
        except Exception:  # noqa: BLE001
            pass

    # sharded PS group (parallel/shardgroup.py): resolved from the HELLO
    # WELCOME below.  None = the classic single PS -- every client below
    # is a stock PSClient and the wire is byte-identical.  The WELCOME
    # also seeds the fencing epochs (async.fence.enabled on the servers;
    # absent = 0 = legacy, clients stamp nothing).
    smap = None
    smap_epochs: Optional[List[int]] = None
    ps_epoch = 0
    # adaptive control plane: built from the WELCOME's CTRL payload when
    # the PS runs a controller (async.control.enabled); every client of
    # this process shares it, and the pipelined loops read the live
    # depth target off it each iteration.  None = control off -- no
    # ``cs`` stamps, byte-identical wire.
    ctrl_sink = None

    def make_client(recorder=None, pl_stats=None, cv_buf=None):
        """One PS-facing client: a ShardedPSClient fan-out facade when
        the HELLO resolved a shard map, the classic PSClient otherwise.
        Same surface either way -- the loops below cannot tell."""
        if smap is not None:
            from asyncframework_tpu.parallel.shardgroup import (
                ShardedPSClient,
            )

            return ShardedPSClient(
                smap, proc=proc_token, recorder=recorder,
                pull_mode=getattr(cfg, "pull_mode", None),
                pl_stats=pl_stats, cv_buf=cv_buf, epochs=smap_epochs,
                ctrl_sink=ctrl_sink,
            )
        return PSClient(host, port, proc=proc_token, recorder=recorder,
                        pull_mode=getattr(cfg, "pull_mode", None),
                        pl_stats=pl_stats, cv_buf=cv_buf, epoch=ps_epoch,
                        push_codec=getattr(cfg, "push_codec", None),
                        ctrl_sink=ctrl_sink)

    # elastic adoption bookkeeping: which wids this process serves (own +
    # adopted), and every loop thread ever started (joined at the end)
    group_lock = threading.Lock()
    active_wids = set(wids)
    threads: List[threading.Thread] = []

    def shard_dev(shard):
        return (shard.cols if sparse else shard.X).device

    def run_step(shard, w_dev, key, placed=None):
        """Dense/sparse/mesh ASGD: (g, new_key)."""
        if placed is not None:
            Xs, ys, vs = placed
            return mesh_step(Xs, ys, vs, w_dev, key)
        if sparse:
            return step(shard.cols, shard.vals, shard.y, w_dev, key)
        return step(shard.X, shard.y, w_dev, key)

    def run_saga_step(shard, w_dev, idx_dev, alpha_dev, n_valid,
                      placed=None):
        """Dense/sparse/mesh DCN-ASAGA: (g, diff_sel)."""
        if placed is not None:
            Xs, ys, _vs = placed
            return mesh_step(Xs, ys, w_dev, idx_dev, alpha_dev, n_valid)
        if sparse:
            return step(shard.cols, shard.vals, shard.y, w_dev, idx_dev,
                        alpha_dev, n_valid)
        return step(shard.X, shard.y, w_dev, idx_dev, alpha_dev, n_valid)

    def put_model(w_host, dev, placed):
        """Host model -> device(s): replicated over the mesh when this
        wid computes mesh-parallel, the classic single-device put
        otherwise."""
        if placed is not None:
            return jax.device_put(w_host, mesh_replicated)
        return jax.device_put(w_host, dev)

    # warm every owned shard's executable BEFORE the first pull
    # (first-iteration-blocking parity): without this, compile skew across
    # worker threads lets fast workers drive the run to done while slow ones
    # are still in XLA -- their first push then lands post-done and drops
    import jax.numpy as jnp

    warmed = set()
    for wid in wids:
        shard = shards[wid]
        dev = shard_dev(shard)
        n_p = int(shard.y.shape[0])
        shape = (shard.cols if sparse else shard.X).shape
        placed = mesh_place(wid, shard)  # one-time HBM placement per wid
        wkey = (shape, "mesh" if placed is not None else dev)
        if wkey in warmed:
            continue
        warmed.add(wkey)
        w0 = put_model(np.zeros(d, np.float32), dev, placed)
        if algo == "asaga":
            cap = steps.sparse_step_capacity(cfg.batch_rate, n_p)
            g0, _ = run_saga_step(
                shard, w0,
                np.zeros(cap, np.int32) if placed is not None
                else jax.device_put(jnp.zeros(cap, jnp.int32), dev),
                np.zeros(cap, np.float32) if placed is not None
                else jax.device_put(jnp.zeros(cap, jnp.float32), dev),
                np.int32(0), placed=placed,
            )
        else:
            key0 = (jax.random.PRNGKey(0) if placed is not None
                    else jax.device_put(jax.random.PRNGKey(0), dev))
            g0, _ = run_step(shard, w0, key0, placed=placed)
        g0.block_until_ready()

    def adopt(orphan: int) -> None:
        """Adoption order from the PS: materialize the dead peer's shard
        locally and start serving it (idempotent -- orders are re-delivered
        until the first pull for the orphan lands)."""
        with group_lock:
            if orphan in active_wids:
                return
            active_wids.add(orphan)
        try:
            built = shard_factory(orphan)  # device placement: off the lock
        except Exception:
            with group_lock:
                active_wids.discard(orphan)
            return
        with group_lock:
            # shared-dict writes under the lock: the end-of-run eval reads
            # `shards` under it too, and a late adoption racing that read
            # must not blow up the iteration
            shards[orphan] = built
            counts.setdefault(orphan, 0)
        spawn(orphan)

    def worker_loop(wid: int) -> None:
        shard = shards[wid]
        dev = shard_dev(shard)
        placed = mesh_place(wid, shard)  # None = single-device step
        key = None
        if algo != "asaga":  # ASAGA samples PS-side; workers need no chain
            key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid)
            key = (jax.device_put(key, mesh_replicated)
                   if placed is not None else jax.device_put(key, dev))
        deadline = time.monotonic() + deadline_s
        cl: Optional[PSClient] = None
        try:
            while not stop.is_set() and time.monotonic() < deadline:
                try:
                    if cl is None:
                        cl = make_client(recorder=recorder, cv_buf=cv_buf)
                    # per-update sampling decision: a traced update's RPCs
                    # carry the trace context on the wire and its lifecycle
                    # spans (pull.rtt/compute/push.wait/push.rtt) land in
                    # the recorder ring for the PUSH piggyback
                    tr = (recorder.start_update(wid)
                          if recorder is not None else None)
                    # per-RPC transport faults (reconnect, backoff, jitter,
                    # breaker) are the client's RetryPolicy's problem now;
                    # PUSH retries are exactly-once-applied via the PS
                    # dedup window, so nothing here needs to reason about
                    # "did my gradient land"
                    if algo == "asaga":
                        got = cl.pull_saga(wid, int(shard.y.shape[0]),
                                           tr=tr)
                    else:
                        got = cl.pull(wid, tr=tr)
                    if got is None:
                        break  # DONE, or this wid was RELEASED to a rejoiner
                    if shard_factory is not None:
                        for orphan in cl.take_orders():
                            adopt(orphan)
                    if algo == "asaga":
                        (ts, w_host, idx, alpha_sel, n_valid, avg_ms,
                         calibrated) = got
                    else:
                        ts, w_host, avg_ms, calibrated = got
                    if calibrated and not calibrated_once.is_set():
                        delay_model.calibrate(avg_ms)
                        calibrated_once.set()
                    # compute span: straggler delay + host->device put +
                    # gradient step + device->host readback -- everything
                    # between the pull reply and the push encode
                    t_c0 = _trace.now_ms() if tr is not None else 0.0
                    dly = delay_model.delay_ms(wid) if calibrated else 0.0
                    if dly > 0:
                        time.sleep(dly / 1e3)
                    w_dev = put_model(w_host, dev, placed)
                    counts[wid] += 1
                    if algo == "asaga":
                        idx32 = idx.astype(np.int32)
                        g, diff = run_saga_step(
                            shard, w_dev,
                            idx32 if placed is not None
                            else jax.device_put(idx32, dev),
                            alpha_sel if placed is not None
                            else jax.device_put(alpha_sel, dev),
                            np.int32(n_valid), placed=placed,
                        )
                        g_host = np.asarray(g)
                        diff_host = np.asarray(diff)
                        if tr is not None:
                            tr.add(_trace.COMPUTE, t_c0, _trace.now_ms())
                        if cv_buf is not None and \
                                counts[wid] % conv_every == 0:
                            # mesh path: the shard-loss eval runs on the
                            # shard's own device -- hand it the HOST
                            # model, not the mesh-replicated handle
                            # (committed-device mismatch would raise)
                            conv_sample(shard,
                                        w_host if placed is not None
                                        else w_dev, ts, g_host)
                        _accepted, done = cl.push_saga(
                            wid, ts, g_host, diff_host, sparse=sparse,
                            tr=tr,
                        )
                    else:
                        g, new_key = run_step(shard, w_dev, key,
                                              placed=placed)
                        key = new_key
                        g_host = np.asarray(g)  # the push IS the readback
                        if tr is not None:
                            tr.add(_trace.COMPUTE, t_c0, _trace.now_ms())
                        if cv_buf is not None and \
                                counts[wid] % conv_every == 0:
                            conv_sample(shard,
                                        w_host if placed is not None
                                        else w_dev, ts, g_host)
                        _accepted, done = cl.push(wid, ts, g_host,
                                                  sparse=sparse, tr=tr)
                    # flight-recorder breadcrumb: the last acked push
                    # rides the dump, so a SIGKILLed worker's post-mortem
                    # ends at (wid, basis version, cumulative count) the
                    # PS-side ledgers can be checked against.  ``ts`` is
                    # an int against a single PS and a per-shard vector
                    # against a sharded group -- pass it through as-is
                    # (the dump serializer stringifies anything exotic)
                    _flight.note("push", wid=wid, ts=ts,
                                 acc=bool(_accepted), n=counts[wid])
                    if done:
                        break
                except (ConnectionError, OSError):
                    # the RPC's whole retry budget is spent (RetryError) or
                    # the endpoint's breaker is open (CircuitOpenError): the
                    # PS is restarting from checkpoint or the DCN is down
                    # for longer than one policy window.  Pace and re-enter
                    # -- the client reconnects lazily, and a restarted PS
                    # has no pending state for the lost round anyway.
                    time.sleep(0.2)
        finally:
            if cl is not None:
                if cl.released:
                    # the wid was reclaimed by a rejoiner: forget it so a
                    # LATER re-adoption (rejoiner dies again) can restart
                    # a loop here instead of finding the wid "active"
                    with group_lock:
                        active_wids.discard(wid)
                cl.bye()

    def pipelined_worker_loop(wid: int) -> None:
        """Pipelined update loop (``async.pipeline.depth`` >= 1): the
        serial loop's per-update stall structure is pull(RTT + wave wait)
        -> compute -> push(RTT + merge wait), strictly serialized -- the
        device idles during every RTT and the socket idles during every
        compute.  Here the three overlap, on ONE thread per worker (the
        overlap lives in the kernel socket buffers, not in extra threads
        whose GIL handoffs would eat the win):

        - **prefetched pulls** on a second PSClient connection:
          ``pull_start`` SENDS the pull for model v(k+1) before step k
          computes; the request parks in the PS wave gate and the reply
          lands in this socket's kernel buffer while the step runs
          (delta-mode ``have=`` pulls make an unchanged version a
          header-only NOT_MODIFIED); ``pull_finish`` then decodes it --
          usually without blocking at all (``prefetch_hits``);
        - **decoupled pushes** on a bounded wire window:
          ``push_start`` sends step k's gradient and the loop moves
          straight on -- push k+1 goes out before ACK k returns (the
          server replies in order, so ACKs pair FIFO); ACKs are reaped
          lazily, and only when ``depth`` pushes are unacknowledged
          does the loop block on one (``push_finish``);
        - staleness stays bounded: the PS's taw admission prices the
          in-flight window, and a taw REJECTION makes this loop discard
          its prefetched model and pull fresh (``stale_discards``).

        Exactly-once pushes ride the session/dedup machinery: window
        entries are stamped once and REPLAYED on reconnect, so a
        delivered-but-unACKed push is re-answered from the PS dedup
        window, never re-applied.  Adoption orders (they ride PULL
        replies, so they arrive on the prefetch connection),
        RELEASED/DONE, and trace spans all keep working; the residual
        stall (blocking in pull_finish or on the window cap) is
        recorded as the ``pipeline`` trace stage.

        Mesh interaction (``async.mesh.devices``): with a worker mesh
        the staged host->device put replicates the pulled model over
        every mesh device (make_pipelined_transfer handed the mesh's
        replicated sharding) -- the P transfer-engine
        copies overlap step k's compute exactly like the single-device
        double buffer, and the psum at the end of the mesh step overlaps
        the next prefetch's RTT the same way single-device compute did.
        Everything else (two connections, bounded window, exactly-once
        replay) is mesh-oblivious: the loop pushes the one fused
        gradient the mesh step returns."""
        shard = shards[wid]
        dev = shard_dev(shard)
        placed = mesh_place(wid, shard)  # None = single-device step
        stage, readback = steps.make_pipelined_transfer(
            mesh_replicated if placed is not None else dev
        )
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid)
        key = (jax.device_put(key, mesh_replicated)
               if placed is not None else jax.device_put(key, dev))
        deadline = time.monotonic() + deadline_s
        pull_cl: Optional[PSClient] = None
        push_cl: Optional[PSClient] = None
        done = False
        stale_feedback = False

        def reap_one() -> None:
            """Collect the oldest in-flight push's ACK (FIFO)."""
            nonlocal done, stale_feedback
            try:
                accepted, acked_done = push_cl.push_finish()
                pl_stats.bump("pushes_async")
                _flight.note("push", wid=wid, acc=bool(accepted),
                             n=counts[wid])
                if acked_done:
                    done = True
                elif not accepted:
                    # taw rejection: the in-flight window ran too stale
                    # -- discard the prefetched model and pull fresh
                    stale_feedback = True
            except (ConnectionError, OSError):
                # whole retry budget spent: the unacked window is lost,
                # exactly as the serial loop's error path loses its
                # round; pace and keep going
                lost = push_cl.push_abandon()
                pl_stats.bump("push_errors", max(lost, 1))
                time.sleep(0.2)

        try:
            while not stop.is_set() and time.monotonic() < deadline:
                try:
                    pull_cl = make_client(recorder=recorder)
                    push_cl = make_client(recorder=recorder,
                                          pl_stats=pl_stats,
                                          cv_buf=cv_buf)
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.2)  # PS mid-restart: pace and re-dial
            if push_cl is None:
                return
            tr = recorder.start_update(wid) if recorder is not None else None
            pull_cl.pull_start(wid, tr=tr)
            while (not stop.is_set() and not done
                   and time.monotonic() < deadline):
                was_ready = pull_cl.pull_ready()
                t_w0 = _trace.now_ms()
                try:
                    got = pull_cl.pull_finish(wid)
                except (ConnectionError, OSError):
                    time.sleep(0.2)
                    tr = (recorder.start_update(wid)
                          if recorder is not None else None)
                    pull_cl.pull_start(wid, tr=tr)
                    continue
                if got is None:
                    break  # DONE, or this wid was RELEASED to a rejoiner
                if was_ready:
                    pl_stats.bump("prefetch_hits")   # reply was buffered
                else:
                    pl_stats.bump("prefetch_waits")  # loop blocked on it
                if tr is not None:
                    # the pipeline's residual stall: whatever pull wait
                    # the prefetch could not hide
                    tr.add(_trace.PIPELINE, t_w0, _trace.now_ms())
                # adoption orders ride PULL replies, i.e. arrive on the
                # prefetch connection
                if shard_factory is not None:
                    for orphan in pull_cl.take_orders():
                        adopt(orphan)
                if stale_feedback:
                    # stale-prefetch discard: pull fresh instead of
                    # computing on a basis the taw filter just priced out
                    # (delta mode makes the re-pull nearly free)
                    stale_feedback = False
                    pl_stats.bump("stale_discards")
                    tr = (recorder.start_update(wid)
                          if recorder is not None else None)
                    pull_cl.pull_start(wid, tr=tr)
                    continue
                ts, w_host, avg_ms, calibrated = got
                cur_tr = tr
                # prefetch the NEXT model before computing: its wave-gate
                # wait and RTT ride this step's compute
                tr = (recorder.start_update(wid)
                      if recorder is not None else None)
                pull_cl.pull_start(wid, tr=tr)
                if calibrated and not calibrated_once.is_set():
                    delay_model.calibrate(avg_ms)
                    calibrated_once.set()
                t_c0 = _trace.now_ms() if cur_tr is not None else 0.0
                dly = delay_model.delay_ms(wid) if calibrated else 0.0
                if dly > 0:
                    time.sleep(dly / 1e3)
                w_dev = stage(w_host)
                counts[wid] += 1
                g, key = run_step(shard, w_dev, key, placed=placed)
                g_host = readback(g)
                if cur_tr is not None:
                    cur_tr.add(_trace.COMPUTE, t_c0, _trace.now_ms())
                if cv_buf is not None and counts[wid] % conv_every == 0:
                    conv_sample(shard,
                                w_host if placed is not None else w_dev,
                                ts, g_host)
                # depth cap: at most depth_now unACKed pushes in flight
                # -- THE staleness bound the taw admission prices.  The
                # adaptive controller moves the live window within
                # [1, configured depth] (CTRL rides the pull replies
                # this very loop prefetches); without control the cap
                # IS the configured depth.  Reap lazily: ACKs usually
                # sit in the buffer already.
                depth_now = (ctrl_sink.depth(pipe_depth)
                             if ctrl_sink is not None else pipe_depth)
                t_q0 = _trace.now_ms() if cur_tr is not None else 0.0
                blocked = False
                while (push_cl.inflight_pushes() >= depth_now
                       and not done):
                    blocked = True
                    reap_one()
                if done:
                    break
                push_cl.push_start(wid, ts, g_host, sparse=sparse,
                                   tr=cur_tr)
                pl_stats.high_water("inflight_max",
                                    push_cl.inflight_pushes())
                if blocked and cur_tr is not None:
                    # window backpressure: the bounded in-flight cap held
                    # the loop back -- the other face of the pipeline
                    # stage
                    cur_tr.add(_trace.PIPELINE, t_q0, _trace.now_ms())
        finally:
            if push_cl is not None:
                # drain the window: every sent push gets its verdict (a
                # DONE ack inside the tail is fine -- we are leaving)
                while push_cl.inflight_pushes():
                    reap_one()
            released = ((pull_cl is not None and pull_cl.released)
                        or (push_cl is not None and push_cl.released))
            if released:
                with group_lock:
                    active_wids.discard(wid)
            if push_cl is not None:
                push_cl.bye()
            if pull_cl is not None:
                pull_cl.bye()

    def spawn(w: int) -> None:
        target = pipelined_worker_loop if pipe_depth > 0 else worker_loop
        t = threading.Thread(target=target, args=(w,),
                             name=f"dcn-worker-{w}", daemon=True)
        with group_lock:
            threads.append(t)
        t.start()

    # introduce this process to the PS before serving: the supervisor
    # learns the proc token, wids, and pid (local-exit detection); a
    # rejoining process's HELLO is also what deposes its surrogate.  A
    # fixed-membership PS just says WELCOME.  The WELCOME reply is also
    # the SHARD-MAP handshake (parallel/shardgroup.py): against a sharded
    # PS group it carries the per-shard [host, port, lo, hi] map and every
    # loop below runs a ShardedPSClient instead -- so HELLO is retried
    # for the WHOLE worker deadline, never skipped: without the WELCOME
    # this process cannot know whether the PS is a shard group, and
    # serving a sharded group as if it were one PS would pull a single
    # range as the whole model (a width mismatch the loops' transport
    # except clauses cannot absorb).  A PS dark past the deadline aborts
    # the process cleanly instead.
    hello_deadline = time.monotonic() + deadline_s
    hello_ok = False
    while True:
        try:
            hello_cl = PSClient(host, port, proc=proc_token)
            welcome = hello_cl.hello(proc_token, wids, pid=os.getpid())
            hello_cl.bye()
            wire_map = welcome.get("shards") or []
            if len(wire_map) > 1:
                from asyncframework_tpu.parallel.shardgroup import ShardMap

                if algo != "asgd":
                    raise ValueError(
                        "sharded PS groups serve algo='asgd' only"
                    )
                smap = ShardMap.from_wire(wire_map)
                wire_epochs = welcome.get("epochs")
                if wire_epochs:
                    smap_epochs = [int(e) for e in wire_epochs]
            ps_epoch = int(welcome.get("epoch", 0) or 0)
            if welcome.get("ctrl"):
                from asyncframework_tpu.parallel.controller import (
                    ControlSink,
                )

                ctrl_sink = ControlSink(welcome["ctrl"])
            hello_ok = True
            break
        except (ConnectionError, OSError):
            if time.monotonic() >= hello_deadline:
                break
            # gentle pacing: each PSClient ctor already spent a full retry
            # budget (backoff + breaker); hammering here only keeps the
            # shared breaker's open-window fresh and starves the half-open
            # probe that would notice the PS came up
            time.sleep(0.5)
    if not hello_ok:
        # the PS never answered within the worker budget: there is no
        # safe topology to assume, so give up loudly with empty counts
        # (the launcher's summary shows zero contributed gradients)
        return dict(counts)

    for w in wids:
        spawn(w)
    join_deadline = time.monotonic() + deadline_s
    while time.monotonic() < join_deadline:
        with group_lock:
            snapshot = list(threads)
        if all(not t.is_alive() for t in snapshot):
            break
        time.sleep(0.05)
    if eval_wid is not None:
        # distributed optVars evaluation: score the PS's snapshot stack over
        # this process's shards, push one summed loss vector.  Only shards
        # this process still SERVES count -- an adopted shard whose owner
        # rejoined (RELEASED) is evaluated by its real owner, and summing
        # it here too would double-count its loss.  Against a shard group
        # the client assembles the full-width snapshot stack per range.
        # The fan-out is RETRIED under pacing: a shard mid-relaunch
        # (elastic failover; a fenced zombie being replaced right at run
        # end) must cost the eval plane a pause, not the whole trajectory
        # -- before this, one refused dial here crashed the worker and
        # silently voided the assembled loss curve.
        eval_deadline = time.monotonic() + min(60.0, deadline_s)
        while True:
            cl = None
            try:
                cl = make_client()
                times, W = cl.snapshots()
                with group_lock:
                    served = {w: s for w, s in shards.items()
                              if w in active_wids}
                losses = evaluate_snapshots_on_shards(served, times, W,
                                                      cfg.loss)
                cl.send_eval(eval_wid, losses)
                break
            except (ConnectionError, OSError):
                if time.monotonic() >= eval_deadline:
                    break  # trajectory forfeited, counts still returned
                if smap is not None:
                    # a hot-standby promotion may have MOVED a shard's
                    # endpoint since HELLO: every retry here builds a
                    # FRESH facade, so refresh the map from any live
                    # member or the rebuilds would dial the dead
                    # endpoint until the deadline forfeits the curve
                    from asyncframework_tpu.parallel.shardgroup import (
                        resolve_live_group,
                    )

                    smap2, epochs2 = resolve_live_group(smap.entries)
                    if smap2 is not None:
                        smap = smap2
                        if epochs2:
                            smap_epochs = epochs2
                time.sleep(0.5)
            finally:
                if cl is not None:
                    try:
                        cl.bye()
                    except (ConnectionError, OSError):
                        pass
    return counts


def evaluate_snapshots_on_shards(shards: Dict[int, object], times: List[float],
                                 W: np.ndarray, loss: str = "least_squares"
                                 ) -> np.ndarray:
    """Per-snapshot loss SUMS over this process's shards (caller divides by
    global N after summing across processes)."""
    import jax
    import jax.numpy as jnp

    from asyncframework_tpu.ops import steps

    ev_dense = steps.make_trajectory_loss_eval(loss)
    ev_sparse = steps.make_sparse_trajectory_loss_eval()
    total = np.zeros(W.shape[0], np.float64)
    for shard in shards.values():
        if hasattr(shard, "cols"):
            Wd = jax.device_put(jnp.asarray(W), shard.cols.device)
            part = ev_sparse(shard.cols, shard.vals, shard.y, Wd)
        else:
            Wd = jax.device_put(jnp.asarray(W), shard.X.device)
            part = ev_dense(shard.X, shard.y, Wd)
        total += np.asarray(part, np.float64)
    return total
