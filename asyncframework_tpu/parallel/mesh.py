"""Device mesh and sharding helpers.

The reference's "cluster" is Master/Workers/Executors over TCP
(``deploy/master/Master.scala``, ``scheduler/cluster/...``); the TPU-native
cluster is a :class:`jax.sharding.Mesh` over ICI (one slice) or ICI+DCN
(multi-slice / multi-host via ``jax.distributed``).  Data parallelism shards
the batch dimension over the ``dp`` axis; an optional ``md`` (model-dim) axis
shards the feature dimension of very wide models (rcv1 is 47k dims -- fits
replicated, but the axis is wired through so the same code scales).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def resolve_shard_map():
    """The one shard_map entry point for the whole repo.

    ``shard_map`` moved across jax releases: new jax exposes
    ``jax.shard_map`` (keyword-only ``mesh``/``in_specs``/``out_specs``,
    ``check_vma=``), older installs only have
    ``jax.experimental.shard_map.shard_map`` (``check_rep=`` instead of
    ``check_vma=``, no varying-manual-axes tracking).  Every call site
    routes through this resolver so one install difference is absorbed in
    one place.  The returned callable always speaks the NEW surface --
    ``check_vma=`` is accepted (and honored natively); the fallback runs
    with ``check_rep=False`` unconditionally -- the old checker's
    replication inference has known false positives the new API fixed
    (scan carries whose rep sets converge only after a fixed point, e.g.
    "Scan carry input and output got mismatched replication types ...
    as a temporary workaround pass the check_rep=False argument", and
    reductions of ``all_gather`` outputs).  Both flags are trace-time
    diagnostics only; disabling one never changes numerics, and the
    new-API path keeps full vma checking wherever it exists.
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native
    from jax.experimental.shard_map import shard_map as _legacy

    def _compat(f=None, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        del check_vma  # legacy check_rep: known false positives (above)
        if f is None:
            return functools.partial(
                _compat, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kw,
            )
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False, **kw,
        )

    return _compat


def pcast_varying(x, axis: str):
    """``jax.lax.pcast(x, axis, to="varying")`` where available.

    Legacy jax (the ``jax.experimental.shard_map`` era) has no
    varying-manual-axes tracking, so there is nothing to cast -- the
    value is returned unchanged and ``check_rep`` does its own (coarser)
    replication inference.
    """
    pc = getattr(jax.lax, "pcast", None)
    if pc is None:
        return x
    return pc(x, (axis,), to="varying")


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, ...] = ("dp",),
    axis_sizes: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    clamp: bool = False,
) -> Mesh:
    """Create a mesh over the first ``n_devices`` (default: all).

    For multi-host deployments callers run ``jax.distributed.initialize()``
    first; ``jax.devices()`` then spans hosts and the same mesh code rides
    ICI within a slice and DCN across slices.

    ``clamp=True``: an ``n_devices`` beyond what the rig actually has is
    CLAMPED to the available device count (logged) instead of raising --
    the conf-driven path (``async.mesh.devices`` on a worker daemon) must
    degrade on a smaller rig, never crash the process.  The default stays
    strict: a programmatic caller asking for devices that are not there is
    a bug worth a traceback.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            if not clamp:
                raise ValueError(
                    f"requested a {n_devices}-device mesh but only "
                    f"{len(devs)} devices are available"
                )
            logger.warning(
                "make_mesh: requested %d devices but only %d available; "
                "clamping", n_devices, len(devs),
            )
            n_devices = len(devs)
        devs = devs[:n_devices]
    if axis_sizes is None:
        axis_sizes = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(axis_sizes)
    mesh = Mesh(arr, axis_names)
    if clamp:
        logger.info("make_mesh: using mesh %s over %d %s device(s)",
                    dict(zip(axis_names, axis_sizes)), len(devs),
                    devs[0].platform if devs else "?")
    return mesh


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Sharding for an array whose leading dim is the batch dim."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _put_sharded(a, sh: NamedSharding):
    """Multi-host-aware placement: single-process uses device_put; with
    ``jax.distributed`` active, every process holds the same global host
    array and contributes only its addressable shards (the SPMD-driver
    convention -- ``device_put`` would reject non-addressable devices)."""
    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            np.shape(a), sh, lambda idx: np.asarray(a)[idx]
        )
    return jax.device_put(a, sh)


def shard_batch(mesh: Mesh, *arrays, axis: str = "dp"):
    """Place host arrays onto the mesh sharded on their leading dim."""
    sh = batch_sharding(mesh, axis)
    out = tuple(_put_sharded(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]


def pad_and_shard_2d(
    mesh: Mesh,
    X,
    y,
    w0,
    dp_axis: str = "dp",
    md_axis: str = "md",
):
    """2-D layout: rows pad+shard over ``dp_axis`` AND features over
    ``md_axis`` (``w`` sharded over the feature axis, never whole on one
    chip).  Returns ``(Xs, ys, valid, w_dev, d)`` with ``d`` the original
    feature count (padded feature columns are zero and slice off the
    results).  Placement goes through :func:`_put_sharded`, so the same
    code runs single-process and under ``jax.distributed``.
    """
    n, d = X.shape
    n_dp = mesh.shape[dp_axis]
    n_md = mesh.shape[md_axis]
    pad_n = (-n) % n_dp
    pad_d = (-d) % n_md
    Xp = np.pad(np.asarray(X, np.float32), ((0, pad_n), (0, pad_d)))
    yp = np.pad(np.asarray(y, np.float32), (0, pad_n))
    valid = np.pad(np.ones(n, np.float32), (0, pad_n))
    Xs = _put_sharded(Xp, NamedSharding(mesh, P(dp_axis, md_axis)))
    ys = _put_sharded(yp, NamedSharding(mesh, P(dp_axis)))
    vs = _put_sharded(valid, NamedSharding(mesh, P(dp_axis)))
    w_dev = _put_sharded(
        np.pad(np.asarray(w0, np.float32), (0, pad_d)),
        NamedSharding(mesh, P(md_axis)),
    )
    return Xs, ys, vs, w_dev, d


def pad_and_shard(mesh: Mesh, *arrays, axis: str = "dp"):
    """Pad rows to a multiple of the mesh size (static shapes for XLA) and
    shard on the batch axis.

    Returns ``(*sharded_arrays, valid_sharded, n)`` where ``valid`` is a
    float mask that is 0 on padding rows and ``n`` the original row count.
    All arrays are padded along axis 0 with zeros.
    """
    n_dev = mesh.devices.size
    n = arrays[0].shape[0]
    pad = (-n) % n_dev
    valid = np.ones(n, np.float32)
    if pad:
        arrays = tuple(
            np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            for a in arrays
        )
        valid = np.concatenate([valid, np.zeros(pad, np.float32)])
    sharded = shard_batch(mesh, *arrays, valid, axis=axis)
    return (*sharded, n)
