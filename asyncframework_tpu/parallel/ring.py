"""Long-context attention: ring attention + all-to-all sequence parallelism.

Net-new TPU-first scope.  The reference scales *rows of data*, never sequence
length (SURVEY.md section 2.2: no sequence/context parallelism anywhere in
the fork) -- but a TPU framework must treat long context as first-class, so
this module provides the two canonical strategies over a sequence-sharded
mesh axis:

- :func:`ring_attention` -- blockwise (flash-style) online-softmax attention
  where K/V blocks rotate around the ``sp`` ring via ``lax.ppermute``.  Each
  device holds ``T/P`` of the sequence; peak memory is O(T/P * T/P) per step
  instead of O(T^2), and the K/V transfer for step ``s+1`` overlaps the
  compute of step ``s`` (XLA schedules the ppermute DMA concurrently over
  ICI).  Exact (not approximate): the online max/denominator accumulation
  reproduces full softmax attention to float tolerance.
- :func:`ulysses_attention` -- the all-to-all alternative: switch from
  sequence-sharding to head-sharding (``all_to_all`` over ``sp``), run each
  head group's *full-sequence* attention locally, switch back.  Two
  all-to-alls per call; needs ``num_heads % P == 0``.

Both are ``shard_map``-ped over a ``Mesh`` axis and differentiable (JAX
differentiates through the loop and the collectives), and both reduce to
:func:`reference_attention` on a 1-device mesh.

Conventions: ``q, k, v`` are ``(batch, seq, heads, head_dim)``, sharded on
``seq`` over the mesh axis; causal masking uses global positions.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import pcast_varying, resolve_shard_map

_NEG = -1e30  # mask fill / softmax-max init: finite so (-inf) - (-inf) never NaNs


def reference_attention(q, k, v, causal: bool = False):
    """Single-device full softmax attention (the correctness oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_accumulate(q, k, v, m, l, o, mask):
    """One flash step: fold a K/V block into the running (max, denom, out).

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``m``/``l``: (B, H, Tq)
    float32; ``o``: (B, Tq, H, D) float32; ``mask``: (Tq, Tk) or None.
    Accumulation is float32 regardless of input dtype (flash-attention
    practice: bf16 inputs, fp32 running state -- the per-step corr rescale
    compounds rounding otherwise).
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG)
    # local stats for this block, then the ONE shared flash rescale
    # (_merge_stats) -- the same fold the Pallas path uses, so the two
    # block kernels can never drift numerically
    m_b = s.max(axis=-1)                         # (B, H, Tq) f32
    p = jnp.exp(s - m_b[..., None])              # (B, H, Tq, Tk) f32
    l_b = p.sum(axis=-1)
    o_b = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return _merge_stats(m, l, o, m_b, l_b, o_b)


def _merge_stats(m, l, o, m_b, l_b, o_b):
    """Fold a block's local softmax stats into the running (m, l, o) --
    the standard flash rescale, shared by the XLA and Pallas block paths."""
    m_new = jnp.maximum(m, m_b)
    c_old = jnp.exp(m - m_new)
    c_new = jnp.exp(m_b - m_new)
    l_new = l * c_old + l_b * c_new
    o_new = (
        o * c_old.transpose(0, 2, 1)[..., None]
        + o_b * c_new.transpose(0, 2, 1)[..., None]
    )
    return m_new, l_new, o_new


def ring_attention(
    q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
    block_kernel: str = "xla",
):
    """Exact attention over a sequence-sharded mesh axis via a K/V ring.

    Device ``p`` starts with its own K/V block and at ring step ``s`` holds
    the block originally on device ``(p - s) mod P`` (ppermute sends each
    block to the next device).  Causal masking uses global positions, so
    fully-masked future blocks contribute nothing (their probabilities
    underflow to zero against the running max).

    ``block_kernel``: "xla" runs the per-step block attention as fused XLA
    (:func:`_block_accumulate`); "pallas" offloads it to the hand-tiled
    :func:`~asyncframework_tpu.ops.pallas_kernels.chunk_attention` kernel
    (two MXU matmuls + exp entirely in VMEM, interpret-mode on CPU) and
    merges the returned (o, m, l) stats with the same flash rescale.
    """
    if block_kernel not in ("xla", "pallas"):
        raise ValueError("block_kernel must be 'xla' or 'pallas'")
    n_dev = mesh.shape[axis]
    if q.shape[1] % n_dev:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis size {n_dev}"
        )
    if q.shape[1] != k.shape[1]:
        # the block-position causal mask assumes aligned q/k positions;
        # cross-attention-style tq != tk would be silently wrong
        raise ValueError(
            f"ring_attention requires equal q/k seq lens, got {q.shape[1]} "
            f"vs {k.shape[1]}"
        )

    # check_vma must be off for the pallas block path: the pallas
    # interpreter's internal pad/slice mixes varying and invariant
    # operands, which strict vma checking rejects (a JAX interpreter
    # limitation, not a sharding bug -- the XLA path keeps the check)
    use_vma = block_kernel != "pallas"

    @functools.partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        check_vma=use_vma,
    )
    def ring(ql, kl, vl):
        p_idx = jax.lax.axis_index(axis)
        P_sz = n_dev  # static mesh axis size (jax.lax.axis_size is new-API)
        b, tq, h, d = ql.shape
        t_local = kl.shape[1]
        # pcast to varying: the accumulators become device-varying on the sp
        # axis (the loop body's outputs are, via axis_index), so carry types
        # match.  Accumulators are f32 (see _block_accumulate).
        def varying(x):
            if not use_vma:
                return x  # vma tracking off: pcast is meaningless
            return pcast_varying(x, axis)

        m0 = varying(jnp.full((b, h, tq), _NEG, jnp.float32))
        l0 = varying(jnp.zeros((b, h, tq), jnp.float32))
        o0 = varying(jnp.zeros(ql.shape, jnp.float32))
        q_pos = p_idx * tq + jnp.arange(tq)

        def fold(kb, vb, m, l, o, mask):
            if block_kernel == "pallas":
                from asyncframework_tpu.ops.pallas_kernels import (
                    chunk_attention,
                )

                o_b, m_b, l_b = chunk_attention(
                    ql, kb, vb, mask,
                    interpret=jax.default_backend() != "tpu",
                )
                return _merge_stats(m, l, o, m_b, l_b, o_b)
            return _block_accumulate(ql, kb, vb, m, l, o, mask)

        def accumulate(s, kb, vb, m, l, o):
            if causal:
                k_block = (p_idx - s) % P_sz
                k_pos = k_block * t_local + jnp.arange(t_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                # a block strictly in the future (k_block > p_idx) is fully
                # masked: skip its einsums entirely -- halves causal FLOPs
                return jax.lax.cond(
                    k_block > p_idx,
                    lambda m, l, o: (m, l, o),
                    lambda m, l, o: fold(kb, vb, m, l, o, mask),
                    m, l, o,
                )
            return fold(kb, vb, m, l, o, None)

        def step(s, carry):
            kb, vb, m, l, o = carry
            m, l, o = accumulate(s, kb, vb, m, l, o)
            perm = [(j, (j + 1) % P_sz) for j in range(P_sz)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return kb, vb, m, l, o

        # P-1 rotate-and-accumulate steps, then the final block WITHOUT the
        # trailing ppermute (its output would be discarded -- one wasted
        # rotation of the K and V shards over ICI per call otherwise)
        kb, vb, m, l, o = jax.lax.fori_loop(
            0, P_sz - 1, step, (kl, vl, m0, l0, o0)
        )
        m, l, o = accumulate(P_sz - 1, kb, vb, m, l, o)
        out = o / l.transpose(0, 2, 1)[..., None]
        return out.astype(ql.dtype)

    return ring(q, k, v)


def ulysses_attention(
    q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
    block_kernel: str = "xla", pallas_block: int = 512,
):
    """All-to-all sequence parallelism (Ulysses-style): reshard seq->heads,
    attend over the full sequence per local head group, reshard back.

    ``block_kernel="pallas"`` folds the full-sequence attention through
    :func:`~asyncframework_tpu.ops.pallas_kernels.chunk_attention` in
    ``pallas_block``-sized K/V blocks (VMEM-bounded) merged by the shared
    flash rescale, instead of the XLA reference path.
    """
    if block_kernel not in ("xla", "pallas"):
        raise ValueError("block_kernel must be 'xla' or 'pallas'")
    n_dev = mesh.shape[axis]
    h = q.shape[2]
    if h % n_dev:
        raise ValueError(f"heads {h} not divisible by mesh axis size {n_dev}")
    for name, t in (("q", q.shape[1]), ("k", k.shape[1])):
        if t % n_dev:
            raise ValueError(
                f"{name} seq len {t} not divisible by mesh axis size {n_dev}"
            )
    if causal and q.shape[1] != k.shape[1]:
        # reference aligns the causal mask bottom-right for tq != tk; the
        # resharded local attention here would mask with absolute positions
        raise ValueError(
            f"causal ulysses_attention requires equal q/k seq lens, got "
            f"{q.shape[1]} vs {k.shape[1]}"
        )

    @functools.partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        check_vma=block_kernel != "pallas",  # see ring_attention
    )
    def ulysses(ql, kl, vl):
        # (B, T/P, H, D) --all_to_all--> (B, T, H/P, D)
        def seq_to_heads(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        def heads_to_seq(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = seq_to_heads(ql), seq_to_heads(kl), seq_to_heads(vl)
        if block_kernel == "pallas":
            from asyncframework_tpu.ops.pallas_kernels import chunk_attention

            tq, tk = qh.shape[1], kh.shape[1]
            # fold K/V in VMEM-sized blocks through the shared flash
            # rescale, as a lax.scan so the PROGRAM stays O(1) in sequence
            # length (a Python loop would inline tk/blk pallas calls), and
            # per-block masks from index arithmetic so nothing O(Tq*Tk)
            # ever materializes
            blk = min(tk, max(int(pallas_block), 8))
            pad_k = (-tk) % blk
            kh_p = jnp.pad(kh, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
            vh_p = jnp.pad(vh, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
            nb = (tk + pad_k) // blk
            b, _, hl, dh = qh.shape
            q_pos = jnp.arange(tq)
            interp = jax.default_backend() != "tpu"

            def fold_block(carry, i):
                m, l, o = carry
                kb = jax.lax.dynamic_slice_in_dim(kh_p, i * blk, blk, 1)
                vb = jax.lax.dynamic_slice_in_dim(vh_p, i * blk, blk, 1)
                k_pos = i * blk + jnp.arange(blk)
                valid = k_pos[None, :] < tk  # padded K columns masked off
                if causal:
                    mask_b = (q_pos[:, None] >= k_pos[None, :]) & valid
                else:
                    mask_b = jnp.broadcast_to(valid, (tq, blk))
                o_b, m_b, l_b = chunk_attention(
                    qh, kb, vb, mask_b, interpret=interp
                )
                return _merge_stats(m, l, o, m_b, l_b, o_b), None

            init = (
                jnp.full((b, hl, tq), _NEG, jnp.float32),
                jnp.zeros((b, hl, tq), jnp.float32),
                jnp.zeros(qh.shape, jnp.float32),
            )
            (m, l, o), _ = jax.lax.scan(
                fold_block, init, jnp.arange(nb)
            )
            oh = (o / l.transpose(0, 2, 1)[..., None]).astype(qh.dtype)
        else:
            oh = reference_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(oh)

    return ulysses(q, k, v)
