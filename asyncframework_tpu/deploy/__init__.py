"""Standalone cluster deploy: Master/Worker daemons + submission client.

Parity: ``deploy/master/Master.scala:41`` / ``deploy/worker/Worker.scala:43``
/ ``deploy/client/StandaloneAppClient.scala:44`` -- the reference's
standalone resource manager.  See ``deploy/master.py`` for the design notes.
"""

from asyncframework_tpu.deploy.client import submit_app, wait_app, MasterClient
from asyncframework_tpu.deploy.leader import FileLeaderElection
from asyncframework_tpu.deploy.master import Master
from asyncframework_tpu.deploy.worker import Worker

__all__ = ["Master", "Worker", "MasterClient", "submit_app", "wait_app",
           "FileLeaderElection"]
