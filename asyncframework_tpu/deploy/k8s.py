"""Kubernetes adapter: render the standalone cluster as k8s manifests.

Parity (studied, not copied): the reference's k8s resource manager
(``resource-managers/kubernetes/.../submit/KubernetesClientApplication.scala:90,188``
-- ``Client.run`` builds a driver pod spec from the submission and creates
it via the API; ``DriverConfigOrchestrator.scala`` assembles the spec
steps).  Same capability here, re-shaped for this runtime: the cluster's
own daemons (master with HA + flock lease, workers, topic server) ARE the
long-lived services, so the adapter's job is to **render deterministic
manifests** that place them on a cluster, plus a Job spec per application
submission that runs the stock ``--master`` CLI against the master
Service.  Rendering is pure (dict -> YAML via pyyaml), testable without a
cluster, and applied with plain ``kubectl apply -f`` -- this build
deliberately has no API-server client: zero-egress environments and the
operator's existing kubectl auth make "generate, then apply" the honest
interface (the reference's in-process fabric8 client exists because
spark-submit must watch the driver pod; our `--wait` polling rides the
master protocol instead).

Rendered topology:

- ``master``: Deployment (1 replica, or N with ``--ha`` sharing a PVC for
  the lease + persistence) + a Service exposing the RPC and UI ports.
- ``workers``: Deployment with ``replicas`` pods of ``bin/async-worker``
  pointed at the master Service (heartbeat re-registration makes pod
  churn safe; supervised executors restart inside the pod).
- ``topic-server`` (optional): Deployment + Service for the network
  streaming source.
- per-app **Job**: one pod running ``bin/async-submit --master ...`` with
  the recipe argv; ``backoffLimit: 0`` (the daemons own retries via
  ``--supervise``, a failed submission should surface, not loop).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import yaml

DEFAULT_IMAGE = "asyncframework-tpu:latest"
RPC_PORT = 7077
UI_PORT = 8080
#: per-pod telemetry endpoint (metrics/live.start_telemetry_from_conf):
#: every daemon pod sets async.metrics.port to this via env and carries
#: Prometheus scrape annotations pointing at it
METRICS_PORT = 9095
#: fleet-wide sampling-profiler rate (async.prof.hz): lower than the
#: 97 Hz single-process default -- across hundreds of pods the samples
#: aggregate anyway, and a prime avoids lockstep with periodic work
PROF_FLEET_HZ = 29


def _meta(name: str, app: str, namespace: str) -> dict:
    return {
        "name": name,
        "namespace": namespace,
        "labels": {"app.kubernetes.io/part-of": "asyncframework-tpu",
                   "app.kubernetes.io/component": app},
    }


def _pod_meta(app: str) -> dict:
    """Pod-template metadata: selector label + Prometheus scrape
    annotations (the conventional prometheus.io/* trio a cluster-wide
    scrape config discovers) pointing at the pod's telemetry port."""
    return {
        "labels": {"app": app},
        "annotations": {
            "prometheus.io/scrape": "true",
            "prometheus.io/port": str(METRICS_PORT),
            "prometheus.io/path": "/metrics",
        },
    }


def _container(name: str, image: str, command: List[str],
               ports: Optional[List[int]] = None,
               resources: Optional[dict] = None,
               volume_mounts: Optional[List[dict]] = None,
               metrics: bool = True) -> dict:
    c: dict = {"name": name, "image": image, "command": command}
    if metrics:
        # ASYNCTPU_ASYNC_METRICS_PORT is conf async.metrics.port's env
        # spelling: the daemon boots its /metrics + /api/status endpoint
        # without any manifest-side CLI flag plumbing.  The continuous
        # profiler (async.prof.*) rides the same env surface: every
        # telemetry-serving pod also exposes its zone decomposition on
        # /api/status, at a fleet-gentle sampling rate (PROF_FLEET_HZ,
        # below the 97 Hz single-process default)
        c["env"] = [{"name": "ASYNCTPU_ASYNC_METRICS_PORT",
                     "value": str(METRICS_PORT)},
                    {"name": "ASYNCTPU_ASYNC_PROF_ENABLED",
                     "value": "1"},
                    {"name": "ASYNCTPU_ASYNC_PROF_HZ",
                     "value": str(PROF_FLEET_HZ)}]
        ports = list(ports or []) + [METRICS_PORT]
    if ports:
        c["ports"] = [{"containerPort": p} for p in ports]
    if resources:
        c["resources"] = resources
    if volume_mounts:
        c["volumeMounts"] = volume_mounts
    return c


def render_master(namespace: str = "default", image: str = DEFAULT_IMAGE,
                  ha_replicas: int = 1, pvc: str = "async-master-state",
                  ui: bool = True) -> List[dict]:
    """Master Deployment + Service (+ PVC when HA).  HA replicas share the
    persistence PVC; the flock lease elects exactly one active master and
    standbys answer STANDBY until takeover (deploy/leader.py)."""
    if ha_replicas < 1:
        raise ValueError("ha_replicas must be >= 1")
    cmd = ["python", "-m", "asyncframework_tpu.deploy.master",
           "--host", "0.0.0.0", "--port", str(RPC_PORT),
           "--persistence-dir", "/state"]
    if ha_replicas > 1:
        cmd.append("--ha")
    if ui:
        # --ui-host 0.0.0.0: the Service can only route to the UI port if
        # the page binds beyond the pod's loopback
        cmd += ["--ui-port", str(UI_PORT), "--ui-host", "0.0.0.0"]
    objs: List[dict] = []
    objs.append({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": _meta(pvc, "master", namespace),
        "spec": {
            # HA standbys on other nodes need a shared filesystem for the
            # flock lease + recovery state (the ZooKeeper-ensemble role)
            "accessModes": ["ReadWriteMany" if ha_replicas > 1
                            else "ReadWriteOnce"],
            "resources": {"requests": {"storage": "1Gi"}},
        },
    })
    objs.append({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": _meta("async-master", "master", namespace),
        "spec": {
            "replicas": ha_replicas,
            "selector": {"matchLabels": {"app": "async-master"}},
            "template": {
                "metadata": _pod_meta("async-master"),
                "spec": {
                    "containers": [_container(
                        "master", image, cmd,
                        ports=[RPC_PORT] + ([UI_PORT] if ui else []),
                        volume_mounts=[{"name": "state",
                                        "mountPath": "/state"}],
                    )],
                    "volumes": [{
                        "name": "state",
                        "persistentVolumeClaim": {"claimName": pvc},
                    }],
                },
            },
        },
    })
    ports = [{"name": "rpc", "port": RPC_PORT, "targetPort": RPC_PORT}]
    if ui:
        ports.append({"name": "ui", "port": UI_PORT, "targetPort": UI_PORT})
    objs.append({
        "apiVersion": "v1", "kind": "Service",
        "metadata": _meta("async-master", "master", namespace),
        "spec": {"selector": {"app": "async-master"}, "ports": ports},
    })
    return objs


def render_workers(replicas: int, namespace: str = "default",
                   image: str = DEFAULT_IMAGE, cores: int = 1,
                   resources: Optional[dict] = None) -> List[dict]:
    """Worker Deployment: each pod runs one worker daemon registered to the
    master Service.  Pod churn is safe -- heartbeats re-register and the
    master reaps the dead (Worker.scala's reconnect dance)."""
    if replicas < 1:
        raise ValueError("replicas must be >= 1")
    cmd = ["python", "-m", "asyncframework_tpu.deploy.worker",
           f"async-master:{RPC_PORT}", "--cores", str(cores)]
    return [{
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": _meta("async-workers", "worker", namespace),
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": "async-worker"}},
            "template": {
                "metadata": _pod_meta("async-worker"),
                "spec": {"containers": [_container(
                    "worker", image, cmd,
                    resources=resources or {
                        "limits": {"google.com/tpu": 1},
                    },
                )]},
            },
        },
    }]


def render_topic_server(namespace: str = "default",
                        image: str = DEFAULT_IMAGE,
                        port: int = 9092,
                        pvc: str = "async-topics") -> List[dict]:
    """Network LogTopic server (the broker-less streaming source) with a
    PVC for the durable segments."""
    return [
        {
            "apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": _meta(pvc, "topic-server", namespace),
            "spec": {"accessModes": ["ReadWriteOnce"],
                     "resources": {"requests": {"storage": "10Gi"}}},
        },
        {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta("async-topic-server", "topic-server",
                              namespace),
            "spec": {
                "replicas": 1,  # single-writer discipline IS the server
                "selector": {"matchLabels": {"app": "async-topic-server"}},
                "template": {
                    "metadata": {"labels": {"app": "async-topic-server"}},
                    "spec": {
                        "containers": [_container(
                            "topic-server", image,
                            ["python", "-m",
                             "asyncframework_tpu.streaming.log_net",
                             "--root", "/topics", "--host", "0.0.0.0",
                             "--port", str(port)],
                            ports=[port],
                            volume_mounts=[{"name": "topics",
                                            "mountPath": "/topics"}],
                        )],
                        "volumes": [{
                            "name": "topics",
                            "persistentVolumeClaim": {"claimName": pvc},
                        }],
                    },
                },
            },
        },
        {
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta("async-topic-server", "topic-server",
                              namespace),
            "spec": {"selector": {"app": "async-topic-server"},
                     "ports": [{"name": "log", "port": port,
                                "targetPort": port}]},
        },
    ]


SERVE_PORT = 7080


#: relay-node port on relay-tier replica pods (the predict port is
#: SERVE_PORT + 1; the relay tree rides its own port next to it)
RELAY_PORT = 7181


def render_serving(replicas: int, ps: str, namespace: str = "default",
                   image: str = DEFAULT_IMAGE,
                   resources: Optional[dict] = None,
                   relay_fanout: int = 0) -> List[dict]:
    """Serving tier (asyncframework_tpu/serving/): a frontend Deployment +
    Service (the stable predict endpoint) and a replica Deployment whose
    pods SUBSCRIBE to the given PS address and HELLO the frontend Service
    on boot.  Replica pod churn is safe by construction: a killed pod
    drops out of the frontend rotation (pid probe / silence) and its
    replacement re-HELLOs in; scaling reads is ``kubectl scale`` on the
    replica Deployment -- no state moves, every replica serves the same
    subscribed model.

    ``relay_fanout > 0`` renders the **relaycast tier** instead: the
    replica pods become a StatefulSet behind a headless Service, so
    each pod's ordinal hostname IS its tree position -- the replica CLI
    (``--relay-auto``) derives its rid and its planned parent's stable
    DNS name (``async-serve-replica-<p>.async-serve-relay``) from the
    deterministic k-ary plan (relaycast/tree.py), with zero
    coordination.  PS snapshot egress per version is then O(fanout):
    only the first ``fanout`` pods SUBSCRIBE directly; deeper pods
    RELAY_FETCH CRC-gated (compressed) deltas from their parents, and
    ANY relay failure falls back to a direct PS SUBSCRIBE, so pod churn
    degrades to extra root traffic, never to staleness or torn
    models."""
    if replicas < 1:
        raise ValueError("replicas must be >= 1")
    if not ps:
        raise ValueError("serving needs the PS address to SUBSCRIBE to")
    if relay_fanout < 0:
        raise ValueError("relay_fanout must be >= 0 (0 = relay off)")
    fe_cmd = ["python", "-m", "asyncframework_tpu.serving.cli",
              "frontend", "--host", "0.0.0.0", "--port", str(SERVE_PORT)]
    rep_cmd = ["python", "-m", "asyncframework_tpu.serving.cli",
               "replica", "--ps", ps, "--host", "0.0.0.0",
               "--port", str(SERVE_PORT + 1),
               "--frontend", f"async-serve:{SERVE_PORT}"]
    if relay_fanout > 0:
        rep_cmd += ["--relay-auto", "--relay-port", str(RELAY_PORT),
                    "--relay-service", "async-serve-relay",
                    "--conf", f"async.relay.fanout={relay_fanout}"]
    return [
        {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta("async-serve-frontend", "serve-frontend",
                              namespace),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": "async-serve-frontend"}},
                "template": {
                    "metadata": _pod_meta("async-serve-frontend"),
                    "spec": {"containers": [_container(
                        "frontend", image, fe_cmd, ports=[SERVE_PORT],
                    )]},
                },
            },
        },
        {
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta("async-serve", "serve-frontend", namespace),
            "spec": {"selector": {"app": "async-serve-frontend"},
                     "ports": [{"name": "predict", "port": SERVE_PORT,
                                "targetPort": SERVE_PORT}]},
        },
        (
            {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": _meta("async-serve-replicas", "serve-replica",
                                  namespace),
                "spec": {
                    "replicas": replicas,
                    "selector": {
                        "matchLabels": {"app": "async-serve-replica"}},
                    "template": {
                        "metadata": _pod_meta("async-serve-replica"),
                        "spec": {"containers": [_container(
                            "replica", image, rep_cmd,
                            ports=[SERVE_PORT + 1],
                            resources=resources,
                        )]},
                    },
                },
            }
            if relay_fanout <= 0 else
            # relaycast tier: StatefulSet ordinals are tree positions,
            # the headless Service gives every pod the stable DNS name
            # its children dial (async-serve-replica-<i>.async-serve-
            # relay) -- the tree needs identity, which a Deployment's
            # interchangeable pods cannot provide
            {
                "apiVersion": "apps/v1", "kind": "StatefulSet",
                "metadata": _meta("async-serve-replica", "serve-replica",
                                  namespace),
                "spec": {
                    "replicas": replicas,
                    "serviceName": "async-serve-relay",
                    "podManagementPolicy": "Parallel",
                    "selector": {
                        "matchLabels": {"app": "async-serve-replica"}},
                    "template": {
                        "metadata": _pod_meta("async-serve-replica"),
                        "spec": {"containers": [_container(
                            "replica", image, rep_cmd,
                            ports=[SERVE_PORT + 1, RELAY_PORT],
                            resources=resources,
                        )]},
                    },
                },
            }
        ),
    ] + ([] if relay_fanout <= 0 else [
        {
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta("async-serve-relay", "serve-replica",
                              namespace),
            "spec": {
                "clusterIP": "None",  # headless: per-pod DNS records
                "selector": {"app": "async-serve-replica"},
                "ports": [{"name": "relay", "port": RELAY_PORT,
                           "targetPort": RELAY_PORT}],
            },
        },
    ])


PS_SHARD_PORT = 7200


def render_ps_shards(shards: int, d: int, n: int,
                     workers: int = 8, namespace: str = "default",
                     image: str = DEFAULT_IMAGE,
                     cfg_overrides: Optional[dict] = None,
                     resources: Optional[dict] = None,
                     standbys: int = 0) -> List[dict]:
    """Sharded parameter-server group (parallel/shardgroup.py): one
    Deployment + Service + checkpoint PVC **per shard**, each pod running
    the same env-driven shard child the local :class:`ShardGroup`
    controller spawns.  k8s-native failover: the Deployment controller IS
    the restart supervisor -- a killed shard pod comes back behind its
    stable Service name, restores from the durable checkpoint on its PVC
    (model + clock + dedup window, so replayed pushes are exactly-once),
    and rejoins the group at the same map entry.  The shard map is static
    by construction (Service DNS + pinned port), rendered into every
    pod's ``ASYNC_SHARD_MAP``; workers/replicas still discover it at
    HELLO against shard 0 (the primary -- wave gate, worker supervision,
    eval plane), so client manifests only need the ONE address k8s
    already guarantees.  Per-shard scrape: every pod carries the
    prometheus.io annotations plus a ``shard`` label, and the child
    starts its /metrics endpoint with a ``shard=<i>`` exposition label --
    per-shard series never collapse in the aggregator.

    ``standbys=1`` additionally renders one WARM STANDBY pod + Service
    per shard (parallel/replication.py): the primary streams its
    accepted merge batches to ``async-ps-shard-<i>-standby`` (rendered
    into its ``ASYNC_SHARD_STANDBYS``), which mirrors the range live --
    a read replica for SUBSCRIBE / relaycast roots whose staleness is
    priced by replication lag, and a promotion target for an operator
    or external controller (the Deployment controller's restart remains
    the k8s-native recovery for the primary itself; a standby pod needs
    no PVC -- its state is re-synced over the stream on every boot)."""
    import dataclasses
    import json as _json

    from asyncframework_tpu.parallel.shardgroup import shard_ranges
    from asyncframework_tpu.solvers import SolverConfig

    if shards < 2:
        raise ValueError("a PS shard group needs shards >= 2 "
                         "(1 is the classic single PS)")
    if d < shards:
        raise ValueError(f"d={d} cannot range-partition over "
                         f"{shards} shards")
    cfg = dataclasses.asdict(SolverConfig(num_workers=workers))
    cfg.update(cfg_overrides or {})
    ranges = shard_ranges(d, shards)
    smap = [[f"async-ps-shard-{i}", PS_SHARD_PORT, lo, hi]
            for i, (lo, hi) in enumerate(ranges)]
    standby_map = ([[f"async-ps-shard-{i}-standby", PS_SHARD_PORT]
                    for i in range(shards)] if standbys > 0 else None)
    objs: List[dict] = []
    for i, (lo, hi) in enumerate(ranges):
        name = f"async-ps-shard-{i}"
        env = [
            {"name": "ASYNC_SHARD_INDEX", "value": str(i)},
            {"name": "ASYNC_SHARD_COUNT", "value": str(shards)},
            {"name": "ASYNC_SHARD_D", "value": str(d)},
            {"name": "ASYNC_SHARD_N", "value": str(n)},
            {"name": "ASYNC_SHARD_ALGO", "value": "asgd"},
            {"name": "ASYNC_SHARD_BIND_PORT", "value": str(PS_SHARD_PORT)},
            {"name": "ASYNC_SHARD_CFG", "value": _json.dumps(cfg)},
            {"name": "ASYNC_SHARD_CKPT",
             "value": f"/ckpt/ps_shard{i}.npz"},
            {"name": "ASYNC_SHARD_MAP", "value": _json.dumps(smap)},
            {"name": "ASYNC_SHARD_ELASTIC",
             "value": "1" if i == 0 else "0"},
            # epoch fencing, controller-less edition: the Deployment
            # controller restarts a dead shard pod, and the child mints
            # its next epoch from the checkpoint on the PVC
            # (restore bumps past the persisted epoch) -- ASYNC_SHARD_
            # EPOCH=1 is only the base for the very first life.  A
            # zombie pod behind a healed partition answers REJECT_FENCED
            # to everything once its successor's epoch is seen.
            {"name": "ASYNC_SHARD_EPOCH", "value": "1"},
            {"name": "ASYNCTPU_ASYNC_FENCE_ENABLED", "value": "1"},
            # lease-based death detection on the primary's worker
            # supervisor: cross-host pids are never probed, so the lease
            # (silence bound) is the ONLY honest signal up here
            {"name": "ASYNCTPU_ASYNC_LEASE_S", "value": "5"},
        ]
        if i == 0:
            # adaptive asynchrony controller on the primary shard pod:
            # telemetry -> knob decisions, fanned to the other shards
            # via SETMAP (shardgroup.CtrlFanout -- no ShardGroup owns
            # Deployment-managed children)
            env.append({"name": "ASYNCTPU_ASYNC_CONTROL_ENABLED",
                        "value": "1"})
        if standby_map is not None:
            env.append({"name": "ASYNC_SHARD_STANDBYS",
                        "value": _json.dumps(standby_map)})
        container = _container(
            f"ps-shard-{i}", image,
            ["python", "-m", "asyncframework_tpu.parallel.shardgroup"],
            ports=[PS_SHARD_PORT], resources=resources,
            volume_mounts=[{"name": "ckpt", "mountPath": "/ckpt"}],
        )
        container["env"] = env + container.get("env", [])
        meta = _pod_meta(name)
        meta["labels"]["shard"] = str(i)
        objs.append({
            "apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": _meta(f"{name}-ckpt", "ps-shard", namespace),
            "spec": {"accessModes": ["ReadWriteOnce"],
                     "resources": {"requests": {"storage": "1Gi"}}},
        })
        objs.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta(name, "ps-shard", namespace),
            "spec": {
                # exactly one pod per shard: the range's durable state
                # lives in its checkpoint, and two writers of one range
                # would fork the model
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": meta,
                    "spec": {
                        "containers": [container],
                        "volumes": [{
                            "name": "ckpt",
                            "persistentVolumeClaim":
                                {"claimName": f"{name}-ckpt"},
                        }],
                    },
                },
            },
        })
        objs.append({
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta(name, "ps-shard", namespace),
            "spec": {"selector": {"app": name},
                     "ports": [{"name": "ps", "port": PS_SHARD_PORT,
                                "targetPort": PS_SHARD_PORT}]},
        })
        if standby_map is None:
            continue
        sb_name = f"{name}-standby"
        sb_env = [
            {"name": "ASYNC_SHARD_INDEX", "value": str(i)},
            {"name": "ASYNC_SHARD_COUNT", "value": str(shards)},
            {"name": "ASYNC_SHARD_D", "value": str(d)},
            {"name": "ASYNC_SHARD_N", "value": str(n)},
            {"name": "ASYNC_SHARD_ALGO", "value": "asgd"},
            {"name": "ASYNC_SHARD_BIND_PORT", "value": str(PS_SHARD_PORT)},
            {"name": "ASYNC_SHARD_CFG", "value": _json.dumps(cfg)},
            {"name": "ASYNC_SHARD_ROLE", "value": "standby"},
            # no checkpoint, no PVC: a standby's state arrives over the
            # replication stream (REPL_SYNC on every boot/reconnect)
            {"name": "ASYNC_SHARD_CKPT", "value": ""},
            {"name": "ASYNC_SHARD_MAP", "value": _json.dumps(smap)},
            {"name": "ASYNC_SHARD_ELASTIC", "value": "0"},
            {"name": "ASYNC_SHARD_EPOCH", "value": "1"},
            {"name": "ASYNCTPU_ASYNC_FENCE_ENABLED", "value": "1"},
        ]
        sb_container = _container(
            f"ps-shard-{i}-standby", image,
            ["python", "-m", "asyncframework_tpu.parallel.shardgroup"],
            ports=[PS_SHARD_PORT], resources=resources,
        )
        sb_container["env"] = sb_env + sb_container.get("env", [])
        sb_meta = _pod_meta(sb_name)
        sb_meta["labels"]["shard"] = str(i)
        sb_meta["labels"]["role"] = "standby"
        objs.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta(sb_name, "ps-shard-standby", namespace),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": sb_name}},
                "template": {
                    "metadata": sb_meta,
                    "spec": {"containers": [sb_container]},
                },
            },
        })
        objs.append({
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta(sb_name, "ps-shard-standby", namespace),
            "spec": {"selector": {"app": sb_name},
                     "ports": [{"name": "ps", "port": PS_SHARD_PORT,
                                "targetPort": PS_SHARD_PORT}]},
        })
    return objs


def render_app_job(name: str, argv: List[str], num_processes: int,
                   namespace: str = "default", image: str = DEFAULT_IMAGE,
                   supervise: bool = True,
                   wait_timeout_s: float = 3600.0) -> List[dict]:
    """One application as a k8s Job: the pod runs the stock ``--master``
    CLI against the master Service and exits 0 only on FINISHED -- the
    ``KubernetesClientApplication.Client.run`` role with the submission
    CLI as the driver process."""
    if not name or not argv:
        raise ValueError("app job needs a name and a recipe argv")
    cmd = ["python", "-m", "asyncframework_tpu.cli",
           "--master", f"async-master:{RPC_PORT}",
           "--processes", str(num_processes),
           "--wait-timeout", str(wait_timeout_s)]
    if supervise:
        cmd.append("--supervise")
    cmd += list(argv)
    return [{
        "apiVersion": "batch/v1", "kind": "Job",
        "metadata": _meta(f"async-app-{name}", "app", namespace),
        "spec": {
            # the daemons own retries (--supervise); a failed SUBMISSION
            # should surface, not loop
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {"app": f"async-app-{name}"}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [_container("submit", image, cmd)],
                },
            },
        },
    }]


#: the observer's own fleet-view endpoint (bin/async-mon --port)
OBSERVER_PORT = 9096

#: the per-role apps whose pods carry the PR 7 scrape wiring
#: (ASYNCTPU_ASYNC_METRICS_PORT env + prometheus.io/* annotations) --
#: render_observer points a metrics Service at each so the collector
#: has a stable DNS name per role
OBSERVER_SCRAPE_APPS = (
    ("master", "master", "async-master"),
    ("worker", "worker", "async-worker"),
    ("frontend", "frontend", "async-serve-frontend"),
    ("replica", "replica", "async-serve-replica"),
)


def render_observer(namespace: str = "default",
                    image: str = DEFAULT_IMAGE,
                    scrape_apps: Optional[List] = None,
                    extra_endpoints: str = "",
                    history_pvc: str = "async-observer-history"
                    ) -> List[dict]:
    """Cluster-observer tier (metrics/observer.py + bin/async-mon): one
    collector Deployment + its fleet-view Service + the durable
    run-history PVC, plus one **metrics Service** per scraped role.

    The metrics Services are how the collector consumes the PR 7 scrape
    wiring without an API-server client (this adapter renders, it does
    not watch): every daemon pod already listens on ``METRICS_PORT``
    (the ``ASYNCTPU_ASYNC_METRICS_PORT`` env the pod templates ship)
    and carries ``prometheus.io/*`` annotations; each metrics Service
    selects one role's pod label and exposes that port under a stable
    DNS name, and the collector's ``--endpoints`` points at them.
    ``extra_endpoints`` appends operator-supplied
    ``name=role@host:port`` entries (e.g. a PS shard group)."""
    apps = list(scrape_apps if scrape_apps is not None
                else OBSERVER_SCRAPE_APPS)
    objs: List[dict] = []
    endpoints = []
    for (name, role, app) in apps:
        svc = f"async-metrics-{name}"
        objs.append({
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta(svc, "observer", namespace),
            "spec": {
                "selector": {"app": app},
                "ports": [{"name": "metrics", "port": METRICS_PORT,
                           "targetPort": METRICS_PORT}],
            },
        })
        endpoints.append(f"{name}={role}@{svc}:{METRICS_PORT}")
    if extra_endpoints:
        endpoints.append(extra_endpoints)
    cmd = ["python", "-m", "asyncframework_tpu.metrics.observer",
           "--endpoints", ";".join(endpoints),
           "--history-dir", "/history",
           "--port", str(OBSERVER_PORT)]
    objs.append({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": _meta(history_pvc, "observer", namespace),
        "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "5Gi"}}},
    })
    # the collector's own scrape annotations point at its fleet-view
    # port (it serves /metrics THERE, not on the per-role 9095 the
    # stock pod meta advertises -- metrics=False below skips that env)
    observer_pod_meta = {
        "labels": {"app": "async-observer"},
        "annotations": {
            "prometheus.io/scrape": "true",
            "prometheus.io/port": str(OBSERVER_PORT),
            "prometheus.io/path": "/metrics",
        },
    }
    objs.append({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": _meta("async-observer", "observer", namespace),
        "spec": {
            "replicas": 1,  # ONE collector owns the run-history store
            "selector": {"matchLabels": {"app": "async-observer"}},
            "template": {
                "metadata": observer_pod_meta,
                "spec": {
                    "containers": [_container(
                        "observer", image, cmd,
                        ports=[OBSERVER_PORT],
                        volume_mounts=[{"name": "history",
                                        "mountPath": "/history"}],
                        # the collector's OWN telemetry rides the
                        # --port fleet-view server; a second 9095
                        # endpoint would just duplicate it
                        metrics=False,
                    )],
                    "volumes": [{
                        "name": "history",
                        "persistentVolumeClaim": {
                            "claimName": history_pvc},
                    }],
                },
            },
        },
    })
    objs.append({
        "apiVersion": "v1", "kind": "Service",
        "metadata": _meta("async-observer", "observer", namespace),
        "spec": {"selector": {"app": "async-observer"},
                 "ports": [{"name": "fleet", "port": OBSERVER_PORT,
                            "targetPort": OBSERVER_PORT}]},
    })
    return objs


def render_cluster(workers: int, namespace: str = "default",
                   image: str = DEFAULT_IMAGE, ha_replicas: int = 1,
                   cores: int = 1, topic_server: bool = False,
                   serving: int = 0,
                   serving_ps: Optional[str] = None,
                   relay_fanout: int = 0,
                   ps_shards: int = 0, ps_d: int = 0, ps_n: int = 0,
                   ps_workers: int = 8,
                   ps_standbys: int = 0,
                   observer: bool = False) -> Dict[str, str]:
    """The whole standalone topology as {filename: yaml} -- apply with
    ``kubectl apply -f <dir>``."""
    out = {
        "master.yaml": to_yaml(render_master(
            namespace, image, ha_replicas=ha_replicas
        )),
        "workers.yaml": to_yaml(render_workers(
            workers, namespace, image, cores=cores
        )),
    }
    if topic_server:
        out["topic-server.yaml"] = to_yaml(
            render_topic_server(namespace, image)
        )
    if serving > 0:
        out["serving.yaml"] = to_yaml(render_serving(
            serving, serving_ps or f"async-master:{RPC_PORT}",
            namespace, image, relay_fanout=relay_fanout,
        ))
    if ps_shards > 0:
        out["ps-shards.yaml"] = to_yaml(render_ps_shards(
            ps_shards, ps_d, ps_n, workers=ps_workers,
            namespace=namespace, image=image, standbys=ps_standbys,
        ))
    if observer:
        apps = list(OBSERVER_SCRAPE_APPS)
        # shard pods carry the same scrape wiring; give each shard a
        # metrics Service too so the collector sees every range's ps.*
        for i in range(ps_shards):
            apps.append((f"ps-shard-{i}", "ps", f"async-ps-shard-{i}"))
        out["observer.yaml"] = to_yaml(render_observer(
            namespace, image, scrape_apps=apps,
        ))
    return out


def to_yaml(objs: List[dict]) -> str:
    return "---\n".join(
        yaml.safe_dump(o, sort_keys=False, default_flow_style=False)
        for o in objs
    )


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m asyncframework_tpu.deploy.k8s render --out DIR
    --workers N [--ha N] [--image I] [--topic-server]`` and
    ``... app --name n --processes P -- <recipe argv>``."""
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser("async-k8s")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("render", help="render the cluster manifests")
    r.add_argument("--out", required=True)
    r.add_argument("--workers", type=int, required=True)
    r.add_argument("--ha", type=int, default=1, metavar="REPLICAS")
    r.add_argument("--image", default=DEFAULT_IMAGE)
    r.add_argument("--cores", type=int, default=1)
    r.add_argument("--namespace", default="default")
    r.add_argument("--topic-server", action="store_true")
    r.add_argument("--serving", type=int, default=0, metavar="REPLICAS",
                   help="also render the serving tier (async-serve "
                        "frontend + this many predict replica pods)")
    r.add_argument("--serving-ps", default=None, metavar="HOST:PORT",
                   help="PS address the serving replicas SUBSCRIBE to")
    r.add_argument("--relay-fanout", type=int, default=0, metavar="K",
                   help="render the serving replicas as a relaycast "
                        "tree of this arity (StatefulSet + headless "
                        "Service; 0 = classic direct-SUBSCRIBE "
                        "Deployment)")
    r.add_argument("--ps-shards", type=int, default=0, metavar="N",
                   help="also render an N-shard parameter-server group "
                        "(per-shard pod + Service + checkpoint PVC; "
                        "workers HELLO async-ps-shard-0)")
    r.add_argument("--ps-d", type=int, default=0,
                   help="model width the shard group range-partitions")
    r.add_argument("--ps-n", type=int, default=0,
                   help="dataset rows the shard group's run covers")
    r.add_argument("--ps-workers", type=int, default=8,
                   help="logical workers the shard group's primary gates")
    r.add_argument("--observer", action="store_true",
                   help="also render the cluster-observer tier "
                        "(async-mon collector Deployment + run-history "
                        "PVC + per-role metrics Services)")
    a = sub.add_parser("app", help="render one application Job")
    a.add_argument("--out", required=True)
    a.add_argument("--name", required=True)
    a.add_argument("--processes", type=int, default=2)
    a.add_argument("--image", default=DEFAULT_IMAGE)
    a.add_argument("--namespace", default="default")
    a.add_argument("--no-supervise", action="store_true")
    a.add_argument("argv", nargs="+", help="recipe argv after --")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    if args.cmd == "render":
        files = render_cluster(
            args.workers, namespace=args.namespace, image=args.image,
            ha_replicas=args.ha, cores=args.cores,
            topic_server=args.topic_server,
            serving=args.serving, serving_ps=args.serving_ps,
            relay_fanout=args.relay_fanout,
            ps_shards=args.ps_shards, ps_d=args.ps_d, ps_n=args.ps_n,
            ps_workers=args.ps_workers,
            observer=args.observer,
        )
    else:
        files = {f"app-{args.name}.yaml": to_yaml(render_app_job(
            args.name, args.argv, args.processes,
            namespace=args.namespace, image=args.image,
            supervise=not args.no_supervise,
        ))}
    for fname, text in files.items():
        path = os.path.join(args.out, fname)
        with open(path, "w") as f:
            f.write(text)
        print(path)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
