"""Submission client: talk to a standalone Master.

Parity: ``deploy/client/StandaloneAppClient.scala:44`` + the submit side of
``SparkSubmit.scala:71`` -- register an application, learn its id, poll its
state.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from asyncframework_tpu.net import ClientSession, RetryPolicy
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net.frame import recv_msg as _recv_msg
from asyncframework_tpu.net.frame import send_msg as _send_msg


class MasterClient:
    def __init__(self, host: str, port: int,
                 standby_masters: Optional[List[str]] = None,
                 retry: Optional[RetryPolicy] = None,
                 session: Optional[ClientSession] = None):
        self._addrs = [(host, int(port))]
        for addr in standby_masters or []:
            h, p = addr.rsplit(":", 1)
            self._addrs.append((h, int(p)))
        self._mi = 0
        self.retry = retry if retry is not None else RetryPolicy.from_conf()
        self.session = session if session is not None else ClientSession()

    @property
    def addr(self):
        return self._addrs[self._mi]

    def _call(self, msg: dict) -> dict:
        """RPC to the active master under the shared retry policy; each
        attempt rotates through every configured master on connection
        failure or a STANDBY reply (reference parity: StandaloneAppClient
        tries every master URL).  Mutating ops arrive pre-stamped with a
        (sid, seq), so the retried SUBMIT of a lost reply is answered from
        the master's dedup window -- exactly one app, as long as the SAME
        master answers the retry (windows are in-memory: a retry that
        lands on a freshly promoted standby is at-least-once again)."""

        def attempt() -> dict:
            last_err: Optional[Exception] = None
            for _ in range(len(self._addrs)):
                try:
                    with _frame.connect(self.addr, timeout=10) as s:
                        _send_msg(s, msg)
                        reply, _ = _recv_msg(s)
                except (ConnectionError, OSError) as e:
                    last_err = e
                    self._mi = (self._mi + 1) % len(self._addrs)
                    continue
                if reply.get("op") == "STANDBY":
                    self._mi = (self._mi + 1) % len(self._addrs)
                    continue
                if reply.get("op") == "ERR":
                    raise RuntimeError(f"master error: {reply.get('msg')}")
                return reply
            raise ConnectionError(
                f"no active master among {self._addrs}"
            ) from last_err

        return self.retry.call(attempt)

    def submit(self, argv: List[str], num_processes: int,
               env: Optional[Dict[str, str]] = None,
               supervise: bool = False) -> str:
        """``supervise``: the reference's ``spark-submit --supervise`` --
        a worker daemon relaunches an executor that exits nonzero (bounded
        restarts), instead of reporting the failure."""
        reply = self._call(self.session.stamp({
            "op": "SUBMIT_APP", "argv": list(argv),
            "num_processes": int(num_processes), "env": env or {},
            "supervise": bool(supervise),
        }))
        return reply["app_id"]

    def status(self, app_id: str) -> dict:
        return self._call({"op": "APP_STATUS", "app_id": app_id})

    def workers(self) -> dict:
        return self._call({"op": "LIST_WORKERS"})["workers"]

    def kill(self, app_id: str) -> dict:
        return self._call(self.session.stamp(
            {"op": "KILL_APP", "app_id": app_id}
        ))


def _client(master: str) -> MasterClient:
    """``master`` may be a comma-separated HA list: primary,standby,..."""
    primary, *standbys = master.split(",")
    host, port = primary.rsplit(":", 1)
    return MasterClient(host, int(port), standby_masters=standbys)


def submit_app(master: str, argv: List[str], num_processes: int,
               env: Optional[Dict[str, str]] = None) -> str:
    return _client(master).submit(argv, num_processes, env)


def wait_app(master: str, app_id: str, timeout_s: float = 300.0) -> dict:
    """Poll until the app reaches a terminal state (FINISHED/FAILED/LOST).

    Rides through a master failover: during the takeover window every
    configured master refuses or answers STANDBY for a few hundred ms --
    the poll keeps retrying until the deadline (the Worker daemon's
    heartbeat loop does the same)."""
    cl = _client(master)
    deadline = time.monotonic() + timeout_s
    st = {"state": "UNKNOWN"}  # non-positive timeout: loop never runs
    while time.monotonic() < deadline:
        try:
            st = cl.status(app_id)
        except (ConnectionError, OSError):
            time.sleep(0.25)
            continue
        if st["state"] in ("FINISHED", "FAILED", "LOST", "KILLED"):
            return st
        time.sleep(0.25)
    raise TimeoutError(f"app {app_id} still {st['state']}")
