"""Submission client: talk to a standalone Master.

Parity: ``deploy/client/StandaloneAppClient.scala:44`` + the submit side of
``SparkSubmit.scala:71`` -- register an application, learn its id, poll its
state.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List, Optional

from asyncframework_tpu.parallel.ps_dcn import _recv_msg, _send_msg


class MasterClient:
    def __init__(self, host: str, port: int):
        self.addr = (host, int(port))

    def _call(self, msg: dict) -> dict:
        with socket.create_connection(self.addr, timeout=10) as s:
            _send_msg(s, msg)
            reply, _ = _recv_msg(s)
        if reply.get("op") == "ERR":
            raise RuntimeError(f"master error: {reply.get('msg')}")
        return reply

    def submit(self, argv: List[str], num_processes: int,
               env: Optional[Dict[str, str]] = None) -> str:
        reply = self._call({
            "op": "SUBMIT_APP", "argv": list(argv),
            "num_processes": int(num_processes), "env": env or {},
        })
        return reply["app_id"]

    def status(self, app_id: str) -> dict:
        return self._call({"op": "APP_STATUS", "app_id": app_id})

    def workers(self) -> dict:
        return self._call({"op": "LIST_WORKERS"})["workers"]

    def kill(self, app_id: str) -> dict:
        return self._call({"op": "KILL_APP", "app_id": app_id})


def submit_app(master: str, argv: List[str], num_processes: int,
               env: Optional[Dict[str, str]] = None) -> str:
    host, port = master.rsplit(":", 1)
    return MasterClient(host, int(port)).submit(argv, num_processes, env)


def wait_app(master: str, app_id: str, timeout_s: float = 300.0) -> dict:
    """Poll until the app reaches a terminal state (FINISHED/FAILED/LOST)."""
    host, port = master.rsplit(":", 1)
    cl = MasterClient(host, int(port))
    deadline = time.monotonic() + timeout_s
    st = {"state": "UNKNOWN"}  # non-positive timeout: loop never runs
    while time.monotonic() < deadline:
        st = cl.status(app_id)
        if st["state"] in ("FINISHED", "FAILED", "LOST", "KILLED"):
            return st
        time.sleep(0.25)
    raise TimeoutError(f"app {app_id} still {st['state']}")
