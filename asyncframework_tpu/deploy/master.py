"""Standalone Master daemon: worker registry, app scheduling, recovery.

Parity (studied, not copied): ``deploy/master/Master.scala:41`` -- workers
REGISTER and heartbeat; applications are submitted with a requested process
count; the master assigns processes to alive workers and tells each worker
to launch an executor process; lost workers are detected by heartbeat
timeout; master state survives restart through a persistence engine
(``ZooKeeperPersistenceEngine.scala:34`` -- here a single-node
atomic-rename JSON file fills the PersistenceEngine role; the interface
point is the same, the consensus service is out of scope on one machine).

TPU-first deltas: the wire is the same length-prefixed JSON/TCP framing as
the DCN parameter server (``parallel/ps_dcn.py``) -- one transport for the
whole control plane, no RPC mesh.  A launched app process receives the
``ASYNCTPU_*`` env (coordinator address, process count, process id), so a
scheduled app IS an ``async-cluster`` run placed by the master: SPMD jobs
join a global mesh, ``asgd`` jobs form the PS + worker-pusher topology.

Protocol (all messages carry ``op``):
  worker -> master: REGISTER_WORKER {worker_id, host, port, cores}
                    HEARTBEAT {worker_id}
                    EXECUTOR_EXIT {worker_id, app_id, proc_id, returncode}
  client -> master: SUBMIT_APP {argv, num_processes, env}
                    APP_STATUS {app_id} | LIST_WORKERS | KILL_APP {app_id}
  master -> worker: (reply to heartbeat) LAUNCH orders piggybacked
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from asyncframework_tpu.cluster import _free_port
from asyncframework_tpu.net import DedupWindow
from asyncframework_tpu.net import protocol as _protocol
from asyncframework_tpu.net.frame import recv_msg as _recv_msg
from asyncframework_tpu.net.frame import send_msg as _send_msg

#: ops that mutate master state: a retried SUBMIT_APP must not schedule the
#: app twice, a retried KILL_APP is answered from cache (net/session.py)
# the (sid, seq)-gated verbs come from the declared wire-protocol table
# (net/protocol.py): the table is the single place an op's exactly-once
# obligation lives, and bin/async-lint checks this derivation stays put
_MUTATING_OPS = _protocol.dedup_gated_ops(_protocol.MASTER)

# NOTE on coordinator ports: _free_port binds-then-releases on the master's
# host, so (a) another process could steal the port before the app binds it
# (submit again on that rare failure) and (b) the probe assumes process 0
# lands on a host where the port is equally free -- both acceptable for the
# single-machine standalone story this layer targets.

WORKER_TIMEOUT_S = 10.0


class Master:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persistence_dir: Optional[str] = None,
        worker_timeout_s: float = WORKER_TIMEOUT_S,
        ha: bool = False,
        ui_port: Optional[int] = None,
        ui_host: str = "127.0.0.1",
    ):
        self.host = host
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        # worker_id -> {host, port, cores, last_seen, alive}
        self.workers: Dict[str, Dict] = {}
        # app_id -> {argv, env, num_processes, state, assignments, exits}
        self.apps: Dict[str, Dict] = {}
        self._app_seq = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._worker_timeout_s = worker_timeout_s
        if persistence_dir:
            os.makedirs(persistence_dir, exist_ok=True)
            self._persist_path = os.path.join(
                persistence_dir, "master-state.json"
            )
        else:
            self._persist_path = None
        # HA: masters race for the flock lease; only the winner recovers
        # state and serves -- standbys answer STANDBY until they win
        # (ZooKeeperLeaderElectionAgent.scala:26 role; see deploy/leader.py)
        if ha and self._persist_path is None:
            raise ValueError("ha masters need a persistence_dir (the lease "
                             "file and shared state live there)")
        self.election = None
        if ha:
            from asyncframework_tpu.deploy.leader import FileLeaderElection

            self.election = FileLeaderElection(
                os.path.join(persistence_dir, "master.lock")
            )
            self.active = False
        else:
            self.active = True
            self._recover()
        self._ui_port = ui_port
        self._ui_host = ui_host
        self._ui = None
        from asyncframework_tpu.conf import NET_DEDUP_WINDOW, global_conf

        self._dedup = DedupWindow(window=global_conf().get(NET_DEDUP_WINDOW))

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Master":
        t = threading.Thread(target=self._accept_loop, name="master-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        t2 = threading.Thread(target=self._reaper_loop, name="master-reaper",
                              daemon=True)
        t2.start()
        self._threads.append(t2)
        if self.election is not None:
            t3 = threading.Thread(target=self._election_loop,
                                  name="master-election", daemon=True)
            t3.start()
            self._threads.append(t3)
        if self._ui_port is not None:
            self._ui = MasterUIServer(self, port=self._ui_port,
                                      host=self._ui_host)
        return self

    @property
    def dedup_hits(self) -> int:
        """Retried mutating RPCs answered from the dedup window."""
        return self._dedup.hits

    def status_snapshot(self) -> Dict:
        """Cluster state for the web UI / ops tooling (MasterPage role)."""
        with self._lock:
            return {
                "address": self.address,
                "active": self.active,
                "workers": {
                    wid: {"host": w["host"], "cores": w["cores"],
                          "alive": w["alive"]}
                    for wid, w in self.workers.items()
                },
                "apps": {
                    app_id: {
                        "state": a["state"],
                        "num_processes": a["num_processes"],
                        "supervise": a.get("supervise", False),
                        "exits": dict(a["exits"]),
                        "argv": list(a["argv"])[:6],
                    }
                    for app_id, a in self.apps.items()
                },
            }

    def _election_loop(self) -> None:
        if not self.election.acquire_blocking(self._stop,
                                              holder=self.address):
            return
        with self._lock:
            # takeover recovery: worker daemons and their executors are
            # still alive (only the old MASTER died), so RUNNING apps stay
            # RUNNING -- the workers' EXECUTOR_EXIT reports will land here
            self._recover(takeover=True)
            self.active = True

    def stop(self) -> None:
        self._stop.set()
        if self.election is not None:
            self.election.release()
        if self._ui is not None:
            self._ui.stop()
        try:
            self._srv.close()
        except OSError:
            pass

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ---------------------------------------------------------- persistence
    def _persist(self) -> None:
        """PersistenceEngine role: apps + registered workers survive a
        master restart (atomic rename; heartbeats re-validate liveness)."""
        if self._persist_path is None:
            return
        state = {
            "workers": {
                wid: {k: w[k] for k in ("host", "port", "cores")}
                for wid, w in self.workers.items()
            },
            "apps": {
                aid: {
                    "argv": a["argv"], "env": a["env"],
                    "num_processes": a["num_processes"],
                    "state": a["state"],
                    # exits persist too: an HA takeover that reset them
                    # could never complete an app whose executors partly
                    # exited before the failover (the worker's ACKed report
                    # is never resent)
                    "exits": dict(a["exits"]),
                }
                for aid, a in self.apps.items()
            },
            "app_seq": self._app_seq,
        }
        tmp = self._persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        # fsync file + rename + fsync directory: an HA takeover after host
        # power loss must see the registry the dead master believed it had
        from asyncframework_tpu.checkpoint import durable_replace

        durable_replace(tmp, self._persist_path)

    def _recover(self, takeover: bool = False) -> None:
        if self._persist_path is None or not os.path.exists(
            self._persist_path
        ):
            return
        with open(self._persist_path) as f:
            state = json.load(f)
        now = time.monotonic()
        for wid, w in state.get("workers", {}).items():
            # recovered workers must re-heartbeat before they count as alive
            self.workers[wid] = {
                **w, "last_seen": now - self._worker_timeout_s, "alive": False
            }
        for aid, a in state.get("apps", {}).items():
            # cold restart: RUNNING apps lost their master mid-flight with
            # no standby watching -- surface LOST instead of pretending.
            # HA takeover: the executors belong to live worker daemons that
            # are about to rotate their heartbeats here, so the app is
            # still RUNNING and its exits will arrive.
            st = a["state"]
            if st in ("RUNNING", "LAUNCHING"):
                st = "RUNNING" if takeover else "LOST"
            self.apps[aid] = {
                **a, "assignments": [],
                "exits": dict(a.get("exits") or {}), "state": st,
            }
        self._app_seq = int(state.get("app_seq", 0))

    # -------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 name="master-conn", daemon=True)
            t.start()

    def _reaper_loop(self) -> None:
        """Worker-loss detection (the reference's CheckForWorkerTimeOut)."""
        while not self._stop.wait(self._worker_timeout_s / 4):
            now = time.monotonic()
            with self._lock:
                for wid, w in self.workers.items():
                    if w["alive"] and now - w["last_seen"] > self._worker_timeout_s:
                        w["alive"] = False

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                header, _payload = _recv_msg(conn)
                # handler errors must come back as ERR replies -- letting
                # them fall into the connection-error handler would close
                # the socket without replying ("peer closed" at the client,
                # with the real cause invisible)
                cached = (self._dedup.check(header)
                          if header.get("op") in _MUTATING_OPS else None)
                if cached is not None:
                    # duplicate of an applied mutation (reply lost on the
                    # wire): re-answer from cache -- one SUBMIT_APP retry
                    # storm must still schedule exactly one app
                    _send_msg(conn, cached[0])
                    continue
                try:
                    reply = self._handle(header)
                except Exception as e:  # noqa: BLE001 - reported to caller
                    reply = {"op": "ERR",
                             "msg": f"{type(e).__name__}: {e}"}
                if (header.get("op") in _MUTATING_OPS
                        and reply.get("op") not in ("ERR", "STANDBY")):
                    # STANDBY is a routing answer, not an outcome; caching
                    # it would pin a client to the loser after failover
                    self._dedup.record(header, reply)
                _send_msg(conn, reply)
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    # ------------------------------------------------------------- handlers
    def _handle(self, msg: dict) -> dict:
        op = msg.get("op")
        if not self.active:
            # standby: refuse everything until the lease is won (reference
            # parity: standby masters reject RPCs with a not-leader error)
            return {"op": "STANDBY", "master": self.address}
        if op == "REGISTER_WORKER":
            with self._lock:
                self.workers[msg["worker_id"]] = {
                    "host": msg["host"], "port": int(msg["port"]),
                    "cores": int(msg.get("cores", 1)),
                    "last_seen": time.monotonic(), "alive": True,
                }
                self._persist()
            return {"op": "REGISTERED", "master": self.address}
        if op == "HEARTBEAT":
            with self._lock:
                w = self.workers.get(msg["worker_id"])
                if w is None:
                    # reference parity: an unknown heartbeat asks the
                    # worker to re-register (master may have restarted)
                    return {"op": "RECONNECT"}
                w["last_seen"] = time.monotonic()
                w["alive"] = True
            return {"op": "ACK"}
        if op == "EXECUTOR_EXIT":
            with self._lock:
                app = self.apps.get(msg["app_id"])
                if app is not None:
                    app["exits"][str(msg["proc_id"])] = int(msg["returncode"])
                    if (
                        len(app["exits"]) >= app["num_processes"]
                        and app["state"] in ("LAUNCHING", "RUNNING")
                    ):
                        # KILLED stays KILLED: the kill's terminations
                        # produce nonzero exits that must not relabel it
                        bad = [rc for rc in app["exits"].values() if rc]
                        app["state"] = "FAILED" if bad else "FINISHED"
                    # persist EVERY exit, not just the terminal one: the
                    # worker never resends an ACKed report, so a standby
                    # recovering mid-app must find partial exits on disk
                    self._persist()
            return {"op": "ACK"}
        if op == "SUBMIT_APP":
            return self._submit(msg)
        if op == "KILL_APP":
            return self._kill(msg["app_id"])
        if op == "APP_STATUS":
            with self._lock:
                app = self.apps.get(msg["app_id"])
                if app is None:
                    return {"op": "ERR", "msg": "no such app"}
                # copies, not live references: serialization happens after
                # the lock is released, racing EXECUTOR_EXIT mutations
                return {
                    "op": "APP", "app_id": msg["app_id"],
                    "state": app["state"],
                    "assignments": [dict(a) for a in app["assignments"]],
                    "exits": dict(app["exits"]),
                }
        if op == "LIST_WORKERS":
            with self._lock:
                return {
                    "op": "WORKERS",
                    "workers": {
                        wid: {"host": w["host"], "cores": w["cores"],
                              "alive": w["alive"]}
                        for wid, w in self.workers.items()
                    },
                }
        return {"op": "ERR", "msg": f"bad op {op!r}"}

    def _submit(self, msg: dict) -> dict:
        """Schedule: round-robin the app's processes over alive workers
        (spreadOutApps-style placement), then order launches."""
        nproc = int(msg["num_processes"])
        with self._lock:
            alive = [
                (wid, w) for wid, w in self.workers.items() if w["alive"]
            ]
            if not alive:
                return {"op": "ERR", "msg": "no alive workers"}
            self._app_seq += 1
            app_id = f"app-{self._app_seq:04d}"
            coord_port = _free_port()
            coord = f"{alive[0][1]['host']}:{coord_port}"
            assignments = []
            for proc_id in range(nproc):
                wid, w = alive[proc_id % len(alive)]
                assignments.append({"proc_id": proc_id, "worker_id": wid})
            self.apps[app_id] = {
                "argv": list(msg["argv"]), "env": dict(msg.get("env") or {}),
                "num_processes": nproc, "state": "LAUNCHING",
                "assignments": assignments, "exits": {},
                "supervise": bool(msg.get("supervise")),
            }
            self._persist()
            app = self.apps[app_id]
        # order launches outside the lock (worker RPCs)
        ok = True
        for a in assignments:
            w = self.workers[a["worker_id"]]
            env = dict(app["env"])
            env.update(
                ASYNCTPU_COORDINATOR=coord,
                ASYNCTPU_NUM_PROCESSES=str(nproc),
                ASYNCTPU_PROCESS_ID=str(a["proc_id"]),
            )
            try:
                with socket.create_connection(
                    (w["host"], w["port"]), timeout=10
                ) as ws:
                    _send_msg(ws, {
                        "op": "LAUNCH", "app_id": app_id,
                        "proc_id": a["proc_id"], "argv": app["argv"],
                        "env": env, "master": self.address,
                        "supervise": app.get("supervise", False),
                    })
                    _recv_msg(ws)
            except (ConnectionError, OSError):
                ok = False
        if not ok:
            # reclaim executors already launched: half an SPMD app would
            # otherwise sit in distributed bring-up holding devices
            self._order_kills(app_id, assignments)
        with self._lock:
            # only LAUNCHING -> RUNNING: a fast-exiting app may already have
            # reached FINISHED/FAILED via EXECUTOR_EXIT, and stamping RUNNING
            # over a terminal state would strand it forever
            if app["state"] == "LAUNCHING":
                app["state"] = "RUNNING" if ok else "FAILED"
            self._persist()
        return {"op": "SUBMITTED", "app_id": app_id, "coordinator": coord}

    def _order_kills(self, app_id: str, assignments) -> None:
        for a in assignments:
            w = self.workers.get(a["worker_id"])
            if w is None:
                continue
            try:
                with socket.create_connection(
                    (w["host"], w["port"]), timeout=10
                ) as ws:
                    _send_msg(ws, {"op": "KILL", "app_id": app_id})
                    _recv_msg(ws)
            except (ConnectionError, OSError):
                continue  # worker gone; its procs die with it

    def _kill(self, app_id: str) -> dict:
        """KILL_APP: terminate every executor, mark the app KILLED."""
        with self._lock:
            app = self.apps.get(app_id)
            if app is None:
                return {"op": "ERR", "msg": "no such app"}
            assignments = [dict(a) for a in app["assignments"]]
        self._order_kills(app_id, assignments)
        with self._lock:
            if app["state"] in ("LAUNCHING", "RUNNING", "LOST"):
                app["state"] = "KILLED"
                self._persist()
        return {"op": "KILLED", "app_id": app_id}


_UI_HTML = """<!doctype html><html><head><title>async master</title>
<meta http-equiv="refresh" content="2">
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 10px;text-align:left}
.ok{color:#070}.bad{color:#b00}</style></head><body>
<h2>async master <span id="addr"></span></h2>
<h3>workers</h3><table id="w"><tr><th>id</th><th>host</th><th>cores</th>
<th>alive</th></tr></table>
<h3>applications</h3><table id="a"><tr><th>id</th><th>state</th>
<th>procs</th><th>supervise</th><th>exits</th><th>argv</th></tr></table>
<script>
// textContent only: app argv and worker hosts are CLIENT-supplied strings
// and must never be interpreted as markup in the operator's browser
function row(tbl, cells, cls) {
 const r = tbl.insertRow();
 cells.forEach((v, i) => {
  const c = r.insertCell();
  c.textContent = String(v);
  if (cls && cls[i]) c.className = cls[i];
 });
}
fetch('/api/status').then(r=>r.json()).then(s=>{
 document.getElementById('addr').textContent=
   s.address+(s.active?' (active)':' (standby)');
 const w=document.getElementById('w');
 for(const [id,x] of Object.entries(s.workers))
  row(w, [id, x.host, x.cores, x.alive],
      [null, null, null, x.alive ? 'ok' : 'bad']);
 const a=document.getElementById('a');
 for(const [id,x] of Object.entries(s.apps))
  row(a, [id, x.state, x.num_processes, x.supervise,
          JSON.stringify(x.exits), x.argv.join(' ')]);
});
</script></body></html>"""


class MasterUIServer:
    """Master web page (``deploy/master/ui/MasterPage.scala`` role): the
    cluster's workers and applications over plain HTTP -- ``/api/status``
    JSON plus an auto-refreshing HTML table at ``/``."""

    def __init__(self, master: "Master", port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server
        import json as _json

        outer = master

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/api/status":
                    body = _json.dumps(outer.status_snapshot()).encode()
                    self._send(200, body, "application/json")
                elif self.path in ("/", "/index.html"):
                    self._send(200, _UI_HTML.encode(), "text/html")
                else:
                    self._send(404, b"not found", "text/plain")

            def log_message(self, *a):  # quiet: no stderr per request
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._ui_thread = threading.Thread(
            target=self._httpd.serve_forever, name="master-ui",
            daemon=True)
        self._ui_thread.start()

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    import argparse
    import sys

    p = argparse.ArgumentParser("async-master")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7077)
    p.add_argument("--persistence-dir", default=None)
    p.add_argument("--ha", action="store_true",
                   help="race for the persistence-dir lease; serve as "
                        "standby until won (kill the active master and "
                        "this one takes over)")
    p.add_argument("--ui-port", type=int, default=None,
                   help="serve the master status page on this port")
    p.add_argument("--ui-host", default=None,
                   help="bind address for the status page (default "
                        "0.0.0.0 when --ui-port is set: a UI you asked "
                        "for is a UI you can reach from off-box)")
    args = p.parse_args(argv)
    from asyncframework_tpu.net import faults

    faults.maybe_install_from_conf()  # chaos runs configure daemons by env
    from asyncframework_tpu.metrics.live import start_telemetry_from_conf

    start_telemetry_from_conf("master")  # async.metrics.port gates it
    ui_host = args.ui_host
    if ui_host is None:
        ui_host = "0.0.0.0" if args.ui_port is not None else "127.0.0.1"
    m = Master(args.host, args.port, args.persistence_dir,
               ha=args.ha, ui_port=args.ui_port, ui_host=ui_host).start()
    print(f"master listening on {m.address}"
          + (" (ha)" if args.ha else "")
          + (f" ui:{m._ui.port}" if m._ui is not None else ""), flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        m.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
