"""Single-machine leader election for standby masters.

Parity (studied, not copied): the reference's master HA is ZooKeeper leader
election + standby masters
(``deploy/master/ZooKeeperLeaderElectionAgent.scala:26``,
``ZooKeeperPersistenceEngine.scala:34``): masters race for an ephemeral
znode; the winner recovers state from the persistence engine and serves;
the losers answer every RPC with "not leader"; when the leader's session
dies the next master wins the race.

TPU-first single-node re-design: the ephemeral znode's two properties --
exclusive ownership and automatic release on process death -- are exactly
the semantics of an exclusive ``flock`` on a file in the persistence
directory.  A SIGKILLed master's lock is released by the kernel the instant
the process dies, no TTL clock to tune, no renewal thread, no split-brain
window (the consensus *service* stays out of scope on one machine, as the
Master's docstring already records; on a real multi-host deployment this
interface point is where etcd/ZK would plug in).

The holder also writes its address into the lock file so operators (and the
submission client's error messages) can see who is active -- the analog of
the znode payload.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from typing import Optional


class FileLeaderElection:
    """Exclusive-flock leadership over ``<dir>/master.lock``.

    ``try_acquire`` is non-blocking; ``acquire_blocking`` polls until won or
    stopped.  Leadership is held until :meth:`release` or process death.
    """

    def __init__(self, lock_path: str):
        self.lock_path = lock_path
        self._fd: Optional[int] = None

    @property
    def is_leader(self) -> bool:
        return self._fd is not None

    def try_acquire(self, holder: str = "") -> bool:
        if self._fd is not None:
            return True
        fd = os.open(self.lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        # won: record the holder for observability (never read for safety
        # decisions -- the flock itself is the source of truth)
        os.ftruncate(fd, 0)
        os.write(fd, json.dumps(
            {"holder": holder, "pid": os.getpid()}
        ).encode())
        os.fsync(fd)
        self._fd = fd
        return True

    def acquire_blocking(self, stop: Optional[threading.Event] = None,
                         holder: str = "", poll_s: float = 0.1) -> bool:
        """Poll until leadership is won; returns False if ``stop`` fired
        first.  Polling (not a blocking flock) keeps shutdown prompt."""
        while stop is None or not stop.is_set():
            if self.try_acquire(holder):
                return True
            time.sleep(poll_s)
        return False

    def holder_info(self) -> Optional[dict]:
        """Best-effort read of the current holder record (may be stale)."""
        try:
            with open(self.lock_path) as f:
                return json.loads(f.read() or "{}")
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None
