"""Standalone Worker daemon: registers with the master, launches executors.

Parity (studied, not copied): ``deploy/worker/Worker.scala:43`` -- register
with the master, heartbeat, receive LAUNCH orders, fork executor processes
(here: ``python -m asyncframework_tpu.cli`` with the app's argv and the
``ASYNCTPU_*`` env the master assigned), watch them, and report exits back.
An unknown-worker heartbeat reply (master restarted) triggers
re-registration, the reference's reconnect dance.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from asyncframework_tpu.net import RetryPolicy
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.utils.threads import guarded
from asyncframework_tpu.net.frame import recv_msg as _recv_msg
from asyncframework_tpu.net.frame import send_msg as _send_msg


class Worker:
    def __init__(
        self,
        master_host: str,
        master_port: int,
        worker_id: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        cores: int = 1,
        heartbeat_s: float = 1.0,
        launch_env_extra: Optional[Dict[str, str]] = None,
        standby_masters: Optional[List[str]] = None,
    ):
        # HA: the reference's workers take every master URL
        # (spark://h1:7077,h2:7077) and talk to whichever is leader; here
        # the list is [primary] + standby_masters and _master_call rotates
        # on connection failure or a STANDBY reply
        self._masters = [(master_host, int(master_port))]
        for addr in standby_masters or []:
            h, p = addr.rsplit(":", 1)
            self._masters.append((h, int(p)))
        self._mi = 0  # index of the master believed active
        self.worker_id = worker_id or f"worker-{os.getpid()}"
        self.cores = cores
        self.heartbeat_s = heartbeat_s
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.host = host
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        # app_id -> live Popen list (pruned as executors exit)
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._procs_lock = threading.Lock()
        self._killed: set = set()  # apps killed by order: never supervise
        self._launch_env_extra = dict(launch_env_extra or {})
        self.max_supervised_restarts = 3
        # master RPCs ride the shared retry policy; rotation across the HA
        # master list is the per-attempt body, so "no active master" is a
        # retryable condition with real backoff instead of a bare raise
        self._retry = RetryPolicy.from_conf()

    def _master_call(self, msg: dict,
                     retry: "RetryPolicy" = None) -> dict:
        """One RPC to the active master under the retry policy, rotating
        through the configured masters each attempt (STANDBY replies and
        connection failures both rotate).  Raises ConnectionError (via
        RetryError) when no configured master turns active in budget."""

        def attempt() -> dict:
            for _ in range(len(self._masters)):
                addr = self._masters[self._mi]
                try:
                    with _frame.connect(addr, timeout=10) as s:
                        _send_msg(s, msg)
                        reply, _ = _recv_msg(s)
                    if reply.get("op") != "STANDBY":
                        return reply
                except (ConnectionError, OSError):
                    pass
                self._mi = (self._mi + 1) % len(self._masters)
            raise ConnectionError(
                "no active master among "
                f"{[f'{h}:{p}' for h, p in self._masters]}"
            )

        return (retry or self._retry).call(attempt)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Worker":
        self._register()
        for fn, name in (
            (self._serve_loop, "worker-serve"),
            (self._heartbeat_loop, "worker-heartbeat"),
        ):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._procs_lock:
            live = [p for ps in self._procs.values() for p in ps]
        for p in live:
            if p.poll() is None:
                p.terminate()

    # ------------------------------------------------------- master contact
    def _register(self) -> None:
        reply = self._master_call({
            "op": "REGISTER_WORKER", "worker_id": self.worker_id,
            "host": self.host, "port": self.port, "cores": self.cores,
        })
        if reply.get("op") != "REGISTERED":
            raise RuntimeError(f"registration rejected: {reply}")

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                reply = self._master_call({
                    "op": "HEARTBEAT", "worker_id": self.worker_id,
                })
                if reply.get("op") == "RECONNECT":
                    self._register()  # master restarted; re-introduce
            except (ConnectionError, OSError):
                continue  # master gone; keep trying (HA window)

    # --------------------------------------------------------------- orders
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg, _ = _recv_msg(conn)
                if msg.get("op") == "LAUNCH":
                    self._launch(msg)
                    _send_msg(conn, {"op": "ACK"})
                elif msg.get("op") == "KILL":
                    with self._procs_lock:
                        self._killed.add(msg["app_id"])
                        doomed = list(self._procs.get(msg["app_id"], ()))
                    for p in doomed:
                        if p.poll() is None:
                            p.terminate()
                    _send_msg(conn, {"op": "ACK", "killed": len(doomed)})
                else:
                    _send_msg(conn, {"op": "ERR", "msg": "bad op"})
            except (ConnectionError, OSError):
                pass
            finally:
                conn.close()

    def _launch(self, order: dict) -> None:
        env = dict(os.environ)
        env.update(order.get("env") or {})
        env.update(self._launch_env_extra)
        proc = subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.cli", *order["argv"]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        proc.async_proc_id = order["proc_id"]  # introspection (tests, UI)
        with self._procs_lock:
            self._procs.setdefault(order["app_id"], []).append(proc)

        def watch() -> None:
            # NOTE: output is buffered until exit (fine for the batch apps
            # this layer schedules; a log-streaming executor is future work)
            out, err = proc.communicate()
            with self._procs_lock:
                ps = self._procs.get(order["app_id"], [])
                if proc in ps:
                    ps.remove(proc)
                if not ps:
                    self._procs.pop(order["app_id"], None)
                app_killed = order["app_id"] in self._killed
            if (
                proc.returncode
                and order.get("supervise")
                and not app_killed
                and not self._stop.is_set()
                and order.get("_restarts", 0) < self.max_supervised_restarts
            ):
                # spark-submit --supervise parity (DriverRunner's restart
                # loop): relaunch with the SAME order -- env carries the
                # coordinator address, so a restarted PS rebinds its port
                # and the surviving peers reconnect.  No EXECUTOR_EXIT for
                # a supervised death: the master sees one continuous life.
                order2 = dict(order, _restarts=order.get("_restarts", 0) + 1)
                sys.stderr.write(
                    f"[{self.worker_id}] supervising app {order['app_id']} "
                    f"proc {order['proc_id']}: rc={proc.returncode}, "
                    f"restart {order2['_restarts']}/"
                    f"{self.max_supervised_restarts}\n"
                )
                self._launch(order2)
                return
            # the exit report must survive a master failover window: a
            # standby needs a few hundred ms to win the lease and recover,
            # and a lost report strands the app in RUNNING forever -- so
            # this call gets a much deeper retry budget than the default
            try:
                self._master_call(
                    {
                        "op": "EXECUTOR_EXIT", "worker_id": self.worker_id,
                        "app_id": order["app_id"],
                        "proc_id": order["proc_id"],
                        "returncode": proc.returncode,
                    },
                    retry=RetryPolicy.from_conf(
                        max_attempts=120, deadline_s=30.0, max_ms=500.0,
                        # a stopped worker must not keep dialing the master
                        # for the rest of the budget: classify transport
                        # errors as non-retryable once stop() has run
                        classify=lambda e: (isinstance(e, OSError)
                                            and not self._stop.is_set()),
                    ),
                )
            except (ConnectionError, OSError):
                pass  # budget spent; the app stays RUNNING (operator-visible)
            if proc.returncode and err:
                sys.stderr.write(
                    f"[{self.worker_id}] app {order['app_id']} proc "
                    f"{order['proc_id']} rc={proc.returncode}:\n"
                    + "\n".join(err.splitlines()[-10:]) + "\n"
                )
            # process 0's stdout is the app's output (SPMD/PS convention)
            if order["proc_id"] == 0 and out:
                sys.stdout.write(out)
                sys.stdout.flush()

        threading.Thread(
            target=guarded(watch, f"exec-watch-{order['app_id']}"),
            name=f"exec-watch-{order['app_id']}-{order['proc_id']}",
            daemon=True,
        ).start()


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    import argparse

    p = argparse.ArgumentParser("async-worker")
    p.add_argument("master", help="master address(es) host:port[,host:port]"
                                  " -- first is primary, rest standbys")
    p.add_argument("--cores", type=int, default=1)
    p.add_argument("--worker-id", default=None)
    args = p.parse_args(argv)
    from asyncframework_tpu.net import faults

    faults.maybe_install_from_conf()  # chaos runs configure daemons by env
    from asyncframework_tpu.metrics.live import start_telemetry_from_conf

    start_telemetry_from_conf("deploy-worker")  # async.metrics.port gates it
    primary, *standbys = args.master.split(",")
    host, port = primary.rsplit(":", 1)
    w = Worker(host, int(port), worker_id=args.worker_id,
               cores=args.cores, standby_masters=standbys).start()
    print(f"worker {w.worker_id} on {w.host}:{w.port} -> {args.master}",
          flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        w.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
