"""Continuous profiling plane: attribute every hot-path cycle (ISSUE 18).

ROADMAP item 5 argues "the interpreter is the next NIC": every frame
pump, XOR delta, CRC, quantize and compress pass runs in pure Python
under the GIL -- and until now nothing *measured* where those cycles
go.  This module is the measuring instrument the native rewrite will be
validated against: two complementary collectors feeding one declared
zone table.

- **Sampling collector** (statistical, whole-process): a daemon thread
  walks ``sys._current_frames()`` at ``async.prof.hz``, classifies each
  thread's stack into one zone via the ``_CLASSIFIER`` table, and
  collapses the stack into a bounded count map
  (flamegraph-compatible ``a;b;c count`` lines).  Sampling error for a
  zone with true share p after N samples is ~sqrt(p(1-p)/N) -- at
  97 Hz a 60 s window gives ~5800 samples, so a 10 % zone is resolved
  to +-0.4 % -- the ASAP argument (arXiv:1612.08608) that approximate,
  low-overhead measurement is what makes always-on telemetry viable.
- **Exact collector** (nanosecond accumulators): ``zone()`` /
  ``zoned()`` / ``zone_ns()`` at the existing choke points
  (``net/frame.py`` send/recv, ``net/wiredelta.py``,
  ``net/wirecodec.py``, the PS merge drain) plus ``wrap_dispatch()``
  around the jitted step callables (first call = compile, later calls =
  dispatch, per-label EWMA of step wall time).

Off by default (``async.prof.enabled=0``): ``zone()`` returns the one
shared no-op context manager, ``wrap_dispatch()`` returns its argument
unchanged, and the wire is byte-identical -- all asserted by
``tests/test_profiler.py``.

The zone table below is THE declaration: the async-lint ``prof-zone``
rule cross-checks every zone literal used by a collector or accumulator
anywhere in the tree against it, both directions (undeclared use /
declared-but-never-attributed), matching the series-family discipline.

Import-light by contract (the lint imports nothing, but ``bin/async-prof``
and the flight recorder import this module on paths where jax must not
initialize): no jax / conf / live imports at module scope.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# ------------------------------------------------------------- zone table
#: THE one declared zone table.  Every zone literal passed to ``zone()``,
#: ``zoned()``, ``zone_ns()``, ``wrap_dispatch()`` or ``_zrule()`` must
#: appear here, and every zone here must be attributed somewhere
#: (async-lint ``prof-zone``, mutation-tested both directions).
ZONES: Tuple[str, ...] = (
    "wire.encode",      # frame pump, send side: header stamp + sendall
    "wire.decode",      # frame pump, recv side: recv_exact + header parse
    "wire.xor",         # XOR bit-pattern delta encode/decode (wiredelta)
    "wire.crc",         # basis CRC gating (wiredelta.crc)
    "wire.quantize",    # gradient quantize/dequantize (wirecodec fp16/int8)
    "wire.compress",    # model-part compress/decompress (wirecodec)
    "merge.drain",      # PS merge-queue drain + fused apply dispatch
    "kernel.dispatch",  # jitted step dispatch (wrap_dispatch wrappers)
    "serde",            # JSON header encode/decode and friends
    "gil.other",        # sampled Python time not claimed by any rule
)

_WIRE_ZONES: Tuple[str, ...] = tuple(z for z in ZONES if z.startswith("wire."))

#: EWMA weight for the per-label step-time gauge (same spirit as the
#: controller's telemetry smoothing: new sample gets 0.2).
_EWMA_ALPHA = 0.2

#: sampler stack bounds: frames kept per stack, distinct collapsed
#: stacks kept (beyond it new stacks are dropped and counted, never
#: evicted -- eviction would bias long-running hot stacks out).
_STACK_DEPTH = 48

_SCHEMA = 1


# ------------------------------------------------------- frame classifier
class _ZRule:
    """One classifier row: substring of the frame's filename (forward
    slashes), optional function-name set, target zone."""

    __slots__ = ("path", "funcs", "zone")

    def __init__(self, path: str, funcs: Tuple[str, ...], zone: str):
        self.path = path
        self.funcs = frozenset(funcs)
        self.zone = zone


def _zrule(path: str, funcs: Tuple[str, ...], zone: str) -> _ZRule:
    # the lint extracts the LAST positional arg of every _zrule(...) call
    # as a zone literal; keep zone last.
    return _ZRule(path, funcs, zone)


#: ordered, first match wins; function-specific rows precede their
#: same-file catch-alls.  The final row is the declared fallback.
_CLASSIFIER: Tuple[_ZRule, ...] = (
    _zrule("asyncframework_tpu/net/wiredelta", ("crc",), "wire.crc"),
    _zrule("asyncframework_tpu/net/wiredelta", (), "wire.xor"),
    _zrule("asyncframework_tpu/net/wirecodec",
           ("encode_grad", "decode_grad", "_quantize", "_dequantize"),
           "wire.quantize"),
    _zrule("asyncframework_tpu/net/wirecodec", (), "wire.compress"),
    _zrule("asyncframework_tpu/net/frame",
           ("_recv_msg_raw", "recv_msg", "recv_exact", "_recv_exact_into"),
           "wire.decode"),
    _zrule("asyncframework_tpu/net/frame", (), "wire.encode"),
    _zrule("asyncframework_tpu/parallel/ps_dcn",
           ("_drain_merge_locked", "_apply_merge"), "merge.drain"),
    _zrule("/json/", (), "serde"),
    _zrule("/jaxlib/", (), "kernel.dispatch"),
    _zrule("/jax/", (), "kernel.dispatch"),
    _zrule("", (), "gil.other"),
)


def _classify_frame(filename: str, funcname: str) -> Optional[str]:
    """Zone for ONE frame, or None if only the fallback would match
    (the stack walk wants 'no specific claim' to keep descending)."""
    for rule in _CLASSIFIER:
        if not rule.path:
            return None
        if rule.path in filename and (not rule.funcs
                                      or funcname in rule.funcs):
            return rule.zone
    return None


def classify_stack(frames: List[Tuple[str, str]]) -> str:
    """Zone for one sampled stack (``[(filename, funcname), ...]``,
    innermost first): the innermost frame any non-fallback rule claims
    wins; otherwise the declared fallback."""
    for filename, funcname in frames:
        z = _classify_frame(filename, funcname)
        if z is not None:
            return z
    return _CLASSIFIER[-1].zone


# ----------------------------------------------------------- no-op timer
class _NoopZone:
    """The disabled-path context manager: one shared instance, no state.
    ``zone(...) is _NOOP_ZONE`` is the asserted zero-overhead guard."""

    __slots__ = ()

    def __enter__(self) -> "_NoopZone":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


_NOOP_ZONE = _NoopZone()


class _ZoneTimer:
    """One enabled-path timing scope; a fresh instance per ``zone()``
    call so concurrent threads never share a ``t0``."""

    __slots__ = ("_prof", "_name", "_t0")

    def __init__(self, prof: "Profiler", name: str):
        self._prof = prof
        self._name = name
        self._t0 = 0

    def __enter__(self) -> "_ZoneTimer":
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._prof._zone_ns(self._name, time.monotonic_ns() - self._t0)


# --------------------------------------------------------------- profiler
class Profiler:
    """Process-global profiling plane: sampler thread + exact zone
    accumulators + jit compile/dispatch accounting + memory gauges.

    All counters live in one lock-guarded flat dict (the ``_bump`` /
    ``_totals`` pattern every family in ``metrics/registry.py`` uses)
    so the ``profile`` counter family, /metrics exposition and the
    flight recorder's counter-delta events ride for free.
    """

    def __init__(self, role: str, hz: float = 97.0, stacks_max: int = 256):
        self.role = role
        self.hz = float(hz)
        self.stacks_max = int(stacks_max)
        self._lock = threading.Lock()
        self._totals: Dict[str, int] = {}
        self._stacks: Dict[str, int] = {}
        self._ewma_ms: Dict[str, float] = {}
        self._started_s = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ accumulators
    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._totals[key] = self._totals.get(key, 0) + n

    def _zone_ns(self, name: str, ns: int) -> None:
        with self._lock:
            self._totals[f"zone_ns.{name}"] = (
                self._totals.get(f"zone_ns.{name}", 0) + ns)
            self._totals[f"zone_calls.{name}"] = (
                self._totals.get(f"zone_calls.{name}", 0) + 1)

    def note_dispatch(self, zone_name: str, label: str, ns: int,
                      first: bool) -> None:
        """One wrapped step call: first call per wrapper = trace+compile
        (jit compiles on first invocation), later calls = dispatch."""
        with self._lock:
            if first:
                self._totals["compile_count"] = (
                    self._totals.get("compile_count", 0) + 1)
                self._totals["compile_ns"] = (
                    self._totals.get("compile_ns", 0) + ns)
            else:
                self._totals["dispatch_count"] = (
                    self._totals.get("dispatch_count", 0) + 1)
                self._totals["dispatch_ns"] = (
                    self._totals.get("dispatch_ns", 0) + ns)
                self._totals[f"zone_ns.{zone_name}"] = (
                    self._totals.get(f"zone_ns.{zone_name}", 0) + ns)
                self._totals[f"zone_calls.{zone_name}"] = (
                    self._totals.get(f"zone_calls.{zone_name}", 0) + 1)
                ms = ns / 1e6
                prev = self._ewma_ms.get(label or "step")
                self._ewma_ms[label or "step"] = (
                    ms if prev is None
                    else _EWMA_ALPHA * ms + (1.0 - _EWMA_ALPHA) * prev)

    # ----------------------------------------------------------- sampler
    def start(self) -> "Profiler":
        if self.hz > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="prof-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        period = 1.0 / max(self.hz, 1e-3)
        own = threading.get_ident()
        while not self._stop.wait(period):
            try:
                self.sample_once(skip_tid=own)
            except Exception:
                self._bump("sample_errors")

    def sample_once(self, skip_tid: Optional[int] = None) -> int:
        """One sampling pass over every live thread; returns the number
        of stacks sampled (tests drive this directly, hz=0)."""
        frames = sys._current_frames()
        sampled = 0
        for tid, top in frames.items():
            if tid == skip_tid:
                continue
            stack: List[Tuple[str, str]] = []
            f = top
            while f is not None and len(stack) < _STACK_DEPTH:
                code = f.f_code
                stack.append((code.co_filename.replace(os.sep, "/"),
                              code.co_name))
                f = f.f_back
            if not stack:
                continue
            zone_name = classify_stack(stack)
            collapsed = ";".join(
                f"{os.path.basename(fn)}:{func}"
                for fn, func in reversed(stack))
            with self._lock:
                self._totals["samples"] = self._totals.get("samples", 0) + 1
                self._totals[f"samples.{zone_name}"] = (
                    self._totals.get(f"samples.{zone_name}", 0) + 1)
                if collapsed in self._stacks:
                    self._stacks[collapsed] += 1
                elif len(self._stacks) < self.stacks_max:
                    self._stacks[collapsed] = 1
                else:
                    self._totals["stack_overflow"] = (
                        self._totals.get("stack_overflow", 0) + 1)
            sampled += 1
        return sampled

    # ---------------------------------------------------------- readout
    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._totals)

    def reset(self) -> None:
        with self._lock:
            self._totals.clear()
            self._stacks.clear()
            self._ewma_ms.clear()

    def snapshot(self) -> Dict[str, Any]:
        """The full profile snapshot: what /api/status serves, the
        observer harvests, the flight recorder embeds, and bench arms
        report.  Self-contained (carries its own identity + clock)."""
        with self._lock:
            totals = dict(self._totals)
            stacks = dict(self._stacks)
            ewma = dict(self._ewma_ms)
        samples = totals.get("samples", 0)
        zones: Dict[str, Dict[str, Any]] = {}
        for z in ZONES:
            zs = totals.get(f"samples.{z}", 0)
            zns = totals.get(f"zone_ns.{z}", 0)
            zc = totals.get(f"zone_calls.{z}", 0)
            if not (zs or zns or zc):
                continue
            zones[z] = {
                "samples": zs,
                "share": (zs / samples) if samples else 0.0,
                "ns": zns,
                "calls": zc,
            }
        return {
            "schema": _SCHEMA,
            "role": self.role,
            "pid": os.getpid(),
            "host": _hostname(),
            "hz": self.hz,
            "started_s": self._started_s,
            "dumped_s": time.time(),
            "samples": samples,
            "zones": zones,
            "compile": {
                "count": totals.get("compile_count", 0),
                "ns": totals.get("compile_ns", 0),
            },
            "dispatch": {
                "count": totals.get("dispatch_count", 0),
                "ns": totals.get("dispatch_ns", 0),
                "ewma_ms": ewma,
            },
            "memory": memory_gauges(),
            "stacks": stacks,
            "totals": totals,
        }


def _hostname() -> str:
    try:
        import socket
        return socket.gethostname()
    except Exception:
        return "?"


def _host_rss_bytes() -> int:
    """Resident set size without psutil: /proc on Linux, ru_maxrss
    fallback elsewhere (then it is a high-water, not a gauge)."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        try:
            import resource
            ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # Linux reports KiB, macOS bytes; this branch is non-Linux.
            return int(ru)
        except Exception:
            return 0


def memory_gauges() -> Dict[str, Any]:
    """Host RSS always; device stats only if jax is ALREADY imported
    (a profiler readout must never be the thing that initializes a
    backend)."""
    mem: Dict[str, Any] = {"host_rss_bytes": _host_rss_bytes()}
    jaxmod = sys.modules.get("jax")
    if jaxmod is not None:
        try:
            st = jaxmod.devices()[0].memory_stats()
            if st:
                mem["device_bytes_in_use"] = int(st.get("bytes_in_use", 0))
                mem["device_bytes_limit"] = int(st.get("bytes_limit", 0))
        except Exception:
            pass
    return mem


# ----------------------------------------------- process-global plumbing
_lock = threading.Lock()
_profiler: Optional[Profiler] = None
#: final snapshot captured at uninstall so a post-run flight dump still
#: carries the profile post-mortem.
_last_final: Optional[Dict[str, Any]] = None


def active() -> Optional[Profiler]:
    return _profiler


def zone(name: str) -> Any:
    """Timing scope for one zone: ``with zone("wire.encode"): ...``.
    Disabled -> the shared no-op (identity-asserted zero overhead)."""
    p = _profiler
    if p is None:
        return _NOOP_ZONE
    return _ZoneTimer(p, name)


def zone_ns(name: str, ns: int) -> None:
    """Direct exact-accumulator bump for callers that already hold a
    duration (vectored send paths)."""
    p = _profiler
    if p is not None:
        p._zone_ns(name, ns)


def zoned(name: str) -> Callable[[Callable], Callable]:
    """Decorator form of ``zone()`` for whole-function choke points
    (wiredelta/wirecodec codecs, the PS merge drain).  The disabled
    path is one global read + branch."""
    if name not in ZONES:
        raise ValueError(f"undeclared profile zone {name!r}")

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            p = _profiler
            if p is None:
                return fn(*args, **kwargs)
            t0 = time.monotonic_ns()
            try:
                return fn(*args, **kwargs)
            finally:
                p._zone_ns(name, time.monotonic_ns() - t0)
        return wrapper
    return deco


def wrap_dispatch(fn: Callable, zone_name: str, label: str = "") -> Callable:
    """Wrap one jitted step callable: first call is accounted as
    compile (count + ns), later calls as dispatch (count + ns + the
    zone + a per-label EWMA of step wall time).  Disabled -> returns
    ``fn`` UNCHANGED (the asserted zero-overhead guard), so profiling
    must be enabled before the step factories run -- which it is:
    ``live.start_telemetry_from_conf`` installs at process boot."""
    p = _profiler
    if p is None:
        return fn
    state = {"n": 0}

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        t0 = time.monotonic_ns()
        out = fn(*args, **kwargs)
        ns = time.monotonic_ns() - t0
        first = state["n"] == 0
        state["n"] += 1
        p.note_dispatch(zone_name, label, ns, first)
        return out
    return wrapper


def profile_totals() -> Dict[str, int]:
    """Registry provider (``profile`` counter family)."""
    p = _profiler
    return p.totals() if p is not None else {}


def reset_profile_totals() -> None:
    """Registry reset hook."""
    p = _profiler
    if p is not None:
        p.reset()


def last_snapshot() -> Optional[Dict[str, Any]]:
    """Freshest profile snapshot: live (computed now) while installed,
    the final uninstall snapshot afterwards, None when profiling never
    ran.  The flight recorder embeds this in every dump."""
    p = _profiler
    if p is not None:
        return p.snapshot()
    return _last_final


def install(role: str, hz: float = 97.0, stacks_max: int = 256) -> Profiler:
    """Install (and start) the process-global profiler; idempotent per
    process, same contract as ``flightrec.install``."""
    global _profiler
    with _lock:
        if _profiler is not None:
            return _profiler
        p = Profiler(role, hz=hz, stacks_max=stacks_max)
        _profiler = p
    try:
        from asyncframework_tpu.metrics import live
        live.register_status_section("profile", last_snapshot)
    except Exception:
        pass
    return p.start()


def install_from_conf(role: str) -> Optional[Profiler]:
    """Conf-gated install (``async.prof.enabled=0`` = off, the
    default): the one call every daemon entry point makes, riding
    ``live.start_telemetry_from_conf`` next to the flight recorder."""
    from asyncframework_tpu.conf import (
        PROF_ENABLED,
        PROF_HZ,
        PROF_STACKS,
        global_conf,
    )

    conf = global_conf()
    if not int(conf.get(PROF_ENABLED) or 0):
        return None
    return install(role, hz=float(conf.get(PROF_HZ)),
                   stacks_max=int(conf.get(PROF_STACKS)))


def uninstall() -> Optional[Dict[str, Any]]:
    """Stop and drop the process-global profiler; keeps (and returns)
    its final snapshot so late flight dumps still carry it."""
    global _profiler, _last_final
    with _lock:
        p, _profiler = _profiler, None
    if p is None:
        return None
    p.stop()
    snap = p.snapshot()
    _last_final = snap
    try:
        from asyncframework_tpu.metrics import live
        live.unregister_status_section("profile")
    except Exception:
        pass
    return snap


# ------------------------------------------------------------ CLI readers
def collapsed_lines(snap: Dict[str, Any]) -> List[str]:
    """Flamegraph collapsed-stack lines (``a;b;c count``), stable
    order: count desc then stack.  Feed straight to flamegraph.pl /
    speedscope / inferno."""
    stacks = snap.get("stacks") or {}
    # the collapsed format is space-delimited: frames like
    # "<frozen importlib._bootstrap>:_gcd_import" would split wrong in
    # strict consumers, so spaces inside frame names become underscores
    return [f"{stack.replace(' ', '_')} {count}" for stack, count in
            sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))]


def zone_table(snap: Dict[str, Any]) -> List[Tuple[str, int, float, float, int]]:
    """Rows (zone, samples, share, exact_ms, calls), share desc then
    exact time desc -- the async-prof top view."""
    zones = snap.get("zones") or {}
    rows = []
    for z, d in zones.items():
        rows.append((z, int(d.get("samples", 0)),
                     float(d.get("share", 0.0)),
                     float(d.get("ns", 0)) / 1e6,
                     int(d.get("calls", 0))))
    rows.sort(key=lambda r: (-r[2], -r[3], r[0]))
    return rows


def diff_zones(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Zone-level diff of two snapshots: share/ms deltas plus the
    only-in sets (the codec-on-vs-off acceptance reads ``only_in_a``)."""
    za = a.get("zones") or {}
    zb = b.get("zones") or {}
    out: Dict[str, Any] = {
        "only_in_a": sorted(set(za) - set(zb)),
        "only_in_b": sorted(set(zb) - set(za)),
        "zones": {},
    }
    for z in sorted(set(za) | set(zb)):
        da, db = za.get(z) or {}, zb.get(z) or {}
        out["zones"][z] = {
            "share_a": float(da.get("share", 0.0)),
            "share_b": float(db.get("share", 0.0)),
            "share_delta": float(da.get("share", 0.0))
            - float(db.get("share", 0.0)),
            "ms_a": float(da.get("ns", 0)) / 1e6,
            "ms_b": float(db.get("ns", 0)) / 1e6,
        }
    return out


def _looks_like_snapshot(d: Any) -> bool:
    return isinstance(d, dict) and ("zones" in d or "stacks" in d)


def load_profiles(path: str) -> Dict[str, Dict[str, Any]]:
    """Profile snapshots from any artifact the stack produces, keyed by
    a human label:

    - a raw snapshot JSON (async-prof itself, the observer's
      ``profile/`` files),
    - a flight-recorder dump (``flight-*.json``: the ``profile`` key),
    - a bench output (top-level or per-arm ``profile`` blocks, keyed by
      arm name),
    - a directory: an observer run dir (``profile/*.json``) or a flight
      dump dir.
    """
    out: Dict[str, Dict[str, Any]] = {}
    if os.path.isdir(path):
        profdir = os.path.join(path, "profile")
        scan = profdir if os.path.isdir(profdir) else path
        for fn in sorted(os.listdir(scan)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(scan, fn), "r",
                          encoding="utf-8") as f:
                    d = json.load(f)
            except (OSError, ValueError):
                continue
            if _looks_like_snapshot(d):
                out[fn[:-5]] = d
            elif (isinstance(d, dict)
                  and _looks_like_snapshot(d.get("profile"))):
                out[fn[:-5]] = d["profile"]
        return out
    with open(path, "r", encoding="utf-8") as f:
        d = json.load(f)
    if _looks_like_snapshot(d):
        out[os.path.basename(path)] = d
        return out
    if isinstance(d, dict):
        if _looks_like_snapshot(d.get("profile")):
            out[os.path.basename(path)] = d["profile"]
            return out
        arms = d.get("arms")
        if isinstance(arms, dict):
            arms = [dict(v, name=k) for k, v in arms.items()]
        if isinstance(arms, list):
            for i, arm in enumerate(arms):
                if not isinstance(arm, dict):
                    continue
                prof = arm.get("profile")
                if _looks_like_snapshot(prof):
                    out[str(arm.get("name") or arm.get("arm")
                            or arm.get("codec") or i)] = prof
        # bench outputs nest arm records one or two levels deep
        # ({"codec": {"off": {"profile": ...}}}); scan both
        for k, v in d.items():
            if k == "profile" or not isinstance(v, dict):
                continue
            prof = v.get("profile")
            if _looks_like_snapshot(prof):
                out.setdefault(str(k), prof)
                continue
            for k2, v2 in v.items():
                if isinstance(v2, dict) and \
                        _looks_like_snapshot(v2.get("profile")):
                    out.setdefault(f"{k}/{k2}", v2["profile"])
    return out


def _pick(profiles: Dict[str, Dict[str, Any]], arm: Optional[str],
          what: str) -> Dict[str, Any]:
    if arm is not None:
        if arm not in profiles:
            raise SystemExit(
                f"async-prof: no arm {arm!r} in {what} "
                f"(have: {', '.join(sorted(profiles)) or 'none'})")
        return profiles[arm]
    if len(profiles) == 1:
        return next(iter(profiles.values()))
    raise SystemExit(
        f"async-prof: {what} holds {len(profiles)} profiles "
        f"({', '.join(sorted(profiles))}); pick one with --arm/--arm-b")


def _render_table(label: str, snap: Dict[str, Any], out) -> None:
    print(f"== {label}: role={snap.get('role', '?')} "
          f"pid={snap.get('pid', '?')} hz={snap.get('hz', '?')} "
          f"samples={snap.get('samples', 0)}", file=out)
    comp = snap.get("compile") or {}
    disp = snap.get("dispatch") or {}
    print(f"   compile: {comp.get('count', 0)} in "
          f"{float(comp.get('ns', 0)) / 1e6:.1f} ms   dispatch: "
          f"{disp.get('count', 0)} in "
          f"{float(disp.get('ns', 0)) / 1e6:.1f} ms", file=out)
    mem = snap.get("memory") or {}
    if mem:
        dev = mem.get("device_bytes_in_use")
        print(f"   rss: {mem.get('host_rss_bytes', 0) / 2**20:.0f} MiB"
              + (f"   device: {dev / 2**20:.0f} MiB" if dev else ""),
              file=out)
    rows = zone_table(snap)
    if not rows:
        print("   (no zones attributed)", file=out)
        return
    print(f"   {'zone':<16} {'share':>7} {'samples':>8} "
          f"{'exact ms':>10} {'calls':>8}", file=out)
    for z, samples, share, ms, calls in rows:
        print(f"   {z:<16} {share * 100:>6.1f}% {samples:>8} "
              f"{ms:>10.2f} {calls:>8}", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    """bin/async-prof: top-zone tables, flamegraph collapsed stacks,
    and run/arm diffs over any profile-carrying artifact."""
    import argparse

    p = argparse.ArgumentParser(
        prog="async-prof",
        description="Render continuous-profiling snapshots: top-zone "
                    "tables, flamegraph-compatible collapsed stacks, "
                    "and diffs between two runs or bench arms.")
    p.add_argument("source", help="profile snapshot JSON, flight dump, "
                                  "bench output, or observer run dir")
    p.add_argument("source_b", nargs="?", default=None,
                   help="second source (with --diff)")
    p.add_argument("--arm", default=None,
                   help="arm/profile label to pick from a multi-profile "
                        "source")
    p.add_argument("--arm-b", default=None,
                   help="arm/profile label for the second source "
                        "(--diff; defaults to --arm)")
    p.add_argument("--collapsed", action="store_true",
                   help="emit flamegraph collapsed-stack lines instead "
                        "of the zone table")
    p.add_argument("--diff", action="store_true",
                   help="diff two sources (or two arms of one source)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    args = p.parse_args(argv)
    out = sys.stdout

    profiles = load_profiles(args.source)
    if not profiles:
        print(f"async-prof: no profile snapshots in {args.source}",
              file=sys.stderr)
        return 2

    if args.diff:
        if args.source_b is not None:
            profiles_b = load_profiles(args.source_b)
            if not profiles_b:
                print(f"async-prof: no profile snapshots in "
                      f"{args.source_b}", file=sys.stderr)
                return 2
        else:
            profiles_b = profiles
            if args.arm is None or (args.arm_b or args.arm) == args.arm:
                print("async-prof: --diff over one source needs --arm "
                      "and --arm-b", file=sys.stderr)
                return 2
        a = _pick(profiles, args.arm, args.source)
        b = _pick(profiles_b, args.arm_b or args.arm,
                  args.source_b or args.source)
        d = diff_zones(a, b)
        if args.json:
            json.dump(d, out, indent=2, sort_keys=True)
            out.write("\n")
            return 0
        for z in d["only_in_a"]:
            print(f"only in A: {z} "
                  f"(share {d['zones'][z]['share_a'] * 100:.1f}%, "
                  f"{d['zones'][z]['ms_a']:.2f} ms)", file=out)
        for z in d["only_in_b"]:
            print(f"only in B: {z} "
                  f"(share {d['zones'][z]['share_b'] * 100:.1f}%, "
                  f"{d['zones'][z]['ms_b']:.2f} ms)", file=out)
        print(f"   {'zone':<16} {'share A':>8} {'share B':>8} "
              f"{'delta':>8} {'ms A':>10} {'ms B':>10}", file=out)
        for z, row in sorted(d["zones"].items(),
                             key=lambda kv: -abs(kv[1]["share_delta"])):
            print(f"   {z:<16} {row['share_a'] * 100:>7.1f}% "
                  f"{row['share_b'] * 100:>7.1f}% "
                  f"{row['share_delta'] * 100:>+7.1f}% "
                  f"{row['ms_a']:>10.2f} {row['ms_b']:>10.2f}", file=out)
        return 0

    snap = _pick(profiles, args.arm, args.source)
    if args.collapsed:
        for line in collapsed_lines(snap):
            print(line, file=out)
        return 0
    if args.json:
        json.dump(snap, out, indent=2, sort_keys=True)
        out.write("\n")
        return 0
    label = args.arm or next(iter(profiles))
    _render_table(label, snap, out)
    return 0
