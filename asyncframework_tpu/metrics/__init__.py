"""Observability: event bus, event logging/replay, metrics registry+sinks.

Parity (SURVEY.md section 5): the reference's observability spine is
(a) ``SparkListener`` events on ``LiveListenerBus``
(``scheduler/LiveListenerBus.scala:44``), (b) ``EventLoggingListener`` JSON
event logs replayed by the history server
(``scheduler/EventLoggingListener.scala:55``,
``deploy/history/FsHistoryProvider.scala``), and (c) the Dropwizard
``MetricsSystem`` with pluggable sinks (``metrics/MetricsSystem.scala:70``).
This package is the TPU build's equivalent of all three, sized to what a
host-orchestrated XLA runtime actually emits.
"""

from asyncframework_tpu.metrics.bus import (
    Event,
    GradientMerged,
    JobEnd,
    JobStart,
    Listener,
    ListenerBus,
    ModelSnapshot,
    RoundSubmitted,
    TaskEnd,
    TraceSpan,
    WorkerLost,
)
from asyncframework_tpu.metrics.eventlog import EventLogReader, EventLogWriter
from asyncframework_tpu.metrics.report import render_report
from asyncframework_tpu.metrics.system import (
    Counter,
    CsvSink,
    Gauge,
    Histogram,
    JsonlSink,
    MetricsSystem,
)
from asyncframework_tpu.metrics.timeseries import (
    ConvergenceHistory,
    TimeSeriesStore,
)
from asyncframework_tpu.metrics.trace import (
    Span,
    TraceAggregator,
    TraceContext,
    TraceRecorder,
)


def reset_totals() -> None:
    """Zero EVERY process-global observability counter so back-to-back
    runs in one process -- tests, notebooks, long-lived daemons -- start
    from a clean slate instead of inheriting the previous run's counts.

    The counter families (net, net bytes, recovery, shuffle, pipeline,
    serving, convergence history, time-series store) are enumerated by
    the one registry (``metrics/registry.py``) -- adding a family there
    wires it into this reset, the live UI's per-run delta baselines, the
    telemetry sampler, and the Prometheus exposition at once; the
    registration audit test (``tests/test_telemetry.py``) fails on stray
    unregistered ``*_totals`` providers.  The trace aggregator and SLO
    rule states are not flat counter dicts, so they reset beside the
    registry walk."""
    from asyncframework_tpu.metrics import registry as _registry
    from asyncframework_tpu.metrics import slo as _slo
    from asyncframework_tpu.metrics import trace as _trace

    _registry.reset_all()
    _trace.reset_aggregator()
    _slo.reset_engine()


__all__ = [
    "Event",
    "JobStart",
    "JobEnd",
    "TaskEnd",
    "RoundSubmitted",
    "GradientMerged",
    "ModelSnapshot",
    "WorkerLost",
    "Listener",
    "ListenerBus",
    "EventLogWriter",
    "EventLogReader",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsSystem",
    "CsvSink",
    "JsonlSink",
    "render_report",
    "TraceSpan",
    "Span",
    "TraceAggregator",
    "TraceContext",
    "TraceRecorder",
    "TimeSeriesStore",
    "ConvergenceHistory",
    "reset_totals",
]
