"""Observability: event bus, event logging/replay, metrics registry+sinks.

Parity (SURVEY.md section 5): the reference's observability spine is
(a) ``SparkListener`` events on ``LiveListenerBus``
(``scheduler/LiveListenerBus.scala:44``), (b) ``EventLoggingListener`` JSON
event logs replayed by the history server
(``scheduler/EventLoggingListener.scala:55``,
``deploy/history/FsHistoryProvider.scala``), and (c) the Dropwizard
``MetricsSystem`` with pluggable sinks (``metrics/MetricsSystem.scala:70``).
This package is the TPU build's equivalent of all three, sized to what a
host-orchestrated XLA runtime actually emits.
"""

from asyncframework_tpu.metrics.bus import (
    Event,
    GradientMerged,
    JobEnd,
    JobStart,
    Listener,
    ListenerBus,
    ModelSnapshot,
    RoundSubmitted,
    TaskEnd,
    WorkerLost,
)
from asyncframework_tpu.metrics.eventlog import EventLogReader, EventLogWriter
from asyncframework_tpu.metrics.report import render_report
from asyncframework_tpu.metrics.system import (
    Counter,
    CsvSink,
    Gauge,
    Histogram,
    JsonlSink,
    MetricsSystem,
)

__all__ = [
    "Event",
    "JobStart",
    "JobEnd",
    "TaskEnd",
    "RoundSubmitted",
    "GradientMerged",
    "ModelSnapshot",
    "WorkerLost",
    "Listener",
    "ListenerBus",
    "EventLogWriter",
    "EventLogReader",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsSystem",
    "CsvSink",
    "JsonlSink",
    "render_report",
]
