"""Observability: event bus, event logging/replay, metrics registry+sinks.

Parity (SURVEY.md section 5): the reference's observability spine is
(a) ``SparkListener`` events on ``LiveListenerBus``
(``scheduler/LiveListenerBus.scala:44``), (b) ``EventLoggingListener`` JSON
event logs replayed by the history server
(``scheduler/EventLoggingListener.scala:55``,
``deploy/history/FsHistoryProvider.scala``), and (c) the Dropwizard
``MetricsSystem`` with pluggable sinks (``metrics/MetricsSystem.scala:70``).
This package is the TPU build's equivalent of all three, sized to what a
host-orchestrated XLA runtime actually emits.
"""

from asyncframework_tpu.metrics.bus import (
    Event,
    GradientMerged,
    JobEnd,
    JobStart,
    Listener,
    ListenerBus,
    ModelSnapshot,
    RoundSubmitted,
    TaskEnd,
    TraceSpan,
    WorkerLost,
)
from asyncframework_tpu.metrics.eventlog import EventLogReader, EventLogWriter
from asyncframework_tpu.metrics.report import render_report
from asyncframework_tpu.metrics.system import (
    Counter,
    CsvSink,
    Gauge,
    Histogram,
    JsonlSink,
    MetricsSystem,
)
from asyncframework_tpu.metrics.trace import (
    Span,
    TraceAggregator,
    TraceContext,
    TraceRecorder,
)


def reset_totals() -> None:
    """Zero EVERY process-global observability counter (net, recovery,
    shuffle, dedup/fault totals, the global trace aggregator) so
    back-to-back runs in one process -- tests, notebooks, long-lived
    daemons -- start from a clean slate instead of inheriting the previous
    run's counts.  The live UI additionally captures per-run deltas at
    listener construction, so calling this between runs is belt-and-braces
    rather than required for the dashboard."""
    from asyncframework_tpu.data.spill import reset_shuffle_totals
    from asyncframework_tpu.metrics import trace as _trace
    from asyncframework_tpu.net import reset_net_totals
    from asyncframework_tpu.parallel.ps_dcn import reset_pipeline_totals
    from asyncframework_tpu.parallel.supervisor import reset_recovery_totals
    from asyncframework_tpu.serving.metrics import reset_serving_totals

    reset_net_totals()
    reset_recovery_totals()
    reset_shuffle_totals()
    reset_pipeline_totals()
    reset_serving_totals()
    _trace.reset_aggregator()


__all__ = [
    "Event",
    "JobStart",
    "JobEnd",
    "TaskEnd",
    "RoundSubmitted",
    "GradientMerged",
    "ModelSnapshot",
    "WorkerLost",
    "Listener",
    "ListenerBus",
    "EventLogWriter",
    "EventLogReader",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsSystem",
    "CsvSink",
    "JsonlSink",
    "render_report",
    "TraceSpan",
    "Span",
    "TraceAggregator",
    "TraceContext",
    "TraceRecorder",
    "reset_totals",
]
