"""Cluster observer: central collector, run history, cross-role signals.

Thirteen PRs of per-process telemetry left every role an island: each
process serves its own ``/metrics`` + ``/api/status`` and forgets them
at exit.  The ASYNC paper's *history* pillar (arXiv:1907.08526) and the
delay-adaptive controller it motivates (ROADMAP item 2, per "Faster
Asynchronous SGD", arXiv:1601.04033) both assume someone can see the
WHOLE cluster's staleness/availability picture over time.  This module
is that someone:

- :class:`ClusterObserver` discovers every role -- static endpoints
  (conf ``async.observer.endpoints`` / CLI), the active ShardGroup's
  per-shard telemetry ports, and worker processes registered with any
  live ElasticSupervisor (HELLO now advertises the worker's metrics
  port) -- and scrapes each one's ``/api/status`` on an interval over
  the net/ retry plane (RetryPolicy + shared per-endpoint breakers:
  forty scrape failures against one dead role back off as a group).
- every scrape folds the role's numbers into a durable
  :class:`RunHistoryStore`: per-run, per-role compacted time series on
  disk (the ConvergenceHistory stride-compaction, so a series spans the
  whole run at bounded size), readable by ``bin/async-history``, bench,
  and :func:`load_run` -- trajectories outlive processes AND runs.
- cross-role **derived signals** are recomputed per scrape and exposed
  as the ``observer.*`` series family (dynamic source + counter family
  in ``metrics/registry.py``): per-worker straggler scores vs the
  cohort median (compute / push-RTT / push-interval / staleness -- the
  controller's input surface), PS merge-queue depth vs push rate, and
  fleet-wide serving freshness lag.  Default SLO rules over them ride
  ``async.slo.rules`` (``fleet_stragglers`` / ``fleet_freshness`` /
  ``fleet_roles``).
- the collector **harvests crash flight-recorder dumps**
  (``metrics/flightrec.py``) from the configured directories into the
  run-history store, so a chaos SIGKILL produces a post-mortem instead
  of silence.

``bin/async-mon`` is the CLI: it runs a collector, serves the fleet
view on its own ``/api/status`` (the ``observer`` section via
``live.register_status_section``; ``bin/async-top --observer`` renders
it), and persists history until stopped.
"""

from __future__ import annotations

import json
import os
import re
import statistics
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_totals_lock = threading.Lock()
_totals = {"scrapes": 0, "scrape_errors": 0, "harvests": 0,
           "harvest_stale_skipped": 0, "persists": 0,
           "stragglers_flagged": 0, "discovered": 0}


def _bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] += n


def observer_totals() -> Dict[str, int]:
    """Flat meta-counters (registry family ``observer``)."""
    with _totals_lock:
        return dict(_totals)


def reset_observer_totals() -> None:
    with _totals_lock:
        for k in _totals:
            _totals[k] = 0


@dataclass(frozen=True)
class RoleTarget:
    """One scrape target: a stable ``name`` (history key), its ``role``
    kind, and the base URL serving /api/status."""

    name: str
    role: str
    url: str


def parse_endpoints(text: str) -> List[RoleTarget]:
    """Parse the static-endpoint grammar: ``;``/``,``-separated
    ``name=role@host:port`` entries (``role@`` and ``name=`` optional;
    a bare ``host:port`` scrapes as role/name ``process``)."""
    out: List[RoleTarget] = []
    for raw in re.split(r"[;,]", text or ""):
        raw = raw.strip()
        if not raw:
            continue
        name, rest = (raw.split("=", 1) if "=" in raw else ("", raw))
        role, addr = (rest.split("@", 1) if "@" in rest else ("", rest))
        addr = addr.strip()
        if not addr.startswith("http"):
            addr = "http://" + addr
        name = name.strip() or role.strip() or "process"
        out.append(RoleTarget(name=name, role=role.strip() or "process",
                              url=addr.rstrip("/")))
    return out


def _safe_name(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)


# --------------------------------------------------------------------------
# Durable run-history store
# --------------------------------------------------------------------------
class _CompactSeries:
    """One compacted series: the ConvergenceHistory stride discipline
    (at capacity drop every other point and double the acceptance
    stride) so the persisted series always spans the whole run."""

    __slots__ = ("capacity", "pts", "_stride", "_arrivals")

    def __init__(self, capacity: int):
        self.capacity = max(16, int(capacity))
        self.pts: List[List[float]] = []
        self._stride = 1
        self._arrivals = 0

    def add(self, t_s: float, v: float) -> None:
        k = self._arrivals
        self._arrivals += 1
        if k % self._stride != 0:
            return
        self.pts.append([t_s, v])
        if len(self.pts) >= self.capacity:
            del self.pts[1::2]
            self._stride *= 2


class RunHistoryStore:
    """Per-run, per-role compacted time series + harvested flight dumps,
    persisted under ``<root>/run-<run_id>/`` (``root=None`` keeps it
    in-memory only -- same API, nothing written)."""

    MAX_SERIES_PER_ROLE = 256
    SCHEMA = 1

    def __init__(self, root: Optional[str], run_id: str,
                 points: int = 512):
        self.root = str(root) if root else None
        self.run_id = str(run_id)
        self.points = max(16, int(points))
        self._lock = threading.Lock()
        self._roles: Dict[str, Dict[str, object]] = {}  # name -> meta
        self._series: Dict[str, Dict[str, _CompactSeries]] = {}
        self._flight: Dict[str, dict] = {}  # dump filename -> dump dict
        self._flight_persisted: Dict[str, object] = {}  # fname -> dumped_s
        # profile snapshots harvested next to the flight dumps (from a
        # dump's embedded "profile" key OR straight off a scraped
        # /api/status), keyed role-pid; same fresher-dumped_s re-harvest
        # and dirty-tracked persist discipline as the dumps themselves
        self._profile: Dict[str, dict] = {}
        self._profile_persisted: Dict[str, object] = {}
        self.started_s = time.time()
        self.series_dropped = 0
        self.persists = 0

    @property
    def run_dir(self) -> Optional[str]:
        if self.root is None:
            return None
        return os.path.join(self.root, f"run-{_safe_name(self.run_id)}")

    # ------------------------------------------------------------- recording
    def note_role(self, name: str, role: str, url: str) -> None:
        with self._lock:
            self._roles[name] = {"role": role, "url": url}

    def record(self, role_name: str, series: str, t_s: float,
               value: float) -> None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        with self._lock:
            per = self._series.setdefault(role_name, {})
            s = per.get(series)
            if s is None:
                if len(per) >= self.MAX_SERIES_PER_ROLE:
                    self.series_dropped += 1
                    return
                s = per[series] = _CompactSeries(self.points)
            s.add(float(t_s), v)

    def harvest(self, dump: dict, source: str) -> bool:
        """Fold one flight-recorder dump in; returns True when it is new
        or newer than the copy already held (re-harvest on a fresher
        periodic overwrite of the same file)."""
        key = os.path.basename(str(source))
        with self._lock:
            prev = self._flight.get(key)
            if prev is not None and \
                    prev.get("dumped_s") == dump.get("dumped_s"):
                return False
            self._flight[key] = dump
        prof = dump.get("profile")
        if isinstance(prof, dict) and prof.get("zones"):
            self.harvest_profile(prof, source)
        return True

    def harvest_profile(self, snap: dict, source: str) -> bool:
        """Fold one profile snapshot in (from a flight dump's embedded
        ``profile`` key or a scraped ``/api/status`` section); keyed
        role-pid so a role's periodic snapshots overwrite in place;
        returns True when new or fresher (``dumped_s``)."""
        if not isinstance(snap, dict):
            return False
        key = f"{snap.get('role', '_')}-{snap.get('pid', 0)}"
        with self._lock:
            prev = self._profile.get(key)
            if prev is not None and \
                    prev.get("dumped_s") == snap.get("dumped_s"):
                return False
            self._profile[key] = snap
        return True

    # --------------------------------------------------------------- queries
    def roles(self) -> List[str]:
        with self._lock:
            return sorted(self._series.keys() | self._roles.keys())

    def series_of(self, role_name: str) -> Dict[str, List[List[float]]]:
        with self._lock:
            per = self._series.get(role_name, {})
            return {k: list(s.pts) for k, s in per.items()}

    def flight_dumps(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._flight)

    def profile_snapshots(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._profile)

    def summary(self) -> dict:
        with self._lock:
            return {
                "run_id": self.run_id,
                "run_dir": self.run_dir,
                "roles": {
                    n: {
                        **self._roles.get(n, {}),
                        "series": len(self._series.get(n, {})),
                    }
                    for n in sorted(self._series.keys()
                                    | self._roles.keys())
                },
                "flight_dumps": sorted(self._flight),
                "profile_snapshots": sorted(self._profile),
                "series_dropped": self.series_dropped,
                "persists": self.persists,
            }

    # ------------------------------------------------------------ persistence
    def _write_json(self, path: str, obj: dict) -> None:
        from asyncframework_tpu.checkpoint import durable_replace

        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(obj, f, default=str)
        durable_replace(tmp, path)

    def persist(self) -> Optional[str]:
        """Write meta + per-role series + flight dumps under the run
        dir (atomic per file); returns the run dir (None when
        in-memory)."""
        rd = self.run_dir
        if rd is None:
            return None
        with self._lock:
            roles = dict(self._roles)
            series = {n: {k: list(s.pts) for k, s in per.items()}
                      for n, per in self._series.items()}
            # dirty tracking: only dumps whose harvested copy is fresher
            # than the last persisted one get re-written (a long chaos
            # run must not re-serialize + fsync every unchanged dump on
            # every persist cycle)
            flight = {
                f: d for f, d in self._flight.items()
                if self._flight_persisted.get(f) != d.get("dumped_s")
            }
            all_flight = sorted(self._flight)
            profile = {
                k: s for k, s in self._profile.items()
                if self._profile_persisted.get(k) != s.get("dumped_s")
            }
            all_profile = sorted(self._profile)
        os.makedirs(os.path.join(rd, "roles"), exist_ok=True)
        os.makedirs(os.path.join(rd, "flight"), exist_ok=True)
        os.makedirs(os.path.join(rd, "profile"), exist_ok=True)
        for name, per in series.items():
            self._write_json(
                os.path.join(rd, "roles", f"{_safe_name(name)}.json"),
                {"name": name, **roles.get(name, {}), "series": per},
            )
        for fname, dump in flight.items():
            self._write_json(
                os.path.join(rd, "flight", _safe_name(fname)), dump)
            # marked clean only AFTER the write landed: a failed cycle
            # (disk full -> OSError swallowed by the scrape loop) must
            # retry this dump next time, not skip it as persisted
            with self._lock:
                self._flight_persisted[fname] = dump.get("dumped_s")
        for key, snap in profile.items():
            self._write_json(
                os.path.join(rd, "profile", f"{_safe_name(key)}.json"),
                snap)
            with self._lock:
                self._profile_persisted[key] = snap.get("dumped_s")
        self._write_json(os.path.join(rd, "meta.json"), {
            "schema": self.SCHEMA,
            "run_id": self.run_id,
            "started_s": self.started_s,
            "persisted_s": time.time(),
            "roles": roles,
            "flight_dumps": all_flight,
            "profile_snapshots": all_profile,
            "series_dropped": self.series_dropped,
        })
        with self._lock:
            self.persists += 1  # completed cycles only
        _bump("persists")
        return rd


def load_run(run_dir: str) -> dict:
    """Read one persisted run back: ``{"meta", "roles": {name:
    {"series": ...}}, "flight": {fname: dump}}`` -- the reader bench,
    tests, and ad-hoc analysis share."""
    with open(os.path.join(run_dir, "meta.json"), encoding="utf-8") as f:
        meta = json.load(f)
    roles: Dict[str, dict] = {}
    roles_dir = os.path.join(run_dir, "roles")
    if os.path.isdir(roles_dir):
        for fn in sorted(os.listdir(roles_dir)):
            if not fn.endswith(".json"):
                continue
            with open(os.path.join(roles_dir, fn), encoding="utf-8") as f:
                rec = json.load(f)
            roles[rec.get("name", fn[:-5])] = rec
    flight: Dict[str, dict] = {}
    fdir = os.path.join(run_dir, "flight")
    if os.path.isdir(fdir):
        for fn in sorted(os.listdir(fdir)):
            try:
                with open(os.path.join(fdir, fn), encoding="utf-8") as f:
                    flight[fn] = json.load(f)
            except (OSError, ValueError):
                continue  # a torn harvest must not hide the rest
    profile: Dict[str, dict] = {}
    pdir = os.path.join(run_dir, "profile")
    if os.path.isdir(pdir):
        for fn in sorted(os.listdir(pdir)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(pdir, fn), encoding="utf-8") as f:
                    profile[fn[:-5]] = json.load(f)
            except (OSError, ValueError):
                continue
    return {"meta": meta, "roles": roles, "flight": flight,
            "profile": profile}


def list_runs(root: str) -> List[str]:
    """Run directories under a history root (newest first by meta
    mtime; dirs without a readable meta.json are skipped)."""
    out: List[Tuple[float, str]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for n in names:
        rd = os.path.join(root, n)
        meta = os.path.join(rd, "meta.json")
        if n.startswith("run-") and os.path.isfile(meta):
            out.append((os.path.getmtime(meta), rd))
    return [rd for (_m, rd) in sorted(out, reverse=True)]


# --------------------------------------------------------------------------
# Derived signals
# --------------------------------------------------------------------------
#: straggler-score dimensions: per-worker value / cohort median, all
#: oriented so BIGGER = slower (intervals, latencies, staleness).  The
#: value is additive smoothing applied to BOTH sides of the ratio:
#: staleness is a small integer near zero on a healthy cohort, so raw
#: 3-vs-1 ratios would flag noise -- (v+2)/(median+2) needs a genuinely
#: large staleness to clear the factor, while latency dims (floats well
#: above zero) stay unsmoothed.
STRAGGLER_DIMS = {"interval_ms": 0.0, "staleness": 2.0, "rtt_ms": 0.0,
                  "compute_ms": 0.0}


def _median(vals: Sequence[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return statistics.median(vals) if vals else None


def straggler_scores(wstats: Dict[object, dict],
                     factor: float = 2.5,
                     min_accepted: int = 10) -> Dict[str, dict]:
    """Per-worker straggler scores vs the cohort median.

    ``wstats`` is the PS's per-worker stats section (``ps_workers``):
    wid -> flat dims.  Score = max over :data:`STRAGGLER_DIMS` of
    ``worker_value / median(the OTHER workers' values)`` -- excluding
    self keeps the score meaningful in small cohorts (with 2 workers an
    inclusive median would cap every ratio below 2, so a 10x straggler
    could never flag).  A dim needs >= 2 workers reporting and a
    positive peer median to vote; ``flagged`` at >= ``factor``.

    Warm-up guard: a worker reporting an ``accepted`` count below
    ``min_accepted`` neither scores nor votes -- its EWMAs are one or
    two samples deep (boot staggering, the calibration pause), exactly
    the noise that flags the WRONG member while half the cohort is
    still importing jax.  Stats without an ``accepted`` key (synthetic
    fixtures) are always eligible.  Pure -- the tests drive it with
    synthetic cohorts."""
    def eligible(st) -> bool:
        if not isinstance(st, dict):
            return False
        acc = st.get("accepted")
        return acc is None or (isinstance(acc, (int, float))
                               and acc >= min_accepted)

    dims_present: Dict[str, Dict[str, float]] = {}
    for dim in STRAGGLER_DIMS:
        col = {}
        for wid, st in (wstats or {}).items():
            if not eligible(st):
                continue
            v = st.get(dim)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v >= 0:
                col[str(wid)] = float(v)
        if len(col) >= 2:
            dims_present[dim] = col
    out: Dict[str, dict] = {}
    for wid in {str(w) for w in (wstats or {})}:
        ratios: Dict[str, float] = {}
        for dim, col in dims_present.items():
            if wid not in col:
                continue
            smooth = STRAGGLER_DIMS[dim]
            med = _median([v for w, v in col.items() if w != wid])
            if med is None or med + smooth <= 0:
                continue
            ratios[dim] = (col[wid] + smooth) / (med + smooth)
        score = max(ratios.values()) if ratios else None
        out[wid] = {
            "score": None if score is None else round(score, 3),
            "dims": {d: round(r, 3) for d, r in ratios.items()},
            "flagged": bool(score is not None and score >= factor),
        }
    return out


# --------------------------------------------------------------------------
# The collector
# --------------------------------------------------------------------------
class ClusterObserver:
    """Scrape loop + history store + derived-signal computation.

    Construction reads the ``async.observer.*`` conf defaults; every
    knob is overridable per instance (tests run sub-second intervals).
    ``flight_dirs`` are harvested each tick (plus once at stop)."""

    def __init__(self, targets: Sequence[RoleTarget] = (),
                 interval_s: Optional[float] = None,
                 history_dir: Optional[str] = None,
                 history_points: Optional[int] = None,
                 persist_s: Optional[float] = None,
                 straggler_factor: Optional[float] = None,
                 flight_dirs: Sequence[str] = (),
                 run_id: Optional[str] = None):
        from asyncframework_tpu.conf import (
            OBSERVER_HISTORY_DIR,
            OBSERVER_HISTORY_POINTS,
            OBSERVER_INTERVAL_S,
            OBSERVER_PERSIST_S,
            OBSERVER_STRAGGLER_FACTOR,
            global_conf,
        )
        from asyncframework_tpu.metrics.live import RUN_ID

        conf = global_conf()
        self.interval_s = (float(conf.get(OBSERVER_INTERVAL_S))
                           if interval_s is None else float(interval_s))
        self.persist_s = (float(conf.get(OBSERVER_PERSIST_S))
                          if persist_s is None else float(persist_s))
        self.straggler_factor = (
            float(conf.get(OBSERVER_STRAGGLER_FACTOR))
            if straggler_factor is None else float(straggler_factor))
        root = (str(conf.get(OBSERVER_HISTORY_DIR) or "").strip()
                if history_dir is None else str(history_dir))
        points = (int(conf.get(OBSERVER_HISTORY_POINTS))
                  if history_points is None else int(history_points))
        self.history = RunHistoryStore(root or None, run_id or RUN_ID,
                                       points=points)
        self.flight_dirs = [str(d) for d in flight_dirs if d]
        self._lock = threading.Lock()
        self._static: List[RoleTarget] = list(targets)
        self._discovered_names: set = set()
        self._target_state: Dict[str, dict] = {}
        self._last_status: Dict[str, dict] = {}
        self._derived: Dict[str, float] = {}
        self._stragglers: Dict[str, dict] = {}
        self._flagged: set = set()
        #: (primary role name, t_s, ps.accepted) of the last tick --
        #: push_rate only differences the SAME role's counter
        self._prev_accept: Optional[Tuple[str, float, float]] = None
        self._push_rate: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._source_fn: Optional[Callable] = None
        self._section_fn: Optional[Callable] = None

    # ------------------------------------------------------------- discovery
    def add_targets(self, targets: Sequence[RoleTarget]) -> None:
        with self._lock:
            known = {t.name for t in self._static}
            for t in targets:
                if t.name not in known:
                    self._static.append(t)
                    known.add(t.name)

    def _discover_shardgroup(self) -> List[RoleTarget]:
        """The active ShardGroup's per-shard telemetry endpoints (the
        controller pre-assigns each slot a metrics port, so a relaunched
        shard keeps its scrape URL)."""
        try:
            from asyncframework_tpu.parallel import shardgroup

            group = shardgroup.active_group()
            if group is None:
                return []
            return [RoleTarget(name=n, role=r, url=u)
                    for (n, r, u) in group.telemetry_targets()]
        except Exception:  # noqa: BLE001 - a half-built group must not
            return []      # kill the scrape loop

    def _discover_supervisors(self) -> List[RoleTarget]:
        """Worker processes registered with any live ElasticSupervisor
        in this process: HELLO advertises the worker's telemetry port
        (``mport``), the supervisor records it, the observer scrapes
        it."""
        try:
            from asyncframework_tpu.parallel import supervisor as sup_mod

            out: List[RoleTarget] = []
            seen = set()
            for sup in sup_mod.active_supervisors():
                for rec in sup.proc_records():
                    mport = rec.get("mport")
                    host = rec.get("host")
                    proc = rec.get("proc")
                    if not mport or not host or proc in seen:
                        continue
                    seen.add(proc)
                    out.append(RoleTarget(
                        name=f"worker-{proc}", role="worker",
                        url=f"http://{host}:{int(mport)}"))
            return out
        except Exception:  # noqa: BLE001 - discovery is best-effort
            return []

    def targets(self) -> List[RoleTarget]:
        """Static + discovered targets, deduped by name (static wins)."""
        with self._lock:
            out = list(self._static)
            known = set(self._discovered_names)
        seen = {t.name for t in out}
        fresh = []
        for t in self._discover_shardgroup() + self._discover_supervisors():
            if t.name not in seen:
                seen.add(t.name)
                out.append(t)
                if t.name not in known:
                    fresh.append(t.name)
        if fresh:
            # counted once per NAME, not once per tick: "discovered" is
            # how many roles discovery ever surfaced, not a tick rate
            with self._lock:
                new = [n for n in fresh
                       if n not in self._discovered_names]
                self._discovered_names.update(new)
            if new:
                _bump("discovered", len(new))
        return out

    # --------------------------------------------------------------- scraping
    def _fetch_status(self, target: RoleTarget) -> dict:
        """One /api/status fetch over the net/ retry plane (short
        policy; the scrape LOOP is the real retry, and the shared
        breaker keeps a dead role from stalling every tick)."""
        from asyncframework_tpu.net.retry import RetryPolicy

        url = target.url.rstrip("/") + "/api/status"
        timeout = max(0.2, min(2.0, self.interval_s or 1.0))

        def get() -> dict:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read().decode())

        policy = RetryPolicy(max_attempts=2, base_ms=20.0, max_ms=100.0,
                             attempt_timeout_s=timeout,
                             deadline_s=2 * timeout,
                             breaker_threshold=5, breaker_cooldown_s=2.0)
        return policy.call(get, endpoint=target.url)

    def _fold(self, target: RoleTarget, status: dict, t_s: float) -> None:
        hist = self.history
        hist.note_role(target.name, target.role, target.url)
        hist.record(target.name, "up", t_s, 1.0)
        # the per-process sampler already normalized everything into
        # series; its last-value map is the scrape surface
        last = ((status.get("timeseries") or {}).get("last") or {})
        for key, v in last.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                hist.record(target.name, key, t_s, v)
        # driver-dashboard scalars (the PS with a run listener)
        for key in ("updates_per_sec", "accepted", "dropped",
                    "model_version", "queue_depth", "max_staleness"):
            v = status.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                hist.record(target.name, f"run.{key}", t_s, v)
        # continuous-profiling section (async.prof.enabled roles):
        # harvest the snapshot next to the flight dumps so the zone
        # decomposition outlives the process even without a crash
        prof = status.get("profile")
        if isinstance(prof, dict) and prof.get("zones"):
            hist.harvest_profile(prof, f"scrape:{target.name}")

    def scrape_once(self) -> dict:
        """One pass over every target; returns per-target ok/error (the
        CLI prints it) and recomputes the derived signals."""
        t_s = time.time()
        results: Dict[str, dict] = {}
        current = self.targets()
        # prune DISCOVERED targets that discovery no longer returns (a
        # promotion handed the role a new port, a supervisor forgot a
        # member): their stale last-status must not keep feeding the
        # derived signals or the roles_up count.  Static targets stay --
        # the operator asked for them, DOWN is the honest answer there.
        names = {t.name for t in current}
        with self._lock:
            gone = [n for n in self._discovered_names if n not in names]
            for n in gone:
                self._discovered_names.discard(n)
                self._target_state.pop(n, None)
                self._last_status.pop(n, None)
        for target in current:
            try:
                status = self._fetch_status(target)
            except (OSError, ValueError) as e:
                _bump("scrape_errors")
                self.history.record(target.name, "up", t_s, 0.0)
                self.history.note_role(target.name, target.role,
                                       target.url)
                results[target.name] = {"ok": False,
                                        "error": f"{type(e).__name__}"}
                with self._lock:
                    st = self._target_state.setdefault(target.name, {})
                    st.update(role=target.role, url=target.url, up=False)
                    st["errors"] = st.get("errors", 0) + 1
                continue
            _bump("scrapes")
            self._fold(target, status, t_s)
            with self._lock:
                self._last_status[target.name] = status
                st = self._target_state.setdefault(target.name, {})
                st.update(role=target.role, url=target.url, up=True,
                          last_ok_s=t_s)
            results[target.name] = {"ok": True}
        self._recompute_derived(t_s)
        self.harvest_flight()
        return results

    # --------------------------------------------------------------- derived
    def _recompute_derived(self, t_s: float) -> None:
        with self._lock:
            states = dict(self._target_state)
            # derived signals read LIVE roles only: a dead role's final
            # scraped status must not keep owning primary selection,
            # push_rate, or the fleet_done gate after a failover (the
            # fleet view still shows the corpse's last numbers per
            # role; the cross-role signals follow the living)
            statuses = {n: s for n, s in self._last_status.items()
                        if states.get(n, {}).get("up")}
        derived: Dict[str, float] = {}
        up = sum(1 for st in states.values() if st.get("up"))
        derived["roles_up"] = float(up)
        derived["roles_down"] = float(len(states) - up)

        def series_last(status: dict, key: str) -> Optional[float]:
            v = ((status.get("timeseries") or {}).get("last")
                 or {}).get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
            return None

        # the primary PS view: the scraped role with the largest
        # ps.accepted owns the merge plane (secondaries/standbys report
        # their own ranges)
        primary: Optional[dict] = None
        primary_name: Optional[str] = None
        best = -1.0
        done = 0.0
        for name, status in statuses.items():
            acc = series_last(status, "ps.accepted")
            if acc is not None and acc > best:
                best, primary, primary_name = acc, status, name
        if primary is not None:
            acc = series_last(primary, "ps.accepted")
            qd = series_last(primary, "ps.queue_depth")
            if qd is not None:
                derived["merge_queue_depth"] = qd
            if series_last(primary, "ps.done"):
                done = 1.0
            # push_rate = d(ps.accepted)/dt of the SAME role across
            # ticks: when the argmax flips (close shard counts, a
            # failover to a lower-clock member) the baseline resets
            # instead of differencing two different counters into a
            # spurious spike.  Under the lock: scrape_once can run
            # concurrently (loop + bench's final manual scrape).
            if acc is not None:
                with self._lock:
                    prev = self._prev_accept
                    self._prev_accept = (primary_name, t_s, acc)
                    if prev is not None and prev[0] == primary_name \
                            and t_s > prev[1]:
                        self._push_rate = max(
                            0.0, (acc - prev[2]) / (t_s - prev[1]))
                    rate = self._push_rate
                if rate is not None:
                    derived["push_rate"] = round(rate, 3)
        derived["fleet_done"] = done
        # fleet freshness: the STALEST serving replica prices the fleet
        lags = [series_last(s, "serving.freshness_lag_ms")
                for s in statuses.values()]
        lags = [v for v in lags if v is not None]
        if lags:
            derived["freshness_lag_ms"] = max(lags)
        # per-worker straggler scores from whichever role carries the
        # PS's per-worker stats section (the primary's /api/status)
        wstats: Dict[str, dict] = {}
        for status in statuses.values():
            sec = status.get("ps_workers")
            if isinstance(sec, dict) and sec:
                wstats.update(sec)
        stragglers = straggler_scores(wstats, self.straggler_factor)
        scores = [s["score"] for s in stragglers.values()
                  if s.get("score") is not None]
        if scores:
            derived["straggler_score"] = max(scores)
        newly = {w for w, s in stragglers.items() if s["flagged"]}
        with self._lock:
            fresh = newly - self._flagged
            self._flagged |= newly
            self._stragglers = stragglers
            self._derived = derived
        if fresh:
            _bump("stragglers_flagged", len(fresh))
        # the derived signals are a role too: the controller reading
        # history wants observer.* next to every ps.* series
        for k, v in derived.items():
            self.history.record("observer", f"observer.{k}", t_s, v)

    def derived(self) -> Dict[str, float]:
        """The flat ``observer.*`` source dict (registered with the
        sampler; also what bench snapshots)."""
        with self._lock:
            return dict(self._derived)

    def stragglers(self) -> Dict[str, dict]:
        """The per-worker straggler table from the last recompute
        (wid -> score/flagged/dims) -- the adaptive controller's
        per-worker damp input (parallel/controller.py)."""
        with self._lock:
            return {w: dict(s) for w, s in self._stragglers.items()}

    # ---------------------------------------------------------------- flight
    #: how far before this collector's start a dump may have been
    #: written and still belong to ITS run: roles often boot (and flush)
    #: before the collector, but a dump idle since long before that is
    #: a previous run's leftover (dumps are never cleaned up -- a
    #: restarted collector against yesterday's --flight-dir must not
    #: attribute yesterday's crashes to today's run)
    FLIGHT_MAX_AGE_S = 120.0

    def harvest_flight(self) -> int:
        """Scan the flight dirs for dumps and fold new/fresher ones into
        the history store; returns how many were (re)harvested.  Dumps
        last written more than :data:`FLIGHT_MAX_AGE_S` before this
        collector started are skipped (counted) as stale leftovers."""
        from asyncframework_tpu.metrics import flightrec

        cutoff = self.history.started_s - self.FLIGHT_MAX_AGE_S
        n = stale = 0
        for d in self.flight_dirs:
            for path in flightrec.scan_dumps(d):
                try:
                    dump = flightrec.load_dump(path)
                except (OSError, ValueError):
                    continue  # torn mid-write: the next flush completes it
                if float(dump.get("dumped_s") or 0) < cutoff:
                    stale += 1
                    continue
                if self.history.harvest(dump, source=path):
                    n += 1
        if n:
            _bump("harvests", n)
        if stale:
            _bump("harvest_stale_skipped", stale)
        return n

    # --------------------------------------------------------------- serving
    def fleet_snapshot(self) -> dict:
        """The ``observer`` /api/status section + async-top's fleet
        view: per-role liveness and key numbers, derived signals,
        straggler table, history summary."""
        with self._lock:
            states = {n: dict(st) for n, st in self._target_state.items()}
            statuses = dict(self._last_status)
            derived = dict(self._derived)
            stragglers = dict(self._stragglers)

        def series_last(status, key):
            v = ((status.get("timeseries") or {}).get("last")
                 or {}).get(key)
            return v if isinstance(v, (int, float)) else None

        roles = {}
        for name, st in sorted(states.items()):
            status = statuses.get(name) or {}
            roles[name] = {
                "role": st.get("role"),
                "url": st.get("url"),
                "up": bool(st.get("up")),
                "errors": st.get("errors", 0),
                "run_id": status.get("run_id"),
                "health": (status.get("health") or {}).get("state"),
                "accepted": series_last(status, "ps.accepted"),
                "staleness": series_last(status, "ps.max_staleness"),
                "qps": series_last(status, "serving.qps"),
                "freshness_lag_ms": series_last(
                    status, "serving.freshness_lag_ms"),
            }
            # compact zone-share row (async.prof.enabled roles): the
            # top sampled zones, enough for async-mon's fleet table
            # without dragging whole stack maps through every snapshot
            prof = status.get("profile")
            if isinstance(prof, dict) and isinstance(prof.get("zones"),
                                                     dict):
                top = sorted(
                    ((z, float((d or {}).get("share", 0.0)))
                     for z, d in prof["zones"].items()),
                    key=lambda kv: -kv[1])[:4]
                roles[name]["profile"] = {
                    "samples": prof.get("samples", 0),
                    "zones": {z: round(s, 4) for z, s in top if s > 0},
                }
        # adaptive control plane: whichever LIVE role serves a
        # ``control`` status section (the primary PS running the
        # AsyncController) contributes it to the fleet view, so
        # async-mon renders the current knob values next to the
        # stragglers that drive them.  Live roles only -- a SIGKILLed
        # primary's cached final status must not shadow its
        # replacement's board (the corpse-owns-the-fleet-view class
        # the derived signals were already hardened against)
        control = None
        for name, st in sorted(states.items()):
            if not st.get("up"):
                continue
            sec = (statuses.get(name) or {}).get("control")
            if isinstance(sec, dict) and sec:
                control = {"role": name, **sec}
                break
        out = {
            "interval_s": self.interval_s,
            "roles": roles,
            "derived": derived,
            "stragglers": stragglers,
            "straggler_factor": self.straggler_factor,
            "history": self.history.summary(),
            "totals": observer_totals(),
        }
        if control is not None:
            out["control"] = control
        return out

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ClusterObserver":
        """Register the ``observer`` source + status section and start
        the scrape loop (interval <= 0: registration only)."""
        from asyncframework_tpu.metrics import live, timeseries

        self._source_fn = self.derived
        timeseries.register_source("observer", self._source_fn)
        self._section_fn = self.fleet_snapshot
        live.register_status_section("observer", self._section_fn)
        timeseries.ensure_started()
        if self.interval_s <= 0:
            return self
        last_persist = [time.monotonic()]

        def loop() -> None:
            while not self._stop.wait(timeout=self.interval_s):
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 - one bad tick must not
                    pass           # end observation for good
                if (self.persist_s > 0 and
                        time.monotonic() - last_persist[0]
                        >= self.persist_s):
                    last_persist[0] = time.monotonic()
                    try:
                        self.history.persist()
                    except OSError:
                        pass  # a full disk must not kill the scrape loop

        self._thread = threading.Thread(
            target=loop, name="observer-scrape", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        from asyncframework_tpu.metrics import live, timeseries

        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._source_fn is not None:
            timeseries.unregister_source("observer", self._source_fn)
            self._source_fn = None
        if self._section_fn is not None:
            live.unregister_status_section("observer", self._section_fn)
            self._section_fn = None
        self.harvest_flight()
        try:
            self.history.persist()
        except OSError:
            pass


# --------------------------------------------------------------------------
# CLI (bin/async-mon)
# --------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    from asyncframework_tpu.conf import OBSERVER_ENDPOINTS, global_conf

    p = argparse.ArgumentParser(
        "async-mon",
        description="cluster observer: scrape every role, persist run "
                    "history, derive fleet signals, harvest flight "
                    "recorder dumps",
    )
    p.add_argument("--endpoints", default=None,
                   help="';'-separated name=role@host:port targets "
                        "(default: conf async.observer.endpoints)")
    p.add_argument("--interval", type=float, default=None,
                   help="scrape period seconds (default: conf)")
    p.add_argument("--history-dir", default=None,
                   help="run-history root (default: conf "
                        "async.observer.history.dir; empty = memory "
                        "only)")
    p.add_argument("--flight-dir", action="append", default=[],
                   help="flight-recorder dump dir to harvest "
                        "(repeatable)")
    p.add_argument("--port", type=int, default=None,
                   help="serve this collector's own /api/status + "
                        "/metrics here (0 = ephemeral; default: conf "
                        "async.metrics.port gating)")
    p.add_argument("--once", action="store_true",
                   help="one scrape, print the fleet view, exit")
    args = p.parse_args(argv)

    text = (args.endpoints if args.endpoints is not None
            else str(global_conf().get(OBSERVER_ENDPOINTS)))
    obs = ClusterObserver(
        targets=parse_endpoints(text),
        interval_s=args.interval,
        history_dir=args.history_dir,
        flight_dirs=args.flight_dir,
    )
    if args.once:
        obs.scrape_once()
        from asyncframework_tpu.metrics.top import render_fleet

        sys.stdout.write(render_fleet(obs.fleet_snapshot()))
        obs.history.persist()
        return 0
    from asyncframework_tpu.metrics.live import LiveUIServer

    srv = None
    if args.port is not None:
        srv = LiveUIServer(None, port=args.port, host="0.0.0.0",
                           role="observer").start()
        print(f"async-mon: serving fleet view on port {srv.port}",
              flush=True)
    # SIGTERM (kubectl delete / rollout restart of the rendered
    # Deployment) must run the same graceful path as Ctrl-C: the final
    # flight harvest + history persist in obs.stop() is the whole point
    # of a durable collector
    stop_ev = threading.Event()
    try:
        import signal as _signal

        _signal.signal(_signal.SIGTERM, lambda *_a: stop_ev.set())
    except (ValueError, OSError):
        pass  # not the main thread (embedded use): Ctrl-C still works
    obs.start()
    try:
        while not stop_ev.wait(timeout=60.0):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        obs.stop()
        if srv is not None:
            srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
