"""Distributed tracing for the async update loop.

The ASYNC paper's contribution (arXiv:1907.08526) is *bounded-staleness*
asynchrony; ASAP (arXiv:1612.08608) argues the quantity to tune against is
staleness **in time**, not versions.  Neither is measurable when the DCN
plane (PSClient -> ParameterServer over ``net/frame.py``) is a telemetry
black hole.  This module makes one update's life observable end to end:

- a **trace context** ``(trace_id, span_id, worker_id, model_version)``
  rides every frame as an optional ``tc`` header entry, stamped at the one
  framing choke point (``net/frame.send_msg`` consults the thread-local
  context installed here) -- so PULL/PUSH/PULL_SAGA/PUSH_SAGA, topic, and
  master ops are all covered without per-callsite plumbing;
- **lifecycle spans** decompose an update's wall-clock:

  ========== =======================================================
  stage      measured where
  ========== =======================================================
  pull.wait  PS: time the PULL sat in the partial-barrier wave gate
  pull.rtt   worker: whole PULL round trip (client-observed)
  compute    worker: gradient step dispatch + device->host readback
  push.wait  worker: encode/stamp time between compute and the wire
  push.rtt   worker: whole PUSH round trip (client-observed)
  merge.queue PS: PUSH decode + wait for the model lock
  merge.apply PS: time under the lock (tau filter + apply dispatch)
  ========== =======================================================

- workers record completed spans into a bounded **lock-light ring buffer**
  (sampled at ``async.trace.sample``, default 1/64, counter-based so the
  first update per worker is always sampled; rate 0 = off with zero wire
  bytes and zero hot-path work) and **piggyback** them on the next PUSH
  header -- exactly like the elastic plane piggybacks adoption orders on
  PULL replies -- so spans survive worker death;
- the PS folds its own server-side spans plus the piggybacked ones into
  the process-global :class:`TraceAggregator` (live UI ``trace`` section:
  per-stage p50/p95/p99 and staleness in versions AND milliseconds) and,
  when given a bus, posts them as ``TraceSpan`` events -> event log ->
  history server.

``bin/async-trace`` (this module's :func:`main`) replays an event log,
reconstructs per-update critical paths, prints a latency-decomposition
table plus a per-worker straggler report, and exports Chrome
``chrome://tracing`` JSON.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from collections import OrderedDict, defaultdict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

# stage names, in canonical critical-path order
PULL_WAIT = "pull.wait"
PULL_RTT = "pull.rtt"
#: pipelined worker loop only (parallel/ps_dcn.py, async.pipeline.depth
#: >= 1): the update loop's RESIDUAL stall -- time the main loop blocked
#: waiting for its prefetched model or for in-flight push-queue space.
#: In the serial loop this time is pull.rtt + push.rtt on the critical
#: path; pipelining overlaps those with compute, and whatever stall is
#: left shows up here.
PIPELINE = "pipeline"
COMPUTE = "compute"
PUSH_WAIT = "push.wait"
PUSH_RTT = "push.rtt"
MERGE_QUEUE = "merge.queue"
MERGE_APPLY = "merge.apply"

STAGES = (PULL_WAIT, PULL_RTT, PIPELINE, COMPUTE, PUSH_WAIT, PUSH_RTT,
          MERGE_QUEUE, MERGE_APPLY)
#: stages recorded client-side (worker process) vs server-side (PS)
CLIENT_STAGES = (PULL_RTT, PIPELINE, COMPUTE, PUSH_WAIT, PUSH_RTT)
SERVER_STAGES = (PULL_WAIT, MERGE_QUEUE, MERGE_APPLY)
#: the minimum chain proving a cross-process trace survived the wire
CHAIN_STAGES = (PULL_RTT, COMPUTE, PUSH_RTT)


def now_ms() -> float:
    """Wall-clock epoch milliseconds: the one span time base.  Monotonic
    clocks do not compare across processes, and a trace IS cross-process."""
    return time.time() * 1e3


# One random prefix per process + an atomic counter: minting an id costs a
# counter bump and a format, not a uuid4 entropy syscall.  The hot path
# mints four ids per sampled update, and measured on the CPU test rig even
# single-digit microseconds per merge in the updater thread measurably
# shifts marginal-stability ASGD runs -- id minting must be near-free.
_ID_PREFIX = uuid.uuid4().hex[:8]
_ID_COUNTER = itertools.count(1)


def _new_id(n: int = 16) -> str:
    c = next(_ID_COUNTER)
    if n >= 16:
        return _ID_PREFIX + format(c & 0xFFFFFFFF, "08x")
    return _ID_PREFIX[:2] + format(c & 0xFFFFFF, "06x")


@dataclass
class Span:
    """One completed stage of a traced update (host-side record)."""

    stage: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    worker_id: int
    model_version: int
    start_ms: float
    dur_ms: float
    staleness: Optional[int] = None
    staleness_ms: Optional[float] = None
    accepted: Optional[bool] = None
    #: wire bytes of the RPC this span covers (pull.rtt/push.rtt: frame
    #: bytes both directions, counted at the net/frame.py choke point) --
    #: latency AND volume decompose per stage
    bytes: Optional[int] = None

    # wire format: short keys, Nones omitted -- spans ride PUSH headers
    _WIRE = (("s", "stage"), ("t", "trace_id"), ("i", "span_id"),
             ("p", "parent_id"), ("w", "worker_id"), ("v", "model_version"),
             ("b", "start_ms"), ("d", "dur_ms"), ("st", "staleness"),
             ("sm", "staleness_ms"), ("ac", "accepted"), ("by", "bytes"))

    def to_wire(self) -> dict:
        out = {}
        for short, name in self._WIRE:
            v = getattr(self, name)
            if v is not None:
                out[short] = v
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "Span":
        kw = {name: d.get(short) for short, name in cls._WIRE}
        kw["stage"] = str(kw["stage"])
        kw["trace_id"] = str(kw["trace_id"])
        kw["span_id"] = str(kw.get("span_id") or _new_id(8))
        # `x or default` would eat legitimate zeros -- model_version 0 is
        # the PS's FIRST served clock, and the first update is exactly the
        # one counter-based sampling always traces
        for name, default in (("worker_id", 0), ("model_version", -1)):
            v = kw.get(name)
            kw[name] = default if v is None else int(v)
        for name in ("start_ms", "dur_ms"):
            v = kw.get(name)
            kw[name] = 0.0 if v is None else float(v)
        return cls(**kw)


class TraceContext:
    """The propagated identity of one traced update: ``trace_id`` pins the
    lifecycle, ``span_id`` is the client span covering the in-flight RPC
    (the server's parent), ``worker_id``/``model_version`` locate it."""

    __slots__ = ("trace_id", "span_id", "worker_id", "model_version")

    def __init__(self, trace_id: str, worker_id: int,
                 model_version: int = -1, span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id or _new_id(8)
        self.worker_id = int(worker_id)
        self.model_version = int(model_version)

    def wire(self) -> list:
        return [self.trace_id, self.span_id, self.worker_id,
                self.model_version]

    @classmethod
    def from_wire(cls, tc: Sequence) -> Optional["TraceContext"]:
        try:
            return cls(str(tc[0]), int(tc[2]), int(tc[3]), str(tc[1]))
        except (IndexError, KeyError, TypeError, ValueError):
            # junk from the wire (wrong type, a dict, short list) must
            # never kill a connection handler -- KeyError included: a JSON
            # object's tc[0] raises it, not IndexError
            return None


# ------------------------------------------------------- ambient propagation
# Thread-local current context: net/frame.py's send_msg stamps every frame
# sent while one is installed.  With nothing installed the cost is one TLS
# getattr + branch, and frames are byte-identical to the pre-trace wire.
_tls = threading.local()


def set_current(ctx: Optional[TraceContext]) -> None:
    _tls.ctx = ctx


def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def wire_header() -> Optional[list]:
    """The ``tc`` header value to stamp, or None (tracing off / untraced
    update).  Called by ``net/frame.send_msg`` on every frame."""
    ctx = getattr(_tls, "ctx", None)
    return None if ctx is None else ctx.wire()


# ------------------------------------------------------------- worker side
class UpdateTrace:
    """One sampled update's in-progress trace on the worker: collects its
    client-side spans and hands the ambient context to the RPCs."""

    __slots__ = ("ctx", "_sink", "spans")

    def __init__(self, ctx: TraceContext, sink: Callable[[Span], None]):
        self.ctx = ctx
        self._sink = sink
        self.spans: List[Span] = []

    def set_model_version(self, mv: int) -> None:
        """Learned from the pull reply; back-fills spans recorded before
        the version was known (pull.rtt itself)."""
        self.ctx.model_version = int(mv)
        for sp in self.spans:
            if sp.model_version < 0:
                sp.model_version = int(mv)

    def add(self, stage: str, start_ms: float, end_ms: float,
            **attrs) -> Span:
        sp = Span(
            stage=stage, trace_id=self.ctx.trace_id, span_id=_new_id(8),
            parent_id=None, worker_id=self.ctx.worker_id,
            model_version=self.ctx.model_version, start_ms=start_ms,
            dur_ms=max(0.0, end_ms - start_ms), **attrs,
        )
        self.spans.append(sp)
        self._sink(sp)
        return sp

    def rpc_begin(self, stage: str) -> tuple:
        """Mint the RPC span's id, install it as the wire span_id, install
        the ambient context; returns the token ``rpc_end`` needs."""
        span_id = _new_id(8)
        self.ctx.span_id = span_id
        set_current(self.ctx)
        return (stage, span_id, now_ms())

    def rpc_end(self, token: tuple, **attrs) -> Span:
        """Uninstall the ambient context and record the RPC span."""
        set_current(None)
        stage, span_id, t0 = token
        sp = Span(
            stage=stage, trace_id=self.ctx.trace_id, span_id=span_id,
            parent_id=None, worker_id=self.ctx.worker_id,
            model_version=self.ctx.model_version, start_ms=t0,
            dur_ms=max(0.0, now_ms() - t0), **attrs,
        )
        self.spans.append(sp)
        self._sink(sp)
        return sp


class TraceRecorder:
    """Per-process sampling decision + bounded ring of completed spans.

    ``sample_rate`` / ``capacity`` default from conf (``async.trace.sample``
    / ``async.trace.buffer``).  Sampling is counter-based per worker id --
    deterministic, and the FIRST update of every worker is always sampled
    when the rate is > 0, so even a short run yields a complete trace.
    With rate 0 (or a None recorder) the hot path does no tracing work at
    all and no wire bytes are added.
    """

    def __init__(self, sample_rate: Optional[float] = None,
                 capacity: Optional[int] = None,
                 sink: Optional[Callable[[Span], None]] = None):
        if sample_rate is None or capacity is None:
            from asyncframework_tpu.conf import (
                TRACE_BUFFER,
                TRACE_SAMPLE,
                global_conf,
            )

            conf = global_conf()
            if sample_rate is None:
                sample_rate = float(conf.get(TRACE_SAMPLE))
            if capacity is None:
                capacity = int(conf.get(TRACE_BUFFER))
        rate = max(0.0, min(1.0, float(sample_rate)))
        self.sample_rate = rate
        self.interval = 0 if rate <= 0.0 else max(1, round(1.0 / rate))
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._ring: "deque[Span]" = deque(maxlen=self.capacity)
        self._sink = sink
        self.sampled = 0
        self.dropped_spans = 0
        self._ring_len_hw = 0

    @property
    def enabled(self) -> bool:
        return self.interval > 0

    def start_update(self, worker_id: int) -> Optional[UpdateTrace]:
        """The per-update sampling decision; None = not traced."""
        if self.interval == 0:
            return None
        with self._lock:
            n = self._counts.get(worker_id, 0)
            self._counts[worker_id] = n + 1
            if n % self.interval != 0:
                return None
            self.sampled += 1
        return UpdateTrace(
            TraceContext(_new_id(16), worker_id), self._record
        )

    def _record(self, span: Span) -> None:
        if self._sink is not None:
            self._sink(span)
            return
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped_spans += 1
            self._ring.append(span)

    def drain_wire(self, max_spans: int = 128) -> List[dict]:
        """Completed spans awaiting shipment, as wire dicts (the PUSH
        piggyback; also drained by BYE so a run's tail spans land).  A
        caller whose send terminally fails should :meth:`requeue` what it
        drained so the spans ride the next attempt instead of vanishing."""
        out: List[dict] = []
        with self._lock:
            while self._ring and len(out) < max_spans:
                out.append(self._ring.popleft().to_wire())
        return out

    def requeue(self, wire_spans: List[dict]) -> None:
        """Put drained-but-undelivered wire spans back at the FRONT of the
        ring (a push that spent its whole retry budget must not silently
        eat its piggyback -- those spans describe exactly the fault window
        a trace exists to explain).  Overflow evicts from the ring's other
        end, counted in ``dropped_spans``."""
        with self._lock:
            for d in reversed(wire_spans):
                try:
                    sp = Span.from_wire(d)
                except Exception:  # noqa: BLE001 - never raise on telemetry
                    continue
                if len(self._ring) == self._ring.maxlen:
                    self.dropped_spans += 1
                self._ring.appendleft(sp)


# ------------------------------------------------------------ aggregation
class TraceAggregator:
    """Folds spans into per-stage latency histograms + staleness (versions
    AND milliseconds) distributions; the ``trace`` section of the live UI
    and of ``bench.py --trace-jsonl`` is one :meth:`snapshot` of this."""

    def __init__(self, capacity: int = 4096):
        from asyncframework_tpu.metrics.system import Histogram

        self._lock = threading.Lock()
        self._mk = lambda: Histogram(capacity)
        self._stages: Dict[str, "Histogram"] = {}
        self._stage_bytes: Dict[str, "Histogram"] = {}
        self._staleness_v = self._mk()
        self._staleness_ms = self._mk()
        self.spans_total = 0
        self.traces_seen: "OrderedDict[str, None]" = OrderedDict()

    def add(self, span: Span) -> None:
        with self._lock:
            self.spans_total += 1
            h = self._stages.get(span.stage)
            if h is None:
                h = self._stages[span.stage] = self._mk()
            h.update(span.dur_ms)
            if span.bytes is not None:
                hb = self._stage_bytes.get(span.stage)
                if hb is None:
                    hb = self._stage_bytes[span.stage] = self._mk()
                hb.update(float(span.bytes))
            if span.staleness is not None:
                self._staleness_v.update(float(span.staleness))
            if span.staleness_ms is not None:
                self._staleness_ms.update(float(span.staleness_ms))
            self.traces_seen[span.trace_id] = None
            while len(self.traces_seen) > 4096:
                self.traces_seen.popitem(last=False)

    def add_wire(self, spans: Sequence[dict]) -> List[Span]:
        out = []
        for d in spans:
            try:
                sp = Span.from_wire(d)
            except Exception:  # noqa: BLE001 - junk from the wire
                continue
            self.add(sp)
            out.append(sp)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            stages = {
                name: self._stages[name].snapshot()
                for name in STAGES if name in self._stages
            }
            # stages outside the canonical vocabulary still show up
            for name in self._stages:
                if name not in stages:
                    stages[name] = self._stages[name].snapshot()
            out = {
                "spans": self.spans_total,
                "traces": len(self.traces_seen),
                "stages_ms": stages,
                "staleness_versions": self._staleness_v.snapshot(),
                "staleness_ms": self._staleness_ms.snapshot(),
            }
            if self._stage_bytes:
                # wire-volume decomposition beside the latency one: rtt
                # spans carry their RPC's frame bytes (net/frame.py)
                out["stages_bytes"] = {
                    name: h.snapshot()
                    for name, h in self._stage_bytes.items()
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()
            self._stage_bytes.clear()
            self._staleness_v = self._mk()
            self._staleness_ms = self._mk()
            self.spans_total = 0
            self.traces_seen.clear()


_global_lock = threading.Lock()
_global_agg: Optional[TraceAggregator] = None


def aggregator() -> TraceAggregator:
    """The process-global aggregator (live UI / bench read it; the PS and
    RunInstruments write it)."""
    global _global_agg
    with _global_lock:
        if _global_agg is None:
            _global_agg = TraceAggregator()
        return _global_agg


def reset_aggregator() -> None:
    aggregator().reset()


def span_event(span: Span, time_ms: float) -> "object":
    """A :class:`~asyncframework_tpu.metrics.bus.TraceSpan` bus event for a
    span (posting process supplies its run-relative ``time_ms``)."""
    from asyncframework_tpu.metrics.bus import TraceSpan

    return TraceSpan(
        time_ms=time_ms, stage=span.stage, trace_id=span.trace_id,
        span_id=span.span_id, parent_id=span.parent_id,
        worker_id=span.worker_id, model_version=span.model_version,
        start_ms=span.start_ms, dur_ms=span.dur_ms,
        staleness=span.staleness, staleness_ms=span.staleness_ms,
        accepted=span.accepted, bytes=span.bytes,
    )


# ----------------------------------------------- reconstruction (async-trace)
def _pct(vals: List[float], q: float) -> float:
    """Nearest-rank percentile -- THE rule, shared with the live
    histograms so post-hoc decomposition never disagrees with the UI."""
    from asyncframework_tpu.metrics.system import Histogram

    return Histogram._pct(vals, q)


def _stats(vals: List[float]) -> dict:
    vals = sorted(vals)
    n = len(vals)
    return {
        "count": n,
        "mean": sum(vals) / n,
        "p50": _pct(vals, 0.50),
        "p95": _pct(vals, 0.95),
        "p99": _pct(vals, 0.99),
        "max": vals[-1],
    }


def load_trace_events(event_log_path) -> tuple:
    """Replay an event log; returns (TraceSpan events, truncated_records)."""
    from asyncframework_tpu.metrics.bus import TraceSpan
    from asyncframework_tpu.metrics.eventlog import EventLogReader

    reader = EventLogReader(event_log_path)
    spans = [ev for ev in reader.replay(strict=False)
             if isinstance(ev, TraceSpan)]
    return spans, reader.truncated_records


def build_traces(spans) -> "OrderedDict[str, list]":
    """Group spans by trace_id, each ordered along the canonical critical
    path (stage order, then start time)."""
    order = {s: i for i, s in enumerate(STAGES)}
    by_trace: Dict[str, list] = defaultdict(list)
    for sp in spans:
        by_trace[sp.trace_id].append(sp)
    out: "OrderedDict[str, list]" = OrderedDict()
    for tid in sorted(by_trace,
                      key=lambda t: min(s.start_ms for s in by_trace[t])):
        out[tid] = sorted(
            by_trace[tid],
            key=lambda s: (order.get(s.stage, len(STAGES)), s.start_ms),
        )
    return out


def complete_traces(traces: "OrderedDict[str, list]") -> "OrderedDict[str, list]":
    """Traces whose span chain covers the full client critical path
    (pull.rtt -> compute -> push.rtt), i.e. survived the wire round trip."""
    out: "OrderedDict[str, list]" = OrderedDict()
    for tid, spans in traces.items():
        have = {s.stage for s in spans}
        if all(st in have for st in CHAIN_STAGES):
            out[tid] = spans
    return out


def decomposition(spans) -> dict:
    """Per-stage latency stats + staleness distributions from TraceSpan
    events (the post-hoc analog of TraceAggregator.snapshot)."""
    by_stage: Dict[str, List[float]] = defaultdict(list)
    by_bytes: Dict[str, List[float]] = defaultdict(list)
    stale_v: List[float] = []
    stale_ms: List[float] = []
    for sp in spans:
        by_stage[sp.stage].append(float(sp.dur_ms))
        b = getattr(sp, "bytes", None)
        if b is not None:
            by_bytes[sp.stage].append(float(b))
        if sp.staleness is not None:
            stale_v.append(float(sp.staleness))
        if sp.staleness_ms is not None:
            stale_ms.append(float(sp.staleness_ms))
    out = {
        "stages_ms": {
            st: _stats(by_stage[st])
            for st in STAGES if st in by_stage
        },
        "spans": len(spans),
    }
    for st in by_stage:
        if st not in out["stages_ms"]:
            out["stages_ms"][st] = _stats(by_stage[st])
    if by_bytes:
        # wire-volume decomposition beside the latency one: rtt spans
        # carry their RPC's frame bytes (net/frame.py choke point)
        out["stages_bytes"] = {
            st: _stats(v) for st, v in by_bytes.items()
        }
    if stale_v:
        out["staleness_versions"] = _stats(stale_v)
    if stale_ms:
        out["staleness_ms"] = _stats(stale_ms)
    return out


def straggler_report(spans) -> List[dict]:
    """Per-worker critical-path profile, slowest first: who is dragging the
    run, and in which stage."""
    by_worker: Dict[int, Dict[str, List[float]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for sp in spans:
        by_worker[sp.worker_id][sp.stage].append(float(sp.dur_ms))
    rows = []
    for wid, stages in by_worker.items():
        path_ms = sum(
            sum(v) / len(v) for st, v in stages.items()
            if st in CLIENT_STAGES
        )
        rows.append({
            "worker_id": wid,
            "spans": sum(len(v) for v in stages.values()),
            "critical_path_ms": path_ms,
            "mean_ms": {st: sum(v) / len(v) for st, v in stages.items()},
        })
    rows.sort(key=lambda r: -r["critical_path_ms"])
    if rows:
        med = sorted(r["critical_path_ms"] for r in rows)[len(rows) // 2]
        for r in rows:
            r["vs_median"] = (
                round(r["critical_path_ms"] / med, 2) if med > 0 else None
            )
    return rows


def chrome_trace(spans) -> dict:
    """Chrome ``chrome://tracing`` / Perfetto JSON: one complete ("X")
    event per span; pid = worker id, tid separates the worker's client
    stages from the PS-side stages of its updates."""
    events = []
    for sp in spans:
        client = sp.stage in CLIENT_STAGES
        args = {"trace_id": sp.trace_id, "model_version": sp.model_version}
        if sp.staleness is not None:
            args["staleness"] = sp.staleness
        if sp.staleness_ms is not None:
            args["staleness_ms"] = sp.staleness_ms
        if sp.accepted is not None:
            args["accepted"] = sp.accepted
        events.append({
            "name": sp.stage,
            "cat": "worker" if client else "ps",
            "ph": "X",
            "ts": sp.start_ms * 1e3,     # microseconds
            "dur": max(sp.dur_ms, 1e-3) * 1e3,
            "pid": int(sp.worker_id),
            "tid": 0 if client else 1,
            "args": args,
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "asyncframework-tpu bin/async-trace"},
    }


def _fmt_table(headers: List[str], rows: List[List[object]]) -> str:
    cells = [headers] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cells) for i in range(len(headers))]
    lines = []
    for j, row in enumerate(cells):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``bin/async-trace <event_log> [--chrome OUT.json] [--json]``."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="async-trace",
        description="Reconstruct per-update traces from an event log: "
        "latency decomposition, straggler report, Chrome tracing export.",
    )
    p.add_argument("event_log", help="JSONL(.gz) event log path")
    p.add_argument("--chrome", default=None, metavar="OUT",
                   help="write Chrome chrome://tracing JSON here")
    p.add_argument("--json", action="store_true",
                   help="print the machine-readable summary instead of "
                   "tables")
    args = p.parse_args(argv)

    spans, truncated = load_trace_events(args.event_log)
    traces = build_traces(spans)
    complete = complete_traces(traces)
    deco = decomposition(spans)
    stragglers = straggler_report(spans)
    summary = {
        "spans": len(spans),
        "traces": len(traces),
        "complete_traces": len(complete),
        "truncated_records": truncated,
        "decomposition": deco,
        "stragglers": stragglers,
    }
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(spans), f)
        summary["chrome"] = args.chrome
    if args.json:
        print(json.dumps(summary, default=float))
        # same exit contract as table mode: a trace-less log (sampling
        # off / no event log attached) is a configuration error scripted
        # callers must be able to gate on
        return 0 if spans else 1
    print(f"event log: {args.event_log}")
    print(f"spans: {len(spans)}  traces: {len(traces)}  "
          f"complete chains: {len(complete)}"
          + (f"  truncated records skipped: {truncated}" if truncated
             else ""))
    if not spans:
        print("no TraceSpan events found (was async.trace.sample > 0 and "
              "an event log attached?)", file=sys.stderr)
        return 1
    print("\nlatency decomposition (ms):")
    rows = []
    for st, s in deco["stages_ms"].items():
        rows.append([st, s["count"], f"{s['p50']:.2f}", f"{s['p95']:.2f}",
                     f"{s['p99']:.2f}", f"{s['max']:.2f}",
                     f"{s['mean']:.2f}"])
    print(_fmt_table(["stage", "count", "p50", "p95", "p99", "max", "mean"],
                     rows))
    for key, label in (("staleness_versions", "staleness (versions)"),
                       ("staleness_ms", "staleness (ms)")):
        if key in deco:
            s = deco[key]
            print(f"\n{label}: p50={s['p50']:.2f} p95={s['p95']:.2f} "
                  f"p99={s['p99']:.2f} max={s['max']:.2f}")
    print("\nper-worker straggler report (slowest first):")
    rows = []
    for r in stragglers:
        m = r["mean_ms"]
        rows.append([
            r["worker_id"], r["spans"], f"{r['critical_path_ms']:.2f}",
            r.get("vs_median"),
            f"{m.get(COMPUTE, 0.0):.2f}", f"{m.get(PULL_RTT, 0.0):.2f}",
            f"{m.get(PUSH_RTT, 0.0):.2f}",
        ])
    print(_fmt_table(
        ["worker", "spans", "critical-path ms", "vs median",
         "compute", "pull.rtt", "push.rtt"], rows,
    ))
    if args.chrome:
        print(f"\nchrome tracing JSON: {args.chrome} "
              "(open via chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via bin/async-trace
    import sys

    sys.exit(main())
