"""``async-top``: a terminal dashboard over ``/api/status``.

The live UI's HTML page needs a browser; this is the ssh-session view —
poll any process's status endpoint (driver dashboard, PS, worker,
serving replica/frontend, master — anything `metrics/live.py` serves)
and render throughput, per-stage latencies, the convergence curve and
its slope, serving QPS/freshness, and the SLO health board in place,
top(1)-style.

Usage::

    bin/async-top http://HOST:PORT [--interval 1.0] [--once] [--plain]

``--once`` renders a single frame and exits (what the tests drive);
``--plain`` skips the ANSI clear (pipe-friendly).  Rendering is PURE
(:func:`render_status`: status dict -> text), so tests feed it captured
snapshots without a server.
"""

from __future__ import annotations

import json
import math
import sys
import time
import urllib.request
from typing import Dict, List, Optional

#: SLO state -> (glyph, ANSI color) for the health board
_STATE_GLYPH = {
    "ok": ("ok", "32"),        # green
    "pending": ("..", "33"),   # yellow
    "firing": ("!!", "31"),    # red
    "no_data": ("--", "90"),   # dim
}

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 48) -> str:
    """Downsample ``values`` to ``width`` block-character cells (the
    loss-curve-in-a-terminal view).  Degenerate spans render flat."""
    vals = [float(v) for v in values if v is not None
            and math.isfinite(float(v))]
    if not vals:
        return ""
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[int(i * step)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * (len(_SPARK) - 1)))]
        for v in vals
    )


def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != v:  # NaN
            return "nan"
        return f"{v:.{nd}f}" if abs(v) < 1e6 else f"{v:.3g}"
    return str(v)


def _color(text: str, code: str, plain: bool) -> str:
    return text if plain else f"\x1b[{code}m{text}\x1b[0m"


def render_status(status: Dict, plain: bool = True) -> str:
    """One dashboard frame from an ``/api/status`` body (pure)."""
    lines: List[str] = []
    role = status.get("role", "driver")
    head = [f"async-top  role={role}"]
    if status.get("run_id"):
        head.append(f"run={status['run_id']}")
    if status.get("elapsed_s") is not None:
        head.append(f"up={_fmt(status['elapsed_s'])}s")
    if status.get("updates_per_sec") is not None:
        head.append(f"{_fmt(status['updates_per_sec'])} upd/s")
    if status.get("accepted") is not None:
        head.append(f"acc={status['accepted']} drop="
                    f"{status.get('dropped', 0)}")
    if status.get("model_version") is not None:
        head.append(f"v={status['model_version']}")
    lines.append("  ".join(head))

    # ---- health board (the reason to look at this screen at 3am)
    health = status.get("health") or {}
    rules = health.get("rules") or {}
    if rules:
        overall = health.get("state", "ok")
        glyph, code = _STATE_GLYPH.get(overall, ("??", "0"))
        lines.append("")
        lines.append("SLO health: "
                     + _color(f"{overall.upper()} [{glyph}]", code, plain))
        for name in sorted(rules):
            r = rules[name]
            glyph, code = _STATE_GLYPH.get(r.get("state"), ("??", "0"))
            detail = (f"{r.get('agg')}({r.get('series')}) {r.get('op')} "
                      f"{_fmt(r.get('threshold'))}")
            val = _fmt(r.get("value"), 2)
            burn = (f" burn={_fmt(r.get('burn_s'))}s"
                    if r.get("burn_s") else "")
            fired = (f" fired×{r['fired']}" if r.get("fired") else "")
            lines.append(
                f"  {_color(glyph, code, plain)} {name:<18} {detail:<44} "
                f"value={val}{burn}{fired}"
            )

    # ---- convergence curve + slope
    conv = status.get("convergence") or {}
    curves = conv.get("curves") or {}
    lw = curves.get("loss_vs_wallclock") or []
    if lw or conv.get("samples"):
        lines.append("")
        slope = conv.get("slope_per_s")
        if slope is None:
            trend = "?"
        elif slope < 0:
            trend = "converging"
        elif slope > 0:
            trend = "diverging"  # the 3am trend this line exists for
        else:
            trend = "plateaued"
        lines.append(
            f"convergence: loss={_fmt(conv.get('last_loss'), 6)} "
            f"best={_fmt(conv.get('best_loss'), 6)} "
            f"slope={_fmt(slope, 6)}/s ({trend}) "
            f"samples={conv.get('samples', 0)}"
        )
        if lw:
            lines.append("  loss " + sparkline([p[1] for p in lw]))

    # ---- per-stage latency decomposition (trace section)
    trace = status.get("trace") or {}
    stages = trace.get("stages_ms") or {}
    shown = [(s, d) for s, d in sorted(stages.items()) if d.get("count")]
    if shown:
        lines.append("")
        lines.append(f"{'stage':<14}{'p50 ms':>10}{'p95 ms':>10}"
                     f"{'p99 ms':>10}{'count':>9}")
        for stage, d in shown:
            lines.append(
                f"{stage:<14}{_fmt(d.get('p50'), 2):>10}"
                f"{_fmt(d.get('p95'), 2):>10}{_fmt(d.get('p99'), 2):>10}"
                f"{d.get('count', 0):>9}"
            )
        sm = trace.get("staleness_ms") or {}
        if sm.get("count"):
            lines.append(f"staleness: p95={_fmt(sm.get('p95'))}ms "
                         f"max={_fmt(sm.get('max'))}ms")

    # ---- serving plane
    serving = status.get("serving") or {}
    detail = serving.get("detail") or serving  # driver vs bare process
    if detail.get("qps") or detail.get("predicts"):
        pm = detail.get("predict_ms") or {}
        lines.append("")
        lines.append(
            f"serving: qps={_fmt(detail.get('qps'))} "
            f"predict p50={_fmt(pm.get('p50'), 2)}ms "
            f"p99={_fmt(pm.get('p99'), 2)}ms "
            f"freshness={_fmt(detail.get('freshness_lag_ms'))}ms "
            f"failovers={detail.get('failovers', 0)}"
        )

    # ---- continuous profiling plane (compact zone-share row)
    prof = status.get("profile") or {}
    if prof.get("zones"):
        lines.append("")
        lines.append(render_profile_row(prof))

    # ---- native data plane (codec dispatches + shm transport)
    native = status.get("native") or {}
    if native:
        nc = sum(int(v) for k, v in native.items()
                 if k.startswith("native_calls."))
        pc = sum(int(v) for k, v in native.items()
                 if k.startswith("python_calls."))
        row = (f"native: calls={nc} python={pc} "
               f"fallbacks={native.get('python_fallbacks', 0)}")
        if native.get("shm_upgrades") or native.get("shm_upgrade_refused"):
            row += (f"  shm: up={native.get('shm_upgrades', 0)} "
                    f"refused={native.get('shm_upgrade_refused', 0)} "
                    f"degraded={native.get('shm_degrades', 0)} "
                    f"tx={_fmt(native.get('shm_bytes_sent', 0) / 1e6, 2)}MB "
                    f"rx={_fmt(native.get('shm_bytes_recv', 0) / 1e6, 2)}MB")
        lines.append("")
        lines.append(row)

    # ---- adaptive control plane
    control = status.get("control") or {}
    if control.get("knobs"):
        lines.append("")
        lines.append(render_control(control, plain=plain).rstrip("\n"))

    ts = status.get("timeseries") or {}
    if ts.get("series"):
        lines.append("")
        lines.append(f"timeseries: {ts['series']} series, "
                     f"{ts.get('samples', 0)} samples "
                     f"({ts.get('evicted', 0)} evicted)")
    return "\n".join(lines) + "\n"


def render_profile_row(section: Dict) -> str:
    """One compact zone-share line from a ``profile`` /api/status
    section (or the observer's per-role compact block): the top sampled
    zones by share, plus compile/dispatch accounting when present.
    Shared by async-top's per-role view and async-mon's fleet table."""
    zones = section.get("zones") or {}
    shares = []
    for z, d in zones.items():
        # a full snapshot carries {"share": ...} dicts; the observer's
        # compact per-role block carries bare share floats
        try:
            s = float(d.get("share", 0.0)) if isinstance(d, dict) \
                else float(d)
        except (TypeError, ValueError):
            continue
        if s > 0:
            shares.append((z, s))
    shares.sort(key=lambda kv: -kv[1])
    parts = [f"{z} {s * 100:.0f}%" for z, s in shares[:5]]
    head = (f"profile: samples={section.get('samples', 0)} "
            + ("  ".join(parts) if parts else "(no sampled zones)"))
    comp = section.get("compile") or {}
    if comp.get("count"):
        head += (f"  compile={comp['count']}"
                 f"/{float(comp.get('ns', 0)) / 1e6:.0f}ms")
    return head


def render_control(section: Dict, plain: bool = True) -> str:
    """The adaptive-control board from a ``control`` /api/status section
    (pure, like :func:`render_status`): current knob values vs their
    configured baselines, the last decision and its reason, and the
    oscillation-guard state.  Shared by async-top's per-role view and
    async-mon's fleet view."""
    lines: List[str] = []
    totals = section.get("totals") or {}
    head = (f"control: seq={section.get('seq', 0)} "
            f"changes={totals.get('changes', 0)} "
            f"clamps={totals.get('clamps', 0)} "
            f"osc_trips={totals.get('osc_trips', 0)}")
    if section.get("role"):
        head += f" via={section['role']}"
    lines.append(head)
    knobs = section.get("knobs") or {}
    if knobs:
        lines.append(f"  {'knob':<8}{'value':>8}{'conf':>8}"
                     f"{'changes':>9}  guard")
        for name in sorted(knobs):
            k = knobs[name]
            frozen = bool(k.get("frozen"))
            guard = (_color("FROZEN", "31", plain) if frozen else "ok")
            lines.append(
                f"  {name:<8}{_fmt(k.get('value'), 0):>8}"
                f"{_fmt(k.get('configured'), 0):>8}"
                f"{k.get('changes', 0):>9}  {guard}"
            )
    damp = section.get("damp") or {}
    if damp:
        wdamp = damp.get("wdamp") or {}
        extra = ("  wdamp " + " ".join(
            f"w{w}={_fmt(f, 2)}" for w, f in sorted(wdamp.items()))
            if wdamp else "")
        lines.append(f"  damp: floor={_fmt(damp.get('floor'), 2)} "
                     f"free={_fmt(damp.get('free'), 1)}{extra}")
    last = section.get("last_decision")
    if last:
        lines.append(
            f"  last: {last.get('knob')} "
            f"{_fmt(last.get('from'), 0)} -> {_fmt(last.get('to'), 0)} "
            f"({last.get('reason')}) at t={_fmt(last.get('t'))}s")
    return "\n".join(lines) + "\n"


def render_fleet(observer_section: Dict, plain: bool = True) -> str:
    """One fleet-dashboard frame from a collector's ``observer``
    /api/status section (pure, like :func:`render_status`): every
    discovered role's liveness + key numbers, the derived cross-role
    signals, and the straggler board."""
    lines: List[str] = []
    roles = observer_section.get("roles") or {}
    derived = observer_section.get("derived") or {}
    up = int(derived.get("roles_up", sum(
        1 for r in roles.values() if r.get("up"))))
    lines.append(f"async-mon  fleet view  roles={len(roles)} up={up}")

    if roles:
        lines.append("")
        lines.append(f"{'role':<22}{'kind':<10}{'up':<4}{'health':<9}"
                     f"{'accepted':>10}{'stale':>7}{'qps':>8}{'lag ms':>8}")
        for name in sorted(roles):
            r = roles[name]
            glyph, code = (("up", "32") if r.get("up")
                           else ("DOWN", "31"))
            lines.append(
                f"{name:<22}{str(r.get('role') or '-'):<10}"
                f"{_color(glyph, code, plain):<4} "
                f"{str(r.get('health') or '-'):<8}"
                f"{_fmt(r.get('accepted'), 0):>10}"
                f"{_fmt(r.get('staleness'), 0):>7}"
                f"{_fmt(r.get('qps')):>8}"
                f"{_fmt(r.get('freshness_lag_ms'), 0):>8}"
            )
            # compact zone-share row under profiling-enabled roles
            prof = r.get("profile") or {}
            if prof.get("zones"):
                lines.append("  " + render_profile_row(prof))

    if derived:
        lines.append("")
        lines.append(
            "derived: "
            f"push_rate={_fmt(derived.get('push_rate'))}/s "
            f"merge_q={_fmt(derived.get('merge_queue_depth'), 0)} "
            f"fleet_lag={_fmt(derived.get('freshness_lag_ms'), 0)}ms "
            f"straggler_max={_fmt(derived.get('straggler_score'), 2)} "
            f"done={int(derived.get('fleet_done', 0))}"
        )

    stragglers = observer_section.get("stragglers") or {}
    shown = [(w, s) for w, s in sorted(stragglers.items())
             if s.get("score") is not None]
    if shown:
        factor = observer_section.get("straggler_factor")
        lines.append("")
        lines.append(f"stragglers (score = worker/median, flag at "
                     f">={_fmt(factor)}):")
        for wid, s in shown:
            mark = (_color("<<", "31", plain) if s.get("flagged")
                    else "  ")
            dims = " ".join(f"{d}={_fmt(r, 2)}"
                            for d, r in sorted(
                                (s.get("dims") or {}).items()))
            lines.append(f"  w{wid:<4} score={_fmt(s['score'], 2):<7} "
                         f"{mark} {dims}")

    control = observer_section.get("control") or {}
    if control.get("knobs"):
        lines.append("")
        lines.append(render_control(control, plain=plain).rstrip("\n"))

    hist = observer_section.get("history") or {}
    if hist:
        nd = len(hist.get("flight_dumps") or [])
        np_ = len(hist.get("profile_snapshots") or [])
        lines.append("")
        lines.append(
            f"history: run={hist.get('run_id')} "
            f"roles={len(hist.get('roles') or {})} "
            f"flight_dumps={nd} "
            f"profiles={np_} "
            f"dir={hist.get('run_dir') or '(memory)'}"
        )
    return "\n".join(lines) + "\n"


def fetch_status(url: str, timeout_s: float = 5.0) -> Dict:
    if not url.startswith("http"):
        url = "http://" + url
    if not url.rstrip("/").endswith("/api/status"):
        url = url.rstrip("/") + "/api/status"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        "async-top", description="terminal dashboard over /api/status"
    )
    p.add_argument("url", nargs="?", default=None,
                   help="http://HOST:PORT (or HOST:PORT) of any "
                        "process serving /api/status")
    p.add_argument("--observer", default=None, metavar="ENDPOINT",
                   help="render the FLEET view from a cluster "
                        "observer's /api/status (bin/async-mon): every "
                        "worker/shard/replica in one dashboard instead "
                        "of polling a single role")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    p.add_argument("--plain", action="store_true",
                   help="no ANSI colors / screen clears (pipe-friendly)")
    args = p.parse_args(argv)
    url = args.observer if args.observer is not None else args.url
    if url is None:
        p.error("need a URL (or --observer ENDPOINT)")
    while True:
        try:
            status = fetch_status(url)
            if args.observer is not None:
                section = status.get("observer")
                if not isinstance(section, dict):
                    frame = (f"async-top: {url} serves no 'observer' "
                             f"section (not an async-mon collector?)\n")
                else:
                    frame = render_fleet(section, plain=args.plain)
            else:
                frame = render_status(status, plain=args.plain)
        except (OSError, ValueError) as e:
            frame = f"async-top: {url} unreachable ({e})\n"
        if not args.plain:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(frame)
        sys.stdout.flush()
        if args.once:
            return 0
        try:
            time.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
