"""Continuous telemetry: bounded time-series store + convergence history.

The ASYNC paper's second pillar is *history* -- the runtime must record
how the computation evolved, not just where it is now.  Everything the
repo measured before this module (net bytes, recovery counters, trace
percentiles, serving lag) was point-in-time: ``/api/status`` answered
"what is the state this instant" and every number died with the run.
This module makes those signals *time series* that a controller (ROADMAP
item 3, delay-adaptive rates per arXiv:1601.04033), an SLO engine
(``metrics/slo.py``), a Prometheus scraper (``metrics/prom.py``), and a
terminal dashboard (``bin/async-top``) can all read:

- :class:`TimeSeriesStore`: per-series bounded rings of ``(t_s, value)``
  samples with windowed aggregates (min/max/mean/last/percentiles) and
  counter **rate derivation** (``rate()``: per-second slope over a
  window, the updates/s and bytes/s view).
- a process-global **sampler thread** (:func:`ensure_started`) that
  every ``async.metrics.interval.s`` seconds walks the counter-family
  registry (``metrics/registry.py``) plus dynamically registered
  sources (the PS registers one; serving/trace/convergence sources are
  built in) and records each flat numeric as ``<family>.<key>``.
  Retention is bounded: ``async.metrics.retention`` samples per series
  (defaults: 512 samples x 1 s interval = ~8.5 min of history; RAM is
  O(series x retention) small floats).
- :class:`ConvergenceHistory`: the loss-vs-wallclock and loss-vs-version
  curves (ASAP, arXiv:1612.08608: error/latency trade-off curves are
  the right product of an approximate async engine).  Workers piggyback
  ``(version, loss, grad_norm)`` samples on PUSH headers (the ``cv``
  entry -- the same discipline as trace spans and pipeline counters,
  see ``parallel/ps_dcn.py``), the PS folds them here stamped with its
  run clock and the staleness it observed; in-process solvers fold
  their trajectory at close.  Bounded by stride compaction: at capacity
  every other point is dropped and the acceptance stride doubles, so
  the curve always spans the whole run at bounded memory.
- :class:`ConvergenceBuffer`: the worker-side bounded sample buffer
  whose unshipped tail rides the next PUSH/BYE header (merge-back on a
  terminally failed push, like every other piggyback).

Everything is lock-guarded, allocation-light, and OFF the hot path: the
sampler is one daemon thread; convergence sampling on workers is
conf-gated (``async.convergence.sample``, default 0 = off, flipped on
for ``async-cluster``) so default wires stay byte-identical.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from asyncframework_tpu.utils.clock import Clock, SystemClock


def _pct(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile, same rule as metrics/system.Histogram."""
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, math.ceil(q * n) - 1))]


class TimeSeriesStore:
    """Bounded per-series rings of ``(t_s, value)`` with windowed
    aggregates and counter-rate derivation.

    ``capacity`` bounds every series independently (oldest samples
    evict first, counted).  ``clock`` is injectable (ManualClock tests);
    times are the clock's ``now_ms() / 1e3``.
    """

    def __init__(self, capacity: int = 512, clock: Optional[Clock] = None):
        self.capacity = max(2, int(capacity))
        self._clock = clock or SystemClock()
        self._lock = threading.Lock()
        self._series: "OrderedDict[str, deque]" = OrderedDict()
        self.samples_recorded = 0
        self.evicted = 0

    def now_s(self) -> float:
        return self._clock.now_ms() / 1e3

    # ------------------------------------------------------------ recording
    def record(self, name: str, value: float,
               t_s: Optional[float] = None) -> None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if t_s is None:
            t_s = self.now_s()
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                ring = self._series[name] = deque(maxlen=self.capacity)
            if len(ring) == ring.maxlen:
                self.evicted += 1
            ring.append((t_s, v))
            self.samples_recorded += 1

    def record_flat(self, prefix: str, values: Dict[str, object],
                    t_s: Optional[float] = None) -> None:
        """Record every numeric in a flat dict as ``<prefix>.<key>``."""
        if t_s is None:
            t_s = self.now_s()
        for k, v in values.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.record(f"{prefix}.{k}", v, t_s=t_s)

    # -------------------------------------------------------------- queries
    def names(self) -> List[str]:
        with self._lock:
            return list(self._series)

    def series(self, name: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Samples of ``name``, oldest first, optionally restricted to
        the trailing ``window_s`` seconds."""
        with self._lock:
            ring = self._series.get(name)
            pts = list(ring) if ring is not None else []
        if window_s is not None and pts:
            cutoff = self.now_s() - window_s
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def last(self, name: str) -> Optional[float]:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1][1] if ring else None

    def window_agg(self, name: str, window_s: float) -> Dict[str, float]:
        """min/max/mean/last + nearest-rank percentiles over the
        trailing window.  ``{"count": 0}`` when no samples fall in it."""
        pts = self.series(name, window_s=window_s)
        if not pts:
            return {"count": 0}
        vals = sorted(v for (_t, v) in pts)
        return {
            "count": len(vals),
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "last": pts[-1][1],
            "p50": _pct(vals, 0.50),
            "p95": _pct(vals, 0.95),
            "p99": _pct(vals, 0.99),
        }

    def rate(self, name: str, window_s: float) -> Optional[float]:
        """Per-second increase of a monotone counter over the trailing
        window: ``(last - first) / (t_last - t_first)``, clamped at 0 so
        a mid-window ``reset_totals()`` reads as a stall, not a negative
        rate.  None without >= 2 samples spanning > 0 time."""
        pts = self.series(name, window_s=window_s)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return max(0.0, (v1 - v0) / (t1 - t0))

    def summary(self) -> Dict[str, object]:
        """Compact meta-view for ``/api/status``: series count, sample
        count, and each series' last value (names only -- full rings are
        served by ``/api/timeseries``)."""
        with self._lock:
            names = list(self._series)
            last = {n: self._series[n][-1][1]
                    for n in names if self._series[n]}
            return {
                "series": len(names),
                "samples": self.samples_recorded,
                "evicted": self.evicted,
                "last": last,
            }

    def dump(self) -> Dict[str, List[List[float]]]:
        """Every series' full ring as JSON-able ``[[t_s, v], ...]``
        (bounded by construction; the ``/api/timeseries`` body)."""
        with self._lock:
            return {n: [[t, v] for (t, v) in ring]
                    for n, ring in self._series.items()}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self.samples_recorded = 0
            self.evicted = 0


# --------------------------------------------------------------------------
# Convergence history (loss-vs-wallclock / loss-vs-version curves)
# --------------------------------------------------------------------------
class ConvergenceHistory:
    """Bounded record of ``(wall_ms, version, loss, grad_norm,
    staleness)`` samples.

    Stride compaction keeps the FULL run span at bounded memory: when
    the list hits capacity, every other point is dropped and the
    acceptance stride doubles (sample k is kept iff k % stride == 0 by
    arrival order), so early and late history coexist -- a ring would
    forget the start of the run, which is exactly the part a
    loss-vs-wallclock curve needs.
    """

    def __init__(self, capacity: int = 2048):
        self.capacity = max(16, int(capacity))
        self._lock = threading.Lock()
        self._pts: List[Tuple[float, int, Optional[float],
                              Optional[float], Optional[int]]] = []
        self._stride = 1
        self._arrivals = 0
        self.samples = 0      # accepted into the history
        self.offered = 0      # offered (add calls)
        self.compactions = 0

    def add(self, wall_ms: float, version: int,
            loss: Optional[float] = None,
            grad_norm: Optional[float] = None,
            staleness: Optional[int] = None) -> None:
        try:
            wall_ms = float(wall_ms)
            version = int(version)
            loss = None if loss is None else float(loss)
            grad_norm = None if grad_norm is None else float(grad_norm)
            staleness = None if staleness is None else int(staleness)
        except (TypeError, ValueError):
            return
        if loss is not None and not math.isfinite(loss):
            loss = None  # diverged/NaN losses must not poison the curve
        with self._lock:
            self.offered += 1
            k = self._arrivals
            self._arrivals += 1
            if k % self._stride != 0:
                return
            self._pts.append((wall_ms, version, loss, grad_norm, staleness))
            self.samples += 1
            if len(self._pts) >= self.capacity:
                del self._pts[1::2]  # keep endpoints-ish, halve density
                self._stride *= 2
                self.compactions += 1

    def _sorted(self) -> List[Tuple]:
        return sorted(self._pts, key=lambda p: p[0])

    def curves(self, max_points: int = 160) -> Dict[str, List[List[float]]]:
        """JSON-able curves, downsampled to ``<= max_points`` each:
        ``loss_vs_wallclock`` [[t_ms, loss]], ``loss_vs_version``
        [[version, loss]], ``grad_norm`` [[t_ms, gnorm]],
        ``staleness`` [[t_ms, staleness]]."""
        with self._lock:
            pts = self._sorted()
        def thin(seq):
            if len(seq) <= max_points:
                return seq
            step = len(seq) / max_points
            return [seq[int(i * step)] for i in range(max_points)]
        loss_t = [[t, l] for (t, _v, l, _g, _s) in pts if l is not None]
        loss_v = [[v, l] for (_t, v, l, _g, _s) in pts if l is not None]
        gnorm = [[t, g] for (t, _v, _l, g, _s) in pts if g is not None]
        stale = [[t, float(s)] for (t, _v, _l, _g, s) in pts
                 if s is not None]
        return {
            "loss_vs_wallclock": thin(loss_t),
            "loss_vs_version": thin(loss_v),
            "grad_norm": thin(gnorm),
            "staleness": thin(stale),
        }

    def summary(self) -> Dict[str, object]:
        """The scalar view the SLO engine / bench / async-top read:
        sample counts, first/last/best loss, the trailing-half slope
        (loss units per second; negative = converging), and loss at
        25/50/100% of the observed wallclock."""
        with self._lock:
            pts = self._sorted()
        losses = [(t, l) for (t, _v, l, _g, _s) in pts if l is not None]
        out: Dict[str, object] = {
            "samples": self.samples,
            "offered": self.offered,
            "stride": self._stride,
            "compactions": self.compactions,
        }
        if not losses:
            return out
        out["first_loss"] = losses[0][1]
        out["last_loss"] = losses[-1][1]
        out["best_loss"] = min(l for (_t, l) in losses)
        out["span_ms"] = losses[-1][0] - losses[0][0]
        out["loss_at"] = loss_at_fractions(losses)
        out["slope_per_s"] = loss_slope(losses)
        return out

    def reset(self) -> None:
        with self._lock:
            self._pts.clear()
            self._stride = 1
            self._arrivals = 0
            self.samples = self.offered = self.compactions = 0


def loss_at_fractions(
    trajectory: Sequence[Tuple[float, float]],
    fractions: Sequence[float] = (0.25, 0.50, 1.0),
) -> Dict[str, Optional[float]]:
    """Loss at given fractions of the observed wallclock span, from a
    ``[(t_ms, loss), ...]`` curve (last sample at-or-before the cut; the
    bench telemetry block and ConvergenceHistory.summary share this)."""
    pts = sorted((float(t), float(l)) for (t, l) in trajectory
                 if l is not None and math.isfinite(float(l)))
    out: Dict[str, Optional[float]] = {}
    for f in fractions:
        key = f"{int(round(f * 100))}pct"
        if not pts:
            out[key] = None
            continue
        t0, t1 = pts[0][0], pts[-1][0]
        cut = t0 + (t1 - t0) * f
        best = None
        for (t, l) in pts:
            if t <= cut:
                best = l
            else:
                break
        out[key] = best if best is not None else pts[0][1]
    return out


def loss_slope(trajectory: Sequence[Tuple[float, float]]
               ) -> Optional[float]:
    """Least-squares slope of loss vs wallclock SECONDS over the
    trailing half of the curve (the convergence-rate signal async-top
    and the bench telemetry block report; negative = still improving,
    ~0 = plateaued)."""
    pts = sorted((float(t) / 1e3, float(l)) for (t, l) in trajectory
                 if l is not None and math.isfinite(float(l)))
    if len(pts) < 2:
        return None
    tail = pts[len(pts) // 2:]
    if len(tail) < 2:
        tail = pts[-2:]
    n = len(tail)
    mt = sum(t for (t, _l) in tail) / n
    ml = sum(l for (_t, l) in tail) / n
    den = sum((t - mt) ** 2 for (t, _l) in tail)
    if den <= 0:
        return None
    return sum((t - mt) * (l - ml) for (t, l) in tail) / den


def fold_trajectory(trajectory) -> None:
    """Fold a finished run's post-hoc trajectory (``[(wall_ms,
    objective), ...]``, the TrainResult shape) into the process-global
    convergence history -- the in-process solvers' analog of the DCN
    workers' PUSH-header piggyback.  Snapshot index stands in for the
    model version (in-process snapshots are taken on the printer-freq
    cadence, not per merge)."""
    conv = convergence()
    for i, (t_ms, obj) in enumerate(trajectory or ()):
        conv.add(t_ms, i, loss=obj)


class ConvergenceBuffer:
    """Worker-side bounded buffer of ``(version, loss, grad_norm)``
    samples awaiting shipment on a PUSH/BYE header (``cv`` entry) --
    the span/pipeline-counter piggyback discipline: ``take_wire`` drains
    the unshipped tail, ``merge_back`` restores a terminally failed
    push's samples so they ride the next attempt instead of vanishing."""

    MAX_WIRE = 32  # samples per header: bounds the piggyback bytes

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self._ring: "deque[list]" = deque(maxlen=max(4, int(capacity)))
        self.dropped = 0

    def add(self, version: int, loss: Optional[float],
            grad_norm: Optional[float]) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append([
                int(version),
                None if loss is None else round(float(loss), 8),
                None if grad_norm is None else round(float(grad_norm), 6),
            ])

    def take_wire(self) -> List[list]:
        with self._lock:
            out: List[list] = []
            while self._ring and len(out) < self.MAX_WIRE:
                out.append(self._ring.popleft())
            return out

    def merge_back(self, wire: List[list]) -> None:
        with self._lock:
            for item in reversed(wire):
                if len(self._ring) == self._ring.maxlen:
                    self.dropped += 1
                self._ring.appendleft(item)


# --------------------------------------------------------------------------
# Process-global store + sampler + convergence history
# --------------------------------------------------------------------------
_glock = threading.Lock()
_store: Optional[TimeSeriesStore] = None
_conv: Optional[ConvergenceHistory] = None
_sources: "OrderedDict[str, Callable[[], Dict[str, object]]]" = OrderedDict()
_sampler_thread: Optional[threading.Thread] = None
_sampler_stop = threading.Event()
_ticks = 0


def store() -> TimeSeriesStore:
    """The process-global time-series store (capacity from conf
    ``async.metrics.retention`` at first touch)."""
    global _store
    with _glock:
        if _store is None:
            from asyncframework_tpu.conf import METRICS_RETENTION, global_conf

            _store = TimeSeriesStore(
                capacity=int(global_conf().get(METRICS_RETENTION))
            )
        return _store


def convergence() -> ConvergenceHistory:
    """The process-global convergence history (PS folds piggybacked
    worker samples here; in-process solvers fold their trajectory)."""
    global _conv
    with _glock:
        if _conv is None:
            _conv = ConvergenceHistory()
        return _conv


def register_source(name: str, fn: Callable[[], Dict[str, object]]) -> None:
    """Register a dynamic flat-dict source sampled every tick as
    ``<name>.<key>`` (the PS registers ``ps``; last registration under a
    name wins -- matching "the live PS owns the dashboard")."""
    with _glock:
        _sources[name] = fn


def unregister_source(name: str, fn=None) -> None:
    """Remove a source; with ``fn`` given, only if it is still the
    registered one (a stopped PS must not unhook its replacement)."""
    with _glock:
        if fn is None or _sources.get(name) is fn:
            _sources.pop(name, None)


def _builtin_sources() -> Dict[str, Callable[[], Dict[str, object]]]:
    """Always-on derived sources beside the registry counters: serving
    freshness/latency, trace stage percentiles, convergence scalars."""
    return {
        "serving": _serving_source,
        "trace": _trace_source,
        "convergence": _convergence_source,
    }


def _serving_source() -> Dict[str, object]:
    from asyncframework_tpu.serving import metrics as smetrics

    snap = smetrics.serving_snapshot()
    out: Dict[str, object] = {}
    if "qps" in snap:
        out["qps"] = snap["qps"]
    fl = smetrics.freshness_lag_ms()
    if fl is not None:
        out["freshness_lag_ms"] = fl
    for key, stat in (("predict_ms", "p99"), ("lag_ms", "p95"),
                      ("lag_versions", "p95")):
        s = snap.get(key) or {}
        if s.get("count"):
            out[f"{key}_{stat}"] = s[stat]
    return out


def _trace_source() -> Dict[str, object]:
    from asyncframework_tpu.metrics import trace as trace_mod

    snap = trace_mod.aggregator().snapshot()
    out: Dict[str, object] = {"spans": snap.get("spans", 0)}
    for stage, s in (snap.get("stages_ms") or {}).items():
        if s.get("count"):
            out[f"{stage}.p95_ms"] = s["p95"]
    sm = snap.get("staleness_ms") or {}
    if sm.get("count"):
        out["staleness_ms_p95"] = sm["p95"]
    sv = snap.get("staleness_versions") or {}
    if sv.get("count"):
        out["staleness_versions_p95"] = sv["p95"]
    return out


def _convergence_source() -> Dict[str, object]:
    s = convergence().summary()
    out: Dict[str, object] = {}
    if "last_loss" in s:
        out["loss"] = s["last_loss"]
    if s.get("slope_per_s") is not None:
        out["slope_per_s"] = s["slope_per_s"]
    return out


def sample_once(st: Optional[TimeSeriesStore] = None) -> None:
    """One sampling tick: registry counter families + dynamic sources
    into the store, then an SLO evaluation pass.  A failing source must
    not kill the sampler (same shield as MetricsSystem sinks)."""
    global _ticks
    from asyncframework_tpu.metrics import registry

    st = st or store()
    t = st.now_s()
    for fam_name, fam in registry.families().items():
        try:
            st.record_flat(fam_name, fam.totals(), t_s=t)
        except Exception:  # noqa: BLE001 - one family (e.g. a lazy
            pass           # import failing in a lean process) must not
                           # kill the sampler thread for good
    with _glock:
        sources = dict(_builtin_sources(), **_sources)
    for name, fn in sources.items():
        try:
            st.record_flat(name, fn(), t_s=t)
        except Exception:  # noqa: BLE001 - telemetry must not crash
            pass
    _ticks += 1
    try:
        from asyncframework_tpu.metrics import slo

        slo.engine().evaluate()
    except Exception:  # noqa: BLE001 - a bad rule set must not kill ticks
        pass


def ensure_started() -> None:
    """Start the process-global sampler thread (idempotent; daemon).
    Interval from conf ``async.metrics.interval.s`` at start time; an
    interval <= 0 disables sampling entirely."""
    global _sampler_thread
    from asyncframework_tpu.conf import METRICS_INTERVAL_S, global_conf

    interval = float(global_conf().get(METRICS_INTERVAL_S))
    if interval <= 0:
        return
    with _glock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return
        _sampler_stop.clear()

        def loop() -> None:
            while not _sampler_stop.wait(timeout=interval):
                sample_once()

        _sampler_thread = threading.Thread(
            target=loop, name="telemetry-sampler", daemon=True
        )
        _sampler_thread.start()


def stop_sampler() -> None:
    global _sampler_thread
    with _glock:
        t = _sampler_thread
        _sampler_thread = None
    _sampler_stop.set()
    if t is not None:
        t.join(timeout=5.0)


def sampler_running() -> bool:
    with _glock:
        return _sampler_thread is not None and _sampler_thread.is_alive()


# ------------------------------------------------- registry provider hooks
def timeseries_totals() -> Dict[str, int]:
    """Flat meta-counters (registry family ``timeseries``)."""
    st = store()
    with st._lock:
        return {
            "series": len(st._series),
            "samples": st.samples_recorded,
            "evicted": st.evicted,
            "ticks": _ticks,
        }


def reset_timeseries() -> None:
    global _ticks
    store().clear()
    _ticks = 0


def convergence_totals() -> Dict[str, int]:
    """Flat meta-counters (registry family ``convergence``)."""
    c = convergence()
    return {
        "samples": c.samples,
        "offered": c.offered,
        "compactions": c.compactions,
    }


def reset_convergence() -> None:
    convergence().reset()
