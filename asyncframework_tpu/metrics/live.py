"""Live run dashboard: HTTP endpoint serving in-progress run state.

Parity: ``ui/SparkUI.scala:39`` -- the reference serves jobs / stages /
executors pages *during* a run from the listener-bus-fed AppStatusStore;
the post-hoc analog here is ``metrics/report.py`` + ``bin/async-history``.
This module closes the gap VERDICT r2 item 7 named: a long ASGD run is no
longer a black box until it ends.

Design: a :class:`LiveStateListener` subscribes to the run's ListenerBus
(same events the event log gets) and folds them into one JSON-able snapshot
-- rounds, accepted/dropped, updates/s, staleness histogram, queue depth,
per-worker state, losses/moves/speculation.  A stdlib ThreadingHTTPServer
(daemon threads, ephemeral port support) serves:

- ``GET /api/status`` -- the snapshot (machine-readable; tests poll this)
- ``GET /``           -- a self-refreshing HTML view of the same snapshot

Zero dependencies, nothing on the hot path: the listener runs on the bus's
single drain thread; HTTP reads take the same lock only per request.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from asyncframework_tpu.metrics.bus import (
    Event,
    GradientMerged,
    Listener,
    ModelSnapshot,
    RoundSubmitted,
    ShardMoved,
    SpeculativeLaunch,
    TraceSpan,
    WorkerLost,
)
from asyncframework_tpu.metrics.trace import Span, TraceAggregator

#: running servers by most-recent-first (tests and tools discover ephemeral
#: ports here; entries are removed on stop)
_ACTIVE: List["LiveUIServer"] = []
_ACTIVE_LOCK = threading.Lock()

#: process-wide run identity: stamped as the ``run_id`` label on every
#: /metrics sample and in /api/status, so a Prometheus scrape (or a human
#: comparing two dashboards) can tell process restarts apart
RUN_ID = f"{uuid.uuid4().hex[:8]}-{os.getpid()}"

#: dynamically registered /api/status sections (name -> zero-arg dict
#: provider): the generic hook subsystems use to surface themselves on
#: every dashboard page -- the PS registers ``ps_workers`` (per-worker
#: stats the observer's straggler scoring reads), the cluster observer
#: registers ``observer`` (the fleet view async-top renders).  Last
#: registration under a name wins; unregister is identity-gated like
#: the time-series sources.
_SECTIONS_LOCK = threading.Lock()
_STATUS_SECTIONS: Dict[str, Callable[[], Dict]] = {}


def register_status_section(name: str, fn: Callable[[], Dict]) -> None:
    with _SECTIONS_LOCK:
        _STATUS_SECTIONS[name] = fn


def unregister_status_section(name: str, fn=None) -> None:
    """Remove a section; with ``fn`` given, only if it is still the
    registered one (a stopped subsystem must not unhook its
    replacement)."""
    with _SECTIONS_LOCK:
        if fn is None or _STATUS_SECTIONS.get(name) is fn:
            _STATUS_SECTIONS.pop(name, None)


def telemetry_port() -> Optional[int]:
    """The port of this process's most recent live/telemetry server
    (None when nothing serves).  Workers advertise it on HELLO
    (``mport``) so supervisors -- and through them the cluster
    observer -- can discover per-role scrape endpoints."""
    servers = active_servers()
    return servers[0].port if servers else None


def _family_totals() -> "Dict[str, Dict[str, int]]":
    from asyncframework_tpu.metrics import registry

    out: Dict[str, Dict[str, int]] = {}
    for name, fam in registry.families().items():
        try:
            out[name] = fam.totals()
        except Exception:  # noqa: BLE001 - one family must not 500 the
            out[name] = {}  # whole status endpoint
    return out


def _baseline_families() -> Dict[str, object]:
    """The registry families the live UI delta-baselines (per-run view);
    keys -> CounterFamily."""
    from asyncframework_tpu.metrics import registry

    return {n: f for n, f in registry.families().items() if f.baseline}


def _serving_snapshot() -> Dict:
    from asyncframework_tpu.serving.metrics import serving_snapshot

    return serving_snapshot()


def _lockwatch_totals() -> Dict:
    from asyncframework_tpu.net import lockwatch

    return lockwatch.totals()


def _telemetry_sections() -> Dict[str, object]:
    """The process-global telemetry-plane sections shared by every
    /api/status (with or without a run listener): convergence curves +
    summary, SLO health, and the time-series store meta-view."""
    from asyncframework_tpu.metrics import slo, timeseries

    conv = timeseries.convergence()
    try:
        health = slo.engine().health()
    except Exception as e:  # noqa: BLE001 - a typo'd async.slo.rules must
        # surface AS the health section, not 500 every dashboard page
        # fleet-wide while training runs fine
        health = {"state": "error", "rules": {},
                  "error": f"{type(e).__name__}: {e}"}
    out = {
        "convergence": {**conv.summary(), "curves": conv.curves()},
        "health": health,
        "timeseries": timeseries.store().summary(),
    }
    try:
        from asyncframework_tpu.parallel import shardgroup

        group = shardgroup.active_group()
        if group is not None:
            # per-shard section (parallel/shardgroup.py): the process
            # hosting the shard-group controller shows its map + member
            # liveness on every dashboard page
            out["shards"] = group.status_section()
    except Exception:  # noqa: BLE001 - a half-torn-down group must not
        pass           # 500 every dashboard page
    with _SECTIONS_LOCK:
        sections = dict(_STATUS_SECTIONS)
    for name, fn in sections.items():
        try:
            out[name] = fn()
        except Exception:  # noqa: BLE001 - one subsystem's section must
            pass           # not 500 every dashboard page
    return out


def process_status(role: str = "process") -> Dict[str, object]:
    """/api/status body for a process WITHOUT a run listener (workers,
    serving replicas/frontends, the master): raw counter-family totals
    plus the telemetry-plane sections."""
    return {
        "role": role,
        "run_id": RUN_ID,
        "pid": os.getpid(),
        "counters": _family_totals(),
        **_telemetry_sections(),
    }


def active_servers() -> List["LiveUIServer"]:
    with _ACTIVE_LOCK:
        return list(_ACTIVE)


def _delta(cur: Dict[str, int], base: Dict[str, int]) -> Dict[str, int]:
    """Per-run view of a process-global counter dict: subtract the values
    captured when THIS run's listener was built, so a second run in the
    same process does not inherit the first run's counts.  A key the
    baseline never saw passes through raw."""
    return {k: v - base.get(k, 0) for k, v in cur.items()}


class LiveStateListener(Listener):
    """Folds bus events into the dashboard snapshot (AppStatusStore role)."""

    STALENESS_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64)

    def __init__(self, num_workers: int):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.num_workers = num_workers
        self.rounds = 0
        self.accepted = 0
        self.dropped = 0
        self.model_version = 0
        self.workers_lost = 0
        self.shards_moved = 0
        self.speculative_launches = 0
        self.last_objective: Optional[float] = None
        self.staleness_hist = [0] * (len(self.STALENESS_BUCKETS) + 1)
        self.max_staleness = 0
        # per-worker: {state, merges, accepted, last_staleness, last_seen_ms}
        self.workers: Dict[int, Dict] = {
            w: {"state": "idle", "merges": 0, "accepted": 0,
                "last_staleness": None, "last_seen_ms": None}
            for w in range(num_workers)
        }
        self._queue_depth_fn: Optional[Callable[[], int]] = None
        # per-run trace view: TraceSpan events folded into this listener's
        # OWN aggregator (the process-global one keeps accumulating for
        # tools; the dashboard shows this run only)
        self._trace = TraceAggregator()
        # per-run delta baselines for the process-global counter panels: a
        # second run's dashboard must not inherit the first run's counts.
        # Registry-driven (metrics/registry.py): every baseline family
        # gets captured here by construction -- a family added to the
        # registry cannot be forgotten by this listener (the audit test
        # in tests/test_telemetry.py checks the coverage).
        self._bases: Dict[str, Dict[str, int]] = {
            name: fam.totals()
            for name, fam in _baseline_families().items()
        }

    def register_queue_depth(self, fn: Callable[[], int]) -> None:
        self._queue_depth_fn = fn

    # ----------------------------------------------------------- bus events
    def on_event(self, event: Event) -> None:
        with self._lock:
            if isinstance(event, RoundSubmitted):
                # count events rather than trusting round_idx: async paths
                # post 1-based counters, sync paths 0-based loop indices
                self.rounds += 1
                self.model_version = event.model_version
                for wid in event.cohort:
                    if wid in self.workers:
                        self.workers[wid]["state"] = "running"
            elif isinstance(event, GradientMerged):
                if event.accepted:
                    self.accepted += 1
                else:
                    self.dropped += 1
                s = event.staleness
                self.max_staleness = max(self.max_staleness, s)
                import bisect

                # bisect_left: staleness == bucket bound belongs in "<=b"
                self.staleness_hist[
                    bisect.bisect_left(self.STALENESS_BUCKETS, s)
                ] += 1
                w = self.workers.get(event.worker_id)
                if w is not None:
                    w["state"] = "idle"
                    w["merges"] += 1
                    w["accepted"] += int(event.accepted)
                    w["last_staleness"] = s
                    w["last_seen_ms"] = event.time_ms
            elif isinstance(event, WorkerLost):
                self.workers_lost += 1
                w = self.workers.get(event.worker_id)
                if w is not None:
                    w["state"] = "lost"
            elif isinstance(event, ShardMoved):
                self.shards_moved += 1
            elif isinstance(event, SpeculativeLaunch):
                self.speculative_launches += 1
            elif isinstance(event, ModelSnapshot):
                self.last_objective = event.objective
            elif isinstance(event, TraceSpan):
                self._trace.add(Span(
                    stage=event.stage, trace_id=event.trace_id,
                    span_id=event.span_id, parent_id=event.parent_id,
                    worker_id=event.worker_id,
                    model_version=event.model_version,
                    start_ms=event.start_ms, dur_ms=event.dur_ms,
                    staleness=event.staleness,
                    staleness_ms=event.staleness_ms,
                    accepted=event.accepted,
                    bytes=getattr(event, "bytes", None),
                ))

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict:
        fams = _family_totals()  # one read per family: delta + raw agree
        # process-global, touches no listener state -- and it runs a full
        # SLO evaluation plus convergence-curve assembly, so gathering it
        # under self._lock would stall every bus event callback behind
        # each dashboard poll
        telemetry = _telemetry_sections()
        with self._lock:
            elapsed = time.monotonic() - self._t0
            buckets = [
                f"<={b}" for b in self.STALENESS_BUCKETS
            ] + [f">{self.STALENESS_BUCKETS[-1]}"]
            pl = fams["pipeline"]
            return {
                "run_id": RUN_ID,
                "elapsed_s": round(elapsed, 3),
                "rounds": self.rounds,
                "accepted": self.accepted,
                "dropped": self.dropped,
                "updates_per_sec": round(self.accepted / elapsed, 1)
                if elapsed > 0 else 0.0,
                "model_version": self.model_version,
                "queue_depth": (
                    self._queue_depth_fn() if self._queue_depth_fn else None
                ),
                "staleness": dict(zip(buckets, self.staleness_hist)),
                "max_staleness": self.max_staleness,
                "workers_lost": self.workers_lost,
                "shards_moved": self.shards_moved,
                "speculative_launches": self.speculative_launches,
                "last_objective": self.last_objective,
                "workers": {str(k): dict(v) for k, v in self.workers.items()},
                # driver-side shuffle accounting (SortShuffleManager /
                # UnifiedMemoryManager observability role); per-run delta
                # of the process-global totals
                "shuffle": _delta(fams["shuffle"], self._bases["shuffle"]),
                # DCN robustness counters (net/): retries taken, breaker
                # trips, dedup hits, faults fired -- the failure-handling
                # subsystem's health at a glance (per-run delta)
                "net": dict(
                    _delta(fams["net"], self._bases["net"]),
                    # wire-bytes accounting (net/frame.py choke point):
                    # per-op sent/received frame bytes, per-run delta
                    bytes=_delta(fams["net_bytes"],
                                 self._bases["net_bytes"]),
                ),
                # elastic-plane counters (parallel/supervisor.py): workers
                # declared dead, shards adopted by survivors, rejoins,
                # surrogate releases, PS checkpoint resumes (per-run delta)
                "recovery": _delta(fams["recovery"],
                                   self._bases["recovery"]),
                # pipelined update-loop counters (parallel/ps_dcn.py):
                # prefetch hits/waits, stale-prefetch discards, async
                # pushes (per-run delta); inflight_max is a high-water
                # mark, shown raw
                "pipeline": dict(
                    _delta({k: v for k, v in pl.items()
                            if k != "inflight_max"},
                           self._bases["pipeline"]),
                    inflight_max=pl.get("inflight_max", 0),
                ),
                # serving-plane counters (serving/metrics.py): predicts,
                # failovers, unhealthy rejects, refresh shapes (per-run
                # delta of the flat counters) plus the derived views --
                # QPS over the delta'd window, predict-latency and
                # freshness-lag (versions + ms) percentiles, per-replica
                # breakdown -- shown raw (rings are reset-scoped, not
                # baseline-scoped)
                "serving": dict(
                    _delta(fams["serving"], self._bases["serving"]),
                    detail=_serving_snapshot(),
                ),
                # debug lock watchdog (net/lockwatch.py): socket-IO-under-
                # model-lock violations (the lock-free PULL claim; 0 =
                # holding) and hold-time stats, raw
                "lockwatch": _lockwatch_totals(),
                # distributed-trace section (metrics/trace.py): per-stage
                # latency p50/p95/p99 and staleness in versions AND ms,
                # folded from this run's TraceSpan events
                "trace": self._trace.snapshot(),
                # telemetry plane (metrics/timeseries.py + slo.py):
                # convergence curves + summary, SLO health with burn
                # durations, and the time-series store's meta-view (full
                # rings on /api/timeseries)
                **telemetry,
            }


_PAGE = """<!doctype html><html><head><title>async run</title>
<meta http-equiv="refresh" content="1">
<style>body{font-family:monospace;margin:2em;background:#111;color:#ddd}
table{border-collapse:collapse}td,th{border:1px solid #444;padding:4px 10px}
h1{font-size:1.2em}.k{color:#8cf}</style></head><body>
<h1>asyncframework-tpu &mdash; live run</h1><pre id="s">%s</pre>
</body></html>"""


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "AsyncLiveUI/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _status(self) -> Dict:
        state = self.server.state_listener  # type: ignore[attr-defined]
        if state is not None:
            return state.snapshot()
        return process_status(
            role=self.server.role  # type: ignore[attr-defined]
        )

    def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
        if self.path.startswith("/api/status"):
            body = json.dumps(self._status()).encode()
            self._send(200, body, "application/json")
        elif self.path.startswith("/api/timeseries"):
            # the full bounded rings (async-top sparklines, ad-hoc
            # plotting); /api/status carries only the meta-view
            from asyncframework_tpu.metrics import timeseries

            body = json.dumps(timeseries.store().dump()).encode()
            self._send(200, body, "application/json")
        elif self.path.startswith("/metrics"):
            # Prometheus text exposition (format 0.0.4), stamped with
            # this server's process labels
            from asyncframework_tpu.metrics import prom

            body = prom.render(
                self.server.prom_labels  # type: ignore[attr-defined]
            ).encode()
            self._send(200, body, "text/plain; version=0.0.4")
        elif self.path == "/" or self.path.startswith("/index"):
            snap = json.dumps(self._status(), indent=2)
            self._send(200, (_PAGE % snap).encode(), "text/html")
        else:
            self._send(404, b"not found", "text/plain")

    def log_message(self, *a) -> None:  # quiet: no stderr per request
        pass


class LiveUIServer:
    """Threaded HTTP server around an optional :class:`LiveStateListener`.

    With ``state=None`` this is a bare **telemetry server**: /api/status
    serves the process-global counter/convergence/health view and
    /metrics the Prometheus exposition -- the per-process endpoint
    workers, serving replicas, frontends, and the master expose (see
    :func:`start_telemetry_from_conf`).  With a state listener it is the
    full live run dashboard, same endpoints included.

    ``port=0`` binds an ephemeral port (read it from ``.port`` after
    ``start``; also discoverable via :func:`active_servers`).
    ``role``/``labels`` become the Prometheus labels on every sample
    (plus ``run_id``, stamped automatically).
    """

    def __init__(self, state: Optional[LiveStateListener], port: int = 0,
                 host: str = "127.0.0.1", role: str = "driver",
                 labels: Optional[Dict[str, str]] = None):
        self.state = state
        self.role = role
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.state_listener = state  # type: ignore[attr-defined]
        self._httpd.role = role  # type: ignore[attr-defined]
        self._httpd.prom_labels = dict(  # type: ignore[attr-defined]
            {"role": role, "run_id": RUN_ID}, **(labels or {})
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "LiveUIServer":
        # the continuous-telemetry contract: any process serving
        # /metrics or a dashboard also samples its counters into the
        # time-series store (SLO windows need history, not points)
        from asyncframework_tpu.metrics import timeseries

        timeseries.ensure_started()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="live-ui", daemon=True
        )
        self._thread.start()
        with _ACTIVE_LOCK:
            _ACTIVE.insert(0, self)
        return self

    def stop(self) -> None:
        with _ACTIVE_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def start_telemetry_from_conf(role: str, host: str = "0.0.0.0",
                              labels: Optional[Dict[str, str]] = None
                              ) -> Optional[LiveUIServer]:
    """Start this process's bare telemetry endpoint when conf asks.

    Reads ``async.metrics.port`` (-1 = off, the default; 0 = ephemeral):
    every daemon entry point (worker daemon, serving replica/frontend,
    master, cluster roles) calls this once at boot, so setting one conf
    key -- or the ``ASYNCTPU_ASYNC_METRICS_PORT`` env var the k8s
    manifests ship -- lights up /metrics and /api/status fleet-wide.

    The crash flight recorder and the continuous profiler ride the same
    choke point (``async.flight.dir`` / ``async.prof.enabled`` gate them
    independently of the port): every role that can serve telemetry also
    keeps its post-mortem ring and its profile plane, and a new daemon
    entry point cannot wire one without the others."""
    from asyncframework_tpu.conf import METRICS_PORT, global_conf
    from asyncframework_tpu.metrics import flightrec
    from asyncframework_tpu.metrics import profiler as _profiler

    flightrec.install_from_conf(role)
    _profiler.install_from_conf(role)
    port = int(global_conf().get(METRICS_PORT))
    if port < 0:
        return None
    try:
        return LiveUIServer(None, port=port, host=host, role=role,
                            labels=labels).start()
    except OSError:
        # the port is taken -- e.g. a DCN executor inheriting its pod's
        # ASYNCTPU_ASYNC_METRICS_PORT while the worker daemon already
        # serves it.  Telemetry is best-effort fleet plumbing: the
        # process must come up either way.
        return None
