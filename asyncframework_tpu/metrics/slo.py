"""Declarative SLO engine over the time-series store.

Rules come from conf (``async.slo.rules``) in a small grammar, one rule
per ``;``-separated clause::

    <name>: <agg>(<series>) <op> <threshold> [over <window>s] [for <burn>s]
            [unless <series>]

- ``agg``: ``last | min | max | mean | p50 | p95 | p99 | count | rate``
  (``rate`` = per-second counter slope over the window, the updates/s
  floor's aggregate).
- ``series``: a store series name (``serving.freshness_lag_ms``,
  ``ps.accepted``, ``trace.staleness_ms_p95``, ...).
- ``op``: ``<  <=  >  >=``.
- ``over`` (default 30 s): the evaluation window.
- ``for`` (default 0 s): the burn duration -- the rule must be violated
  continuously this long before it FIRES (transient spikes stay
  ``pending``).
- ``unless`` (optional): a gate series -- while its LAST sample is
  truthy the rule is not applicable and reads ``no_data`` (clearing
  even a firing state: the gate is an explicit "this condition no
  longer applies" signal, unlike silence).  The registered default uses
  it so the updates/s floor stands down once ``ps.done`` goes to 1 --
  a finished run serving reads forever is healthy, not an outage.

Example (the registered default)::

    serve_freshness: p95(serving.freshness_lag_ms) < 2000 over 15s for 2s;
    predict_p99: max(serving.predict_ms_p99) < 500 over 30s for 5s;
    staleness_ms: max(trace.staleness_ms_p95) < 60000 over 30s for 5s;
    updates_floor: rate(ps.accepted) > 0.5 over 30s for 10s unless ps.done

Each rule is a tiny state machine: ``no_data`` (no samples in window;
never fires -- an idle process is not an outage, and a rule whose
subsystem never ran must not wedge the health red) -> ``ok`` ->
``pending`` (violating, burn accumulating) -> ``firing`` (violated for
>= ``for``); recovery returns it to ``ok`` and counts a transition.
``health()`` is the ``/api/status`` ``health`` section: per-rule state,
last value vs threshold, violation start, burn seconds, and
fired/recovered transition counts -- ``bin/chaos_sweep.py`` asserts no
rule stays firing after recovery completes.

Evaluation is driven by the telemetry sampler (every tick) and on
demand by ``health()`` readers; both paths are cheap (a window scan per
rule) and lock-guarded.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

AGGS = ("last", "min", "max", "mean", "p50", "p95", "p99", "count", "rate")
OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}

_RULE_RE = re.compile(
    r"^\s*(?P<name>[A-Za-z_][\w.-]*)\s*:\s*"
    r"(?P<agg>[a-z0-9]+)\s*\(\s*(?P<series>[\w.-]+)\s*\)\s*"
    r"(?P<op><=|>=|<|>)\s*(?P<threshold>-?\d+(?:\.\d+)?(?:e-?\d+)?)"
    r"(?:\s+over\s+(?P<window>\d+(?:\.\d+)?)\s*s)?"
    r"(?:\s+for\s+(?P<burn>\d+(?:\.\d+)?)\s*s)?"
    r"(?:\s+unless\s+(?P<unless>[\w.-]+))?\s*$",
    re.IGNORECASE,
)


@dataclass(frozen=True)
class SLORule:
    name: str
    agg: str
    series: str
    op: str
    threshold: float
    window_s: float = 30.0
    for_s: float = 0.0
    unless_series: Optional[str] = None

    def spec(self) -> str:
        out = (f"{self.name}: {self.agg}({self.series}) {self.op} "
               f"{self.threshold:g} over {self.window_s:g}s "
               f"for {self.for_s:g}s")
        if self.unless_series:
            out += f" unless {self.unless_series}"
        return out


def parse_rules(text: str) -> List[SLORule]:
    """Parse the conf rule string; raises ValueError naming the bad
    clause (a typo'd SLO must fail loudly at engine build, not silently
    never fire)."""
    rules: List[SLORule] = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        m = _RULE_RE.match(clause)
        if m is None:
            raise ValueError(f"unparseable SLO rule clause: {clause!r}")
        agg = m.group("agg").lower()
        if agg not in AGGS:
            raise ValueError(
                f"unknown aggregate {agg!r} in SLO rule {clause!r} "
                f"(have: {', '.join(AGGS)})"
            )
        rules.append(SLORule(
            name=m.group("name"),
            agg=agg,
            series=m.group("series"),
            op=m.group("op"),
            threshold=float(m.group("threshold")),
            window_s=float(m.group("window") or 30.0),
            for_s=float(m.group("burn") or 0.0),
            unless_series=m.group("unless"),
        ))
    names = [r.name for r in rules]
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        raise ValueError(f"duplicate SLO rule names: {sorted(dup)}")
    return rules


OK, PENDING, FIRING, NO_DATA = "ok", "pending", "firing", "no_data"


@dataclass
class _RuleState:
    state: str = NO_DATA
    value: Optional[float] = None
    violating_since: Optional[float] = None  # monotonic s
    fired_count: int = 0
    recovered_count: int = 0
    last_change: Optional[float] = None


class SLOEngine:
    """Evaluates a rule set against a :class:`TimeSeriesStore`."""

    def __init__(self, rules: List[SLORule], store=None,
                 now_fn=time.monotonic):
        self.rules = list(rules)
        self._store = store
        self._now = now_fn
        self._lock = threading.Lock()
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }

    def _get_store(self):
        if self._store is not None:
            return self._store
        from asyncframework_tpu.metrics import timeseries

        return timeseries.store()

    def _aggregate(self, rule: SLORule) -> Optional[float]:
        st = self._get_store()
        if rule.agg == "rate":
            return st.rate(rule.series, rule.window_s)
        agg = st.window_agg(rule.series, rule.window_s)
        if not agg.get("count"):
            return None
        if rule.agg == "count":
            return float(agg["count"])
        return float(agg[rule.agg])

    def evaluate(self) -> Dict[str, Dict]:
        """One evaluation pass over every rule; returns the health rule
        map (also cached for :meth:`health`)."""
        now = self._now()
        out: Dict[str, Dict] = {}
        for rule in self.rules:
            gated = False
            if rule.unless_series is not None:
                g = self._get_store().last(rule.unless_series)
                gated = bool(g)
            value = None if gated else self._aggregate(rule)
            with self._lock:
                rs = self._states[rule.name]
                rs.value = value
                if gated:
                    # explicit not-applicable signal (e.g. the run is
                    # DONE): stand down COMPLETELY -- unlike silence,
                    # the gate clears even a firing state
                    if rs.state != NO_DATA:
                        rs.state = NO_DATA
                        rs.last_change = now
                    rs.violating_since = None
                elif value is None:
                    # no samples: never fire on silence -- but a rule
                    # that WAS firing stays firing until data says
                    # otherwise (a dead subsystem must not auto-clear
                    # its own alarm by dying harder)
                    if rs.state != FIRING:
                        if rs.state != NO_DATA:
                            rs.state = NO_DATA
                            rs.last_change = now
                        rs.violating_since = None
                else:
                    violated = not OPS[rule.op](value, rule.threshold)
                    if violated:
                        if rs.violating_since is None:
                            rs.violating_since = now
                        burn = now - rs.violating_since
                        want = FIRING if burn >= rule.for_s else PENDING
                        if rs.state != want:
                            if want == FIRING:
                                rs.fired_count += 1
                            rs.state = want
                            rs.last_change = now
                    else:
                        if rs.state == FIRING:
                            rs.recovered_count += 1
                        if rs.state != OK:
                            rs.state = OK
                            rs.last_change = now
                        rs.violating_since = None
                out[rule.name] = self._rule_view(rule, rs, now)
        return out

    def _rule_view(self, rule: SLORule, rs: _RuleState, now: float) -> Dict:
        burn = (now - rs.violating_since
                if rs.violating_since is not None else 0.0)
        out = {
            "state": rs.state,
            "value": rs.value,
            "threshold": rule.threshold,
            "op": rule.op,
            "agg": rule.agg,
            "series": rule.series,
            "window_s": rule.window_s,
            "for_s": rule.for_s,
            "burn_s": round(burn, 3),
            "fired": rs.fired_count,
            "recovered": rs.recovered_count,
        }
        if rule.unless_series:
            out["unless"] = rule.unless_series
        return out

    def health(self) -> Dict[str, object]:
        """The ``/api/status`` ``health`` section: evaluate now, roll up
        the overall state (firing > pending > ok; pure-no_data = ok --
        an idle process is healthy)."""
        rules = self.evaluate()
        states = [r["state"] for r in rules.values()]
        if FIRING in states:
            overall = FIRING
        elif PENDING in states:
            overall = PENDING
        else:
            overall = OK
        return {
            "state": overall,
            "firing": sorted(n for n, r in rules.items()
                             if r["state"] == FIRING),
            "rules": rules,
        }

    def reset(self) -> None:
        with self._lock:
            self._states = {r.name: _RuleState() for r in self.rules}


# --------------------------------------------------------------- global
_glock = threading.Lock()
_engine: Optional[SLOEngine] = None


def engine() -> SLOEngine:
    """The process-global engine, built from conf ``async.slo.rules`` on
    first touch (rebuild after conf changes via :func:`reset_engine`)."""
    global _engine
    with _glock:
        if _engine is None:
            from asyncframework_tpu.conf import SLO_RULES, global_conf

            _engine = SLOEngine(parse_rules(
                str(global_conf().get(SLO_RULES))
            ))
        return _engine


def reset_engine() -> None:
    """Drop the global engine so the next touch re-reads conf (tests,
    and ``metrics.reset_totals`` per-run isolation)."""
    global _engine
    with _glock:
        _engine = None


def bench_verdicts(updates_per_sec: Optional[float],
                   trajectory, extra_series=None) -> Dict[str, Dict]:
    """Static SLO verdicts for a finished benchmark run: evaluate the
    conf rule set against synthesized series -- ``ps.accepted`` rate =
    the run's updates/s, ``convergence.loss`` = the trajectory, plus
    any ``extra_series`` (name -> [(t_ms, value), ...]; the adaptive
    bench arm feeds ``control.changes`` so ``controller_converged`` is
    judged on the real decision trace) -- so BENCH_*.json records
    pass/violated per rule (rules whose series the run never produced
    report ``no_data``)."""
    from asyncframework_tpu.conf import SLO_RULES, global_conf
    from asyncframework_tpu.metrics.timeseries import TimeSeriesStore

    rules = parse_rules(str(global_conf().get(SLO_RULES)))
    st = TimeSeriesStore(capacity=4096)
    now = st.now_s()
    span_ms = float(trajectory[-1][0]) if trajectory else 0.0
    for pts in (extra_series or {}).values():
        if pts:
            span_ms = max(span_ms, float(pts[-1][0]))
    t0 = now - span_ms / 1e3
    if trajectory:
        for (t_ms, loss) in trajectory:
            st.record("convergence.loss", loss, t_s=t0 + float(t_ms) / 1e3)
    for name, pts in (extra_series or {}).items():
        for (t_ms, v) in pts:
            st.record(name, float(v), t_s=t0 + float(t_ms) / 1e3)
    extra_names = set(extra_series or ())
    eng = SLOEngine(rules, store=st)
    out: Dict[str, Dict] = {}
    for rule in eng.rules:
        if rule.series == "ps.accepted" and rule.agg == "rate":
            value: Optional[float] = updates_per_sec
        elif rule.agg == "rate" and rule.series in extra_names:
            # a rate rule over a synthesized counter keeps its DECLARED
            # window, anchored at run end: "the knob-change rate falls
            # below threshold within the burn window" is a claim about
            # the settled tail, not the whole-run average
            value = eng._aggregate(rule)
        else:
            # aggregate over the FULL synthesized span, not the rule's
            # live window (the run already happened)
            wide = SLORule(rule.name, rule.agg, rule.series, rule.op,
                           rule.threshold, window_s=1e9, for_s=0.0)
            value = eng._aggregate(wide)
        if value is None:
            out[rule.name] = {"state": NO_DATA, "value": None,
                              "threshold": rule.threshold}
        else:
            ok = OPS[rule.op](value, rule.threshold)
            out[rule.name] = {
                "state": OK if ok else "violated",
                "value": round(float(value), 6),
                "threshold": rule.threshold,
            }
    return out
