"""Prometheus text exposition (format 0.0.4) for every process.

One :func:`render` call produces the ``/metrics`` body from the same
sources the time-series sampler reads: the counter-family registry
(``async_<family>_<key>_total`` counters), the live derived sources
(serving freshness/latency, trace stage percentiles, convergence
scalars -- gauges), the SLO engine (``async_slo_state`` per rule:
0 = ok, 1 = pending, 2 = firing, -1 = no_data), and a process-identity
``async_process_info`` gauge.  Every sample carries the process labels
(``role``, ``run_id``, plus whatever the server adds -- ``wid`` on
workers) so a cluster scrape distinguishes PS / worker / replica /
frontend / master series without name collisions.

Metric-name hygiene: family keys are free-form internal strings
(``sent.PULL``, ``pull.rtt.p95_ms``); :func:`_metric_name` maps them to
``[a-zA-Z_][a-zA-Z0-9_]*`` deterministically.  :func:`parse_exposition`
is the strict reader the tier-1 tests (and anyone debugging a scrape)
use: it validates comment/sample line shape, label syntax, float
values, and TYPE declarations, returning ``{(name, labels): value}``.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+"
    r"(?P<value>[^\s]+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _metric_name(*parts: str) -> str:
    out = "_".join(parts)
    out = re.sub(r"[^a-zA-Z0-9_]", "_", out)
    if not out or not re.match(r"[a-zA-Z_]", out[0]):
        out = "_" + out
    return out


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Writer:
    def __init__(self, labels: Dict[str, str]):
        self.labels = dict(labels)
        # the exposition format requires all lines of one metric to form
        # a single uninterrupted group, but callers interleave names
        # (e.g. the SLO loop emits state/value/fired per rule) -- so
        # samples buffer per metric and body() emits grouped, metrics in
        # first-seen order
        self._groups: Dict[str, List[str]] = {}
        self._order: List[str] = []

    def sample(self, name: str, value: float, mtype: str = "gauge",
               help_: str = "", extra: Optional[Dict[str, str]] = None
               ) -> None:
        if value is None:
            return
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        group = self._groups.get(name)
        if group is None:
            group = self._groups[name] = (
                [f"# HELP {name} {help_}"] if help_ else []
            )
            group.append(f"# TYPE {name} {mtype}")
            self._order.append(name)
        labels = dict(self.labels)
        if extra:
            labels.update(extra)
        # full precision: '%g' would quantize large counters (a 10 MB
        # byte counter to 6 significant digits), corrupting scrape-side
        # rate() deltas -- integral values print exact, floats via repr
        text = (str(int(v)) if v.is_integer() and abs(v) < 2**63
                else repr(v))
        group.append(f"{name}{_fmt_labels(labels)} {text}")

    def body(self) -> str:
        return "\n".join(line for name in self._order
                         for line in self._groups[name]) + "\n"


def render(labels: Optional[Dict[str, str]] = None) -> str:
    """The ``/metrics`` body for THIS process."""
    from asyncframework_tpu.metrics import registry, slo, timeseries
    from asyncframework_tpu.metrics import trace as trace_mod

    w = _Writer(labels or {})
    w.sample("async_process_info", 1.0, help_="process identity carrier "
             "(labels: role, run_id, ...)")

    fams = registry.families()
    for fam_name, fam in fams.items():
        try:
            tot = fam.totals()
        except Exception:  # noqa: BLE001 - one family must not kill /metrics
            continue
        for key, val in sorted(tot.items()):
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            if key in fam.high_water:
                w.sample(_metric_name("async", fam_name, key), val,
                         mtype="gauge", help_=f"{fam_name} high-water mark")
            else:
                w.sample(_metric_name("async", fam_name, key, "total"),
                         val, mtype="counter", help_=fam.doc or fam_name)

    # trace stage percentiles (latency decomposition as scrapeable gauges)
    snap = trace_mod.aggregator().snapshot()
    for stage, s in sorted((snap.get("stages_ms") or {}).items()):
        if not s.get("count"):
            continue
        for q in ("p50", "p95", "p99"):
            w.sample("async_trace_stage_ms", s[q], mtype="gauge",
                     help_="per-stage update-lifecycle latency (ms)",
                     extra={"stage": stage, "quantile": q})
    for key, metric in (("staleness_ms", "async_trace_staleness_ms"),
                        ("staleness_versions",
                         "async_trace_staleness_versions")):
        s = snap.get(key) or {}
        if s.get("count"):
            for q in ("p50", "p95", "p99"):
                w.sample(metric, s[q], mtype="gauge",
                         help_="gradient staleness distribution",
                         extra={"quantile": q})

    # serving derived gauges (freshness is THE serve SLO input)
    try:
        for key, val in sorted(timeseries._serving_source().items()):
            w.sample(_metric_name("async_serving", key), val,
                     mtype="gauge", help_="serving-plane derived gauge")
    except Exception:  # noqa: BLE001
        pass

    # convergence scalars
    conv = timeseries.convergence().summary()
    if "last_loss" in conv:
        w.sample("async_convergence_loss", conv["last_loss"],
                 mtype="gauge", help_="latest folded training loss")
    if conv.get("slope_per_s") is not None:
        w.sample("async_convergence_slope_per_s", conv["slope_per_s"],
                 mtype="gauge",
                 help_="trailing-half loss slope (units/s; negative = "
                       "converging)")

    # SLO states: 0 ok, 1 pending, 2 firing, -1 no_data
    code = {slo.OK: 0.0, slo.PENDING: 1.0, slo.FIRING: 2.0,
            slo.NO_DATA: -1.0}
    try:
        rules = slo.engine().evaluate()
    except Exception:  # noqa: BLE001 - a bad rule set must not kill /metrics
        rules = {}
    for name, r in sorted(rules.items()):
        w.sample("async_slo_state", code.get(r["state"], -1.0),
                 mtype="gauge",
                 help_="SLO rule state: 0 ok, 1 pending, 2 firing, "
                       "-1 no_data",
                 extra={"rule": name})
        if r.get("value") is not None:
            w.sample("async_slo_value", r["value"], mtype="gauge",
                     help_="SLO rule last aggregate value",
                     extra={"rule": name})
        w.sample("async_slo_fired_total", r.get("fired", 0),
                 mtype="counter", help_="times this rule entered firing",
                 extra={"rule": name})
    return w.body()


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                         ...]], float]:
    """Strict Prometheus text-format reader (the test-suite validator).

    Returns ``{(metric_name, sorted_label_items): value}``.  Raises
    ``ValueError`` on: malformed sample/comment lines, invalid metric or
    label names, unparseable float values, a sample whose metric was
    never TYPE-declared, or metric groups that are interleaved (the
    format requires all lines of one metric to be contiguous).
    """
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    typed: set = set()
    closed: set = set()
    current: Optional[str] = None

    def enter_group(name: str, lineno: int) -> None:
        nonlocal current
        if name == current:
            return
        if name in closed:
            raise ValueError(
                f"line {lineno}: metric {name!r} reappears after its "
                f"group ended (interleaved groups)")
        if current is not None:
            closed.add(current)
        current = name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            if not _NAME_OK.match(parts[2]):
                raise ValueError(
                    f"line {lineno}: bad metric name {parts[2]!r}")
            enter_group(parts[2], lineno)
            if parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    raise ValueError(f"line {lineno}: bad TYPE {line!r}")
                typed.add(parts[2])
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: bad sample line {line!r}")
        name = m.group("name")
        if name not in typed:
            raise ValueError(
                f"line {lineno}: sample for undeclared metric {name!r}")
        enter_group(name, lineno)
        raw_labels = m.group("labels") or ""
        labels: Dict[str, str] = {}
        if raw_labels.strip():
            matched = []
            for lm in _LABEL_RE.finditer(raw_labels):
                labels[lm.group(1)] = lm.group(2)
                matched.append(lm.group(0))
            # everything between the braces must be label pairs (modulo
            # separators) -- leftovers mean malformed label syntax
            stripped = re.sub(r"[,\s]", "", raw_labels)
            joined = len(re.sub(r"[,\s]", "", "".join(matched)))
            if joined != len(stripped):
                raise ValueError(
                    f"line {lineno}: bad label syntax {raw_labels!r}")
        try:
            value = float(m.group("value"))
        except ValueError:
            raise ValueError(
                f"line {lineno}: bad value {m.group('value')!r}")
        out[(name, tuple(sorted(labels.items())))] = value
    return out
