"""Crash flight recorder: a bounded event ring that survives SIGKILL.

Every observability surface this repo built so far dies with its
process: ``/api/status`` stops answering, the time-series store is heap
memory, and a chaos ``kill -9`` leaves nothing but the supervisor's
"member dead" counter.  The flight recorder is the post-mortem path:
each role keeps a bounded in-memory ring of recent events -- data-plane
notes (pushes acked, merge batches drained), membership transitions,
fired fault-schedule events, and per-flush counter deltas -- and writes
it to ``<dir>/flight-<role>-<pid>.json``:

- **on a cadence** (``async.flight.flush.s``): an atomic overwrite via
  ``checkpoint.durable_replace``, so an *uncatchable* SIGKILL leaves a
  dump at most one flush stale;
- **on catchable fatal signals** (SIGTERM/SIGINT, chained to any prior
  handler) and **at interpreter exit** (atexit): a final synchronous
  dump stamped with its reason.

The cluster observer (``metrics/observer.py``) harvests these files
into the durable run-history store, so "worker 3 was SIGKILLed" comes
with the last thing worker 3 did instead of silence.

Cost discipline: recording is one deque append under a short lock;
:func:`note` is a no-op returning immediately when no recorder is
installed (the default -- ``async.flight.dir`` empty), so instrumented
hot paths pay one global read.  Dumps serialize a snapshot taken under
the lock but write the file outside it.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_lock = threading.Lock()
_recorder: Optional["FlightRecorder"] = None

_totals_lock = threading.Lock()
_totals = {"flushes": 0, "dumps": 0, "dump_errors": 0}


def _bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] += n


def flight_totals() -> Dict[str, int]:
    """Flat meta-counters (registry family ``flight``).  ``notes`` and
    ``dropped`` read the installed recorder's own ring ledgers (the
    ring already counts both exactly) -- the hot-path note() pays ONE
    lock, never a second process-global bump per event."""
    with _totals_lock:
        out = dict(_totals)
    rec = _recorder
    if rec is not None:
        with rec._ring_lock:
            out["notes"] = rec._seq
            out["dropped"] = rec._dropped
    else:
        out["notes"] = out["dropped"] = 0
    return out


def reset_flight_totals() -> None:
    with _totals_lock:
        for k in _totals:
            _totals[k] = 0
    rec = _recorder
    if rec is not None:
        # per-run isolation, same contract as every registry family:
        # the note/drop ledgers restart (the ring contents stay -- a
        # post-mortem must not lose its events to a counter reset)
        with rec._ring_lock:
            rec._seq = 0
            rec._dropped = 0


def recorder() -> Optional["FlightRecorder"]:
    with _lock:
        return _recorder


def note(kind: str, **fields) -> None:
    """Record one event into the installed recorder; no-op when none is
    installed (the common case -- callers need no gating of their own)."""
    rec = _recorder  # racy read by design: a torn install drops one note
    if rec is not None:
        rec.note(kind, **fields)


class FlightRecorder:
    """One process's bounded event ring + its dump/flush machinery."""

    SCHEMA = 1

    def __init__(self, role: str, dump_dir: str, capacity: int = 256,
                 flush_s: float = 0.5):
        self.role = str(role)
        self.dump_dir = str(dump_dir)
        self.capacity = max(8, int(capacity))
        self.flush_s = float(flush_s)
        self._ring_lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._seq = 0
        self._dropped = 0
        self._started_s = time.time()
        self._last_counters: Dict[str, Dict[str, float]] = {}
        self._stop = threading.Event()
        self._flush_thread: Optional[threading.Thread] = None
        self._prev_handlers: Dict[int, object] = {}

    # -------------------------------------------------------------- recording
    def note(self, kind: str, **fields) -> None:
        ev = {"t": time.time(), "kind": str(kind)}
        ev.update(fields)
        with self._ring_lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(ev)
            self._seq += 1

    def _counters_delta_event(self) -> None:
        """One per-flush event holding every non-zero counter-family
        delta since the previous flush (the "what moved" view a
        post-mortem reads next to the last data-plane notes)."""
        from asyncframework_tpu.metrics import registry

        delta: Dict[str, float] = {}
        cur: Dict[str, Dict[str, float]] = {}
        for name, fam in registry.families().items():
            if name == "flight":
                continue  # our own meta-counters move on every flush --
                          # including them would make each flush generate
                          # the next flush's "delta" forever
            try:
                tot = fam.totals()
            except Exception:  # noqa: BLE001 - a lean process missing one
                continue       # family must not lose its whole dump
            cur[name] = tot
            prev = self._last_counters.get(name, {})
            for k, v in tot.items():
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                d = v - prev.get(k, 0)
                if d:
                    delta[f"{name}.{k}"] = d
        self._last_counters = cur
        if delta:
            self.note("counters", delta=delta)

    # ----------------------------------------------------------------- dumps
    def dump_path(self) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in self.role)
        return os.path.join(self.dump_dir,
                            f"flight-{safe}-{os.getpid()}.json")

    def snapshot(self, reason: str) -> dict:
        from asyncframework_tpu.metrics.live import RUN_ID

        with self._ring_lock:
            events = list(self._ring)
            seq, dropped = self._seq, self._dropped
        # the profile post-mortem: one fresh snapshot per dump, so even
        # a SIGKILL leaves the zone decomposition at most one flush
        # stale (None while async.prof never ran -- key omitted, old
        # dump shape preserved)
        try:
            from asyncframework_tpu.metrics import profiler as _profiler
            prof = _profiler.last_snapshot()
        except Exception:
            prof = None
        out = {
            "schema": self.SCHEMA,
            "role": self.role,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "run_id": RUN_ID,
            "started_s": self._started_s,
            "dumped_s": time.time(),
            "reason": reason,
            "seq": seq,
            "dropped": dropped,
            "events": events,
            "counters": dict(self._last_counters),
        }
        if prof is not None:
            out["profile"] = prof
        return out

    def dump(self, reason: str = "periodic") -> Optional[str]:
        """Write the ring to disk atomically; returns the path (None on
        error -- a dying process must not die harder over its own
        post-mortem)."""
        from asyncframework_tpu.checkpoint import durable_replace

        snap = self.snapshot(reason)
        path = self.dump_path()
        tmp = path + ".tmp"
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f, default=str)
            durable_replace(tmp, path)
        except OSError:
            _bump("dump_errors")
            return None
        _bump("dumps")
        return path

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "FlightRecorder":
        if self.flush_s > 0:
            def loop() -> None:
                while not self._stop.wait(timeout=self.flush_s):
                    self._counters_delta_event()
                    self.dump("periodic")
                    _bump("flushes")

            self._flush_thread = threading.Thread(
                target=loop, name="flight-flush", daemon=True
            )
            self._flush_thread.start()
        self._install_signal_hooks()
        import atexit

        atexit.register(self._atexit_dump)
        return self

    def _install_signal_hooks(self) -> None:
        """Final dump on catchable fatal signals, chained to whatever
        handler was installed before (a shard child's SIGTERM event,
        the default exit).  Best-effort: handlers only install from the
        main thread; elsewhere the cadence dump is the cover."""
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                prev = signal.getsignal(signum)

                def handler(num, frm, _prev=prev):
                    self.dump(f"signal:{num}")
                    if callable(_prev):
                        _prev(num, frm)
                    elif _prev != signal.SIG_IGN:
                        # SIG_DFL -- or None (a non-Python handler we
                        # cannot call back): either way the signal must
                        # still be FATAL, not swallowed by the dump hook
                        signal.signal(num, signal.SIG_DFL)
                        os.kill(os.getpid(), num)

                signal.signal(signum, handler)
                self._prev_handlers[signum] = prev
            except (ValueError, OSError):
                # not the main thread, or an unsupported platform signal
                pass

    def _atexit_dump(self) -> None:
        if not self._stop.is_set():
            exc = sys.exc_info()[0]
            self.dump("exception-exit" if exc is not None else "exit")

    def stop(self, final_dump: bool = True) -> None:
        if final_dump:
            self._counters_delta_event()
            self.dump("stop")
        self._stop.set()
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=5.0)
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)  # type: ignore[arg-type]
            except (ValueError, TypeError, OSError):
                pass
        self._prev_handlers.clear()


def install(role: str, dump_dir: str, capacity: int = 256,
            flush_s: float = 0.5) -> FlightRecorder:
    """Install (and start) the process-global recorder; idempotent per
    process -- a second install for a different role keeps the first
    (one process, one post-mortem identity)."""
    global _recorder
    with _lock:
        if _recorder is not None:
            return _recorder
        rec = FlightRecorder(role, dump_dir, capacity=capacity,
                             flush_s=flush_s)
        _recorder = rec
    rec.start()
    return rec


def install_from_conf(role: str) -> Optional[FlightRecorder]:
    """Conf-gated install (``async.flight.dir`` empty = off): the one
    call every daemon entry point makes, riding
    ``live.start_telemetry_from_conf`` so new roles cannot forget it."""
    from asyncframework_tpu.conf import (
        FLIGHT_DIR,
        FLIGHT_EVENTS,
        FLIGHT_FLUSH_S,
        global_conf,
    )

    conf = global_conf()
    dump_dir = str(conf.get(FLIGHT_DIR) or "").strip()
    if not dump_dir:
        return None
    return install(role, dump_dir,
                   capacity=int(conf.get(FLIGHT_EVENTS)),
                   flush_s=float(conf.get(FLIGHT_FLUSH_S)))


def uninstall(final_dump: bool = False) -> None:
    """Drop the process-global recorder (tests)."""
    global _recorder
    with _lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.stop(final_dump=final_dump)


def load_dump(path: str) -> dict:
    """Read one dump file back (the harvest/test reader); raises on a
    torn/foreign file -- callers decide how tolerant to be."""
    with open(path, "r", encoding="utf-8") as f:
        out = json.load(f)
    if not isinstance(out, dict) or "events" not in out:
        raise ValueError(f"{path}: not a flight-recorder dump")
    return out


def scan_dumps(dump_dir: str) -> List[str]:
    """All dump files under ``dump_dir`` (sorted; missing dir = [])."""
    try:
        names = os.listdir(dump_dir)
    except OSError:
        return []
    return sorted(
        os.path.join(dump_dir, n) for n in names
        if n.startswith("flight-") and n.endswith(".json")
    )
