"""Metrics registry with periodic sink reporting.

Parity: the Dropwizard-based ``MetricsSystem``
(``metrics/MetricsSystem.scala:70``) with sources (named gauge providers) and
sinks (Console/CSV/... -- ``core/.../metrics/sink/``) polled on an interval.
Here: :class:`Counter` / :class:`Gauge` / :class:`Histogram` instruments in a
registry, callable sources for on-demand gauges, and Console/CSV/JSONL sinks
driven by an injectable :class:`Clock` so tests use virtual time.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from pathlib import Path
from typing import Callable, Dict, List, Optional, Union

from asyncframework_tpu.utils.clock import Clock, SystemClock


class Counter:
    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    def __init__(self, initial: float = 0.0) -> None:
        self._v = initial
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Bounded reservoir histogram (keeps the most recent ``capacity``)."""

    def __init__(self, capacity: int = 4096) -> None:
        self._vals: "deque[float]" = deque(maxlen=capacity)
        self._capacity = capacity
        self._lock = threading.Lock()
        self.count = 0

    def update(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self._vals.append(v)  # deque(maxlen) evicts the oldest in O(1)

    @staticmethod
    def _pct(vals: List[float], q: float) -> float:
        """Nearest-rank percentile: the smallest value whose cumulative
        share is >= q.  The old ``int(q * n)`` indexing returned the MAX
        for p95 at any n <= 20 (int(0.95 * 20) == 19) -- every small-n
        histogram overstated its tail."""
        import math

        n = len(vals)
        return vals[min(n - 1, max(0, math.ceil(q * n) - 1))]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._vals)
        if not vals:
            return {"count": 0}
        n = len(vals)
        return {
            "count": self.count,
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / n,
            "p50": self._pct(vals, 0.50),
            "p95": self._pct(vals, 0.95),
            "p99": self._pct(vals, 0.99),
        }


class Sink:
    def report(self, time_ms: float, values: Dict[str, object]) -> None:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - default
        pass


class ConsoleSink(Sink):
    def __init__(self, out=None):
        import sys

        self._out = out or sys.stderr

    def report(self, time_ms: float, values: Dict[str, object]) -> None:
        print(f"[metrics t={time_ms:.0f}ms] {values}", file=self._out)


class CsvSink(Sink):
    """One CSV per run; columns fixed at first report (late keys ignored)."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("w", buffering=1)
        self._cols: Optional[List[str]] = None

    def report(self, time_ms: float, values: Dict[str, object]) -> None:
        flat = _flatten(values)
        if self._cols is None:
            self._cols = ["time_ms"] + sorted(flat)
            self._f.write(",".join(self._cols) + "\n")
        row = [f"{time_ms:.1f}"] + [
            str(flat.get(c, "")) for c in self._cols[1:]
        ]
        self._f.write(",".join(row) + "\n")

    def close(self) -> None:
        self._f.close()


class JsonlSink(Sink):
    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("w", buffering=1)

    def report(self, time_ms: float, values: Dict[str, object]) -> None:
        self._f.write(
            json.dumps({"time_ms": time_ms, **values}, default=str) + "\n"
        )

    def close(self) -> None:
        self._f.close()


def _flatten(values: Dict[str, object], prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


class MetricsSystem:
    """Registry + polling loop.

    Instruments are registered under dotted names; sources are callables
    returning a dict (evaluated at report time).  ``start(period_s)`` spawns
    the polling thread; with a :class:`ManualClock` the loop ticks only when
    the test advances time (streaming-suite determinism parity).
    """

    def __init__(self, clock: Optional[Clock] = None):
        self._clock = clock or SystemClock()
        self._instruments: Dict[str, object] = {}
        self._sources: Dict[str, Callable[[], Dict[str, object]]] = {}
        self._sinks: List[Sink] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._register(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._register(name, Histogram)

    def _register(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls()
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}"
                )
            return inst

    def register_source(
        self, name: str, fn: Callable[[], Dict[str, object]]
    ) -> None:
        with self._lock:
            self._sources[name] = fn

    def add_sink(self, sink: Sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def collect(self) -> Dict[str, object]:
        with self._lock:
            instruments = dict(self._instruments)
            sources = dict(self._sources)
        out: Dict[str, object] = {}
        for name, inst in instruments.items():
            if isinstance(inst, Histogram):
                out[name] = inst.snapshot()
            else:
                out[name] = inst.value  # type: ignore[union-attr]
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 - source must not kill report
                out[name] = f"<error: {e!r}>"
        return out

    def report(self) -> Dict[str, object]:
        values = self.collect()
        t = self._clock.now_ms()
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.report(t, values)
            except Exception:  # noqa: BLE001 - one sink must not kill the rest
                # mirrors the source-collection shield above; a dead sink
                # must not terminate the polling thread
                pass
        return values

    def start(self, period_s: float = 10.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                if self._clock.wait_for(self._stop, period_s):
                    return  # interrupted by stop(), not a tick
                self.report()

        self._thread = threading.Thread(
            target=loop, name="metrics-system", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            sink.close()
