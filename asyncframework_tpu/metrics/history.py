"""History server: render every event log in a directory to browsable HTML.

Parity: ``deploy/history/FsHistoryProvider.scala`` -- the reference's
history server watches a log directory and serves past applications' UIs.
The TPU build keeps the capability without the daemon: one command scans
the directory, renders a per-run report (``metrics/report.py``) for every
JSONL(.gz) event log, and writes an ``index.html`` linking them with
summary rows -- a static history "server" viewable from any file browser.

CLI: ``bin/async-history <log_dir> [out_dir]`` (defaults
``out_dir = <log_dir>/history``).
"""

from __future__ import annotations

import html
import sys
import time
from pathlib import Path
from typing import List, Optional, Tuple, Union

from asyncframework_tpu.metrics.bus import GradientMerged, JobStart
from asyncframework_tpu.metrics.eventlog import EventLogReader
from asyncframework_tpu.metrics.report import render_report

_LOG_SUFFIXES = (".jsonl", ".jsonl.gz")


def _is_event_log(p: Path) -> bool:
    name = p.name
    return any(name.endswith(sfx) for sfx in _LOG_SUFFIXES)


def _scan(path: Path):
    """ONE tolerant replay: (events, merges, jobs, truncated) -- the same
    pass feeds both the index row and the report render.  A torn record
    (crash mid-write) is skipped and counted (``strict=False``); only a
    file that yields nothing readable at all is flagged unreadable."""
    events = []
    merges = jobs = 0
    reader = EventLogReader(path)
    try:
        for ev in reader.replay(strict=False):
            events.append(ev)
            if isinstance(ev, GradientMerged):
                merges += 1
            elif isinstance(ev, JobStart):
                jobs += 1
    except Exception:
        return None, -1, -1, 0  # foreign/binary file: listed, unreadable
    if not events:
        return None, -1, -1, reader.truncated_records
    return events, merges, jobs, reader.truncated_records


def build_history(
    log_dir: Union[str, Path],
    out_dir: Optional[Union[str, Path]] = None,
) -> Path:
    """Render all event logs under ``log_dir``; returns the index path."""
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        raise ValueError(f"{log_dir} is not a directory")
    out_dir = Path(out_dir) if out_dir is not None else log_dir / "history"
    out_dir.mkdir(parents=True, exist_ok=True)

    logs = sorted(
        (p for p in log_dir.iterdir() if _is_event_log(p)),
        key=lambda p: p.stat().st_mtime,
        reverse=True,
    )
    rows: List[str] = []
    for p in logs:
        stem = p.name
        for sfx in _LOG_SUFFIXES:
            if stem.endswith(sfx):
                stem = stem[: -len(sfx)]
                break
        # report name from the FULL filename: "run.jsonl" and
        # "run.jsonl.gz" must not collide, and "index.jsonl" must not
        # render onto the index itself
        report_name = f"{p.name}.html"
        events, merges, jobs, truncated = _scan(p)
        if events is not None:
            try:
                render_report(
                    p, out_dir / report_name, title=f"run: {stem}",
                    events=events,
                )
            except Exception:
                # schema-drifted field VALUES can pass replay but break
                # the render; one bad log must not abort the whole index
                events = None
        if events is not None:
            link = f'<a href="{html.escape(report_name)}">{html.escape(stem)}</a>'
            status = f"{merges} updates, {jobs} jobs"
            if truncated:
                # crash-mid-write forensics: the run died with a torn tail
                status += f", {truncated} truncated record(s) skipped"
        else:
            link = html.escape(stem)
            status = "unreadable"
        mtime = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(p.stat().st_mtime)
        )
        rows.append(
            f"<tr><td>{link}</td><td>{mtime}</td><td>{status}</td></tr>"
        )

    observer_rows = _observer_rows(log_dir)
    observer_html = ""
    if observer_rows:
        observer_html = (
            "<h1>Observer run history "
            f"({len(observer_rows)} runs)</h1>"
            "<table><thead><tr><th>run</th><th>roles</th>"
            "<th>flight dumps</th><th>profiles</th>"
            "<th>persisted</th></tr></thead>"
            "<tbody>" + "".join(observer_rows) + "</tbody></table>"
        )
    index = out_dir / "index.html"
    index.write_text(
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>asyncframework-tpu history</title>"
        "<style>body{font-family:sans-serif;margin:2em}"
        "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
        "padding:4px 10px}</style></head><body>"
        f"<h1>Run history ({len(logs)} logs)</h1>"
        "<table><thead><tr><th>run</th><th>modified</th><th>summary</th>"
        "</tr></thead><tbody>" + "".join(rows) + "</tbody></table>"
        + observer_html
        + "</body></html>"
    )
    return index


def _observer_rows(log_dir: Path) -> List[str]:
    """Index rows for cluster-observer run-history dirs under
    ``log_dir`` (metrics/observer.py RunHistoryStore layout:
    ``run-<id>/meta.json`` + per-role series + harvested flight
    dumps) -- the same directory can hold event logs AND observer
    history; both get indexed."""
    from asyncframework_tpu.metrics import observer as observer_mod

    rows: List[str] = []
    for run_dir in observer_mod.list_runs(str(log_dir)):
        try:
            run = observer_mod.load_run(run_dir)
        except (OSError, ValueError):
            rows.append(
                f"<tr><td>{html.escape(Path(run_dir).name)}</td>"
                f"<td colspan='4'>unreadable</td></tr>"
            )
            continue
        meta = run.get("meta") or {}
        roles = run.get("roles") or {}
        role_bits = ", ".join(
            f"{html.escape(str(n))} ({len((r or {}).get('series') or {})} "
            f"series)"
            for n, r in sorted(roles.items())
        ) or "-"
        persisted = meta.get("persisted_s")
        when = (
            time.strftime("%Y-%m-%d %H:%M:%S",
                          time.localtime(float(persisted)))
            if persisted else "-"
        )
        rows.append(
            f"<tr><td>{html.escape(str(meta.get('run_id', '?')))}</td>"
            f"<td>{role_bits}</td>"
            f"<td>{len(run.get('flight') or {})}</td>"
            f"<td>{_profile_cell(run)}</td>"
            f"<td>{when}</td></tr>"
        )
    return rows


def _profile_cell(run: dict) -> str:
    """Profile-snapshot column: count plus each snapshot's top zone
    (``bin/async-prof <run_dir>`` renders the full table)."""
    profile = run.get("profile") or {}
    if not profile:
        return "-"
    bits = []
    for key, snap in sorted(profile.items()):
        zones = (snap or {}).get("zones") or {}
        top = max(zones.items(),
                  key=lambda kv: float((kv[1] or {}).get("share", 0.0)),
                  default=None)
        bits.append(
            html.escape(key)
            + (f" ({html.escape(top[0])})" if top else ""))
    return f"{len(profile)}: " + ", ".join(bits)


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not 1 <= len(argv) <= 2:
        print("usage: async-history <log_dir> [out_dir]", file=sys.stderr)
        return 2
    index = build_history(*argv)
    print(index)
    return 0


if __name__ == "__main__":
    sys.exit(main())
