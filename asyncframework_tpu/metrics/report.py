"""Static HTML report rendered from an event log.

Parity: the reference's web UI + history server (``core/.../ui/`` 6.3k LoC of
jetty pages over ``AppStatusStore``, ``deploy/history/FsHistoryProvider``)
exist to answer "what did this run do" after the fact.  The TPU build keeps
the capability but not the server: one self-contained HTML file generated
from the JSONL event log (``metrics/eventlog.py``), viewable anywhere,
zero running processes.  Inline SVG charts -- no JS dependencies, nothing to
install on a TPU host.
"""

from __future__ import annotations

import html
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Tuple, Union

from asyncframework_tpu.metrics.bus import (
    GradientMerged,
    JobEnd,
    JobStart,
    ModelSnapshot,
    RoundSubmitted,
    TaskEnd,
    WorkerLost,
)
from asyncframework_tpu.metrics.eventlog import EventLogReader


def _svg_line(points: List[Tuple[float, float]], width=640, height=200,
              label="") -> str:
    """Minimal inline-SVG line chart with axis annotations."""
    if len(points) < 2:
        return "<p><em>not enough data</em></p>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    pad = 30
    w, h = width - 2 * pad, height - 2 * pad

    def sx(x):
        return pad + (x - x0) / xr * w

    def sy(y):
        return pad + h - (y - y0) / yr * h

    path = " ".join(
        f"{'M' if i == 0 else 'L'}{sx(x):.1f},{sy(y):.1f}"
        for i, (x, y) in enumerate(points)
    )
    return (
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" role="img">'
        f'<rect width="100%" height="100%" fill="#fafafa"/>'
        f'<path d="{path}" fill="none" stroke="#2563eb" stroke-width="1.5"/>'
        f'<text x="{pad}" y="14" font-size="11">{html.escape(label)}</text>'
        f'<text x="{pad}" y="{height - 6}" font-size="10">{x0:.4g}</text>'
        f'<text x="{width - pad}" y="{height - 6}" font-size="10" '
        f'text-anchor="end">{x1:.4g}</text>'
        f'<text x="4" y="{pad + 8}" font-size="10">{y1:.4g}</text>'
        f'<text x="4" y="{height - pad}" font-size="10">{y0:.4g}</text>'
        f"</svg>"
    )


def _table(headers: List[str], rows: List[List[object]]) -> str:
    head = "".join(f"<th>{html.escape(str(hd))}</th>" for hd in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in row) + "</tr>"
        for row in rows
    )
    return f"<table><thead><tr>{head}</tr></thead><tbody>{body}</tbody></table>"


def render_report(
    event_log_path: Union[str, Path],
    out_path: Optional[Union[str, Path]] = None,
    title: str = "asyncframework-tpu run report",
    events: Optional[list] = None,
) -> str:
    """Build the HTML report; optionally write it to ``out_path``.

    Sections: run summary, objective-vs-iteration curve, staleness
    histogram, per-worker task table, failures.  ``events`` (pre-replayed)
    skips re-reading the log -- the history index scans once and reuses
    the same pass here.
    """
    reader = EventLogReader(event_log_path)
    merges: List[GradientMerged] = []
    snaps: List[ModelSnapshot] = []
    tasks: List[TaskEnd] = []
    lost: List[WorkerLost] = []
    jobs = 0
    job_fail = 0
    rounds = 0
    for ev in (events if events is not None else reader.replay()):
        if isinstance(ev, GradientMerged):
            merges.append(ev)
        elif isinstance(ev, ModelSnapshot):
            snaps.append(ev)
        elif isinstance(ev, TaskEnd):
            tasks.append(ev)
        elif isinstance(ev, WorkerLost):
            lost.append(ev)
        elif isinstance(ev, JobStart):
            jobs += 1
        elif isinstance(ev, JobEnd):
            job_fail += 0 if ev.succeeded else 1
        elif isinstance(ev, RoundSubmitted):
            rounds += 1

    accepted = sum(1 for m in merges if m.accepted)
    dropped = len(merges) - accepted
    max_stale = max((m.staleness for m in merges), default=0)

    per_worker: Dict[int, List[TaskEnd]] = defaultdict(list)
    for t in tasks:
        per_worker[t.worker_id].append(t)
    worker_rows = []
    for wid in sorted(per_worker):
        ts = per_worker[wid]
        ok = [t for t in ts if t.succeeded]
        avg = sum(t.run_ms for t in ok) / len(ok) if ok else 0.0
        worker_rows.append(
            [wid, len(ts), len(ts) - len(ok), f"{avg:.1f}"]
        )

    # staleness histogram as a bar-ish line chart over sorted counts
    stale_counts: Dict[int, int] = defaultdict(int)
    for m in merges:
        stale_counts[m.staleness] += 1
    stale_points = [(float(k), float(v)) for k, v in sorted(stale_counts.items())]

    obj_points = [(float(s.iteration), float(s.objective)) for s in snaps]

    summary_rows = [
        ["jobs", jobs],
        ["rounds submitted", rounds],
        ["gradients merged", len(merges)],
        ["accepted / dropped", f"{accepted} / {dropped}"],
        ["max staleness", max_stale],
        ["failed jobs", job_fail],
        ["workers lost", len(lost)],
    ]
    # raw strings here: _table escapes every cell exactly once
    failure_rows = [[l.worker_id, l.reason] for l in lost] + [
        [t.worker_id, t.error or ""] for t in tasks if not t.succeeded
    ]

    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        "<style>body{font:14px system-ui;margin:2em;max-width:72em}"
        "table{border-collapse:collapse;margin:1em 0}"
        "td,th{border:1px solid #ddd;padding:4px 10px;text-align:right}"
        "th{background:#f3f4f6}h2{margin-top:1.6em}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
        "<h2>Summary</h2>",
        _table(["metric", "value"], summary_rows),
        "<h2>Objective vs iteration</h2>",
        _svg_line(obj_points, label="objective"),
        "<h2>Staleness distribution</h2>",
        _svg_line(stale_points, label="merge count by staleness"),
        "<h2>Workers</h2>",
        _table(["worker", "tasks", "failures", "avg run ms"], worker_rows),
    ]
    if failure_rows:
        parts += ["<h2>Failures</h2>",
                  _table(["worker", "error"], failure_rows)]
    parts.append("</body></html>")
    doc = "".join(parts)
    if out_path is not None:
        Path(out_path).write_text(doc, encoding="utf-8")
    return doc
