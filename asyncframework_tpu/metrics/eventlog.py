"""JSONL event logging and replay (history-server analog).

Parity: ``EventLoggingListener`` (``scheduler/EventLoggingListener.scala:55``)
writes one JSON object per line per event; the history server's
``FsHistoryProvider`` replays the file to rebuild application state.  Here
:class:`EventLogWriter` is a bus listener streaming events to a JSONL file and
:class:`EventLogReader` replays a file back into typed events and summary
statistics (the ``AppStatusStore`` role, trimmed to this framework's event
vocabulary: rounds, merges, staleness distribution, worker health).
"""

from __future__ import annotations

import gzip
import json
import threading
from dataclasses import asdict
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Union

from asyncframework_tpu.metrics.bus import EVENT_TYPES, Event, Listener


def _open_log(path: Path, mode: str):
    """``.gz`` paths route through the zlib codec (the reference compresses
    event logs with its native lz4/zstd codecs --
    ``io/CompressionCodec.scala``; CPython's zlib is the native codec here)."""
    if path.suffix == ".gz":
        return gzip.open(path, mode + "t", encoding="utf-8")
    return path.open(mode, buffering=1 if "w" in mode else -1)


class EventLogWriter(Listener):
    """Streams every bus event to a JSONL file (``.gz`` = compressed);
    one line per event."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = _open_log(self.path, "w")
        self._lock = threading.Lock()
        self._closed = False

    def on_event(self, event: Event) -> None:
        rec = {"event": type(event).__name__, **asdict(event)}
        line = json.dumps(rec, separators=(",", ":"), default=_jsonable)
        with self._lock:
            if not self._closed:
                self._f.write(line + "\n")
                # flush per event: the log is a crash-forensics artifact, and
                # the gzip stream would otherwise buffer everything to close()
                self._f.flush()

    # per-type hooks all route to on_event for the writer
    def __getattr__(self, name: str):
        if name.startswith("on_"):
            return self.on_event
        raise AttributeError(name)

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()


def _jsonable(o):
    if isinstance(o, (tuple, set)):
        return list(o)
    return str(o)


class EventLogReader:
    """Replays a JSONL event log into typed events + summary statistics.

    After a replay (or :meth:`summary`), ``truncated_records`` counts the
    torn records that were skipped in tolerant mode -- the kill -9 world's
    crash-mid-write forensics: a writer SIGKILLed between ``write`` and
    ``flush`` leaves a partial final line (or a gzip stream without its end
    marker), and the whole valid prefix must still replay.
    """

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.truncated_records = 0

    def _lines(self, f) -> Iterator[str]:
        """Line iteration tolerating a crash-torn tail: a writer that died
        before close() leaves a gzip stream without its end marker; every
        fully-flushed line before the tear still replays."""
        try:
            yield from f
        except EOFError:
            self.truncated_records += 1
            return

    def replay(self, strict: bool = True) -> Iterator[Event]:
        """Yield events; with ``strict=False`` a torn record (crash
        mid-write) is skipped and counted in ``truncated_records`` instead
        of raising -- the history server's inspect-a-dead-run case."""
        self.truncated_records = 0
        with _open_log(self.path, "r") as f:
            for line in self._lines(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    if strict:
                        raise
                    # torn record: skip-and-count; the flush-per-event
                    # writer can only tear the final line, but counting
                    # (rather than stopping) also survives a foreign tool
                    # concatenating logs
                    self.truncated_records += 1
                    continue
                name = rec.pop("event", None)
                cls = EVENT_TYPES.get(name)
                if cls is None:
                    continue  # unknown event type: forward-compat skip
                fields = {
                    k: (tuple(v) if isinstance(v, list) else v)
                    for k, v in rec.items()
                }
                try:
                    yield cls(**fields)
                except TypeError:
                    continue  # schema drift: skip unreadable record

    def summary(self) -> Dict[str, object]:
        """History-server style aggregate view of one run's log."""
        from asyncframework_tpu.metrics.bus import (
            GradientMerged,
            JobEnd,
            ModelSnapshot,
            RoundSubmitted,
            TaskEnd,
            WorkerLost,
        )

        n_rounds = 0
        merges = 0
        accepted = 0
        staleness: List[int] = []
        task_ms: List[float] = []
        failures = 0
        lost: List[int] = []
        trajectory: List[tuple] = []
        for ev in self.replay(strict=False):
            if isinstance(ev, RoundSubmitted):
                n_rounds += 1
            elif isinstance(ev, GradientMerged):
                merges += 1
                accepted += int(ev.accepted)
                staleness.append(ev.staleness)
            elif isinstance(ev, TaskEnd):
                task_ms.append(ev.run_ms)
                failures += int(not ev.succeeded)
            elif isinstance(ev, JobEnd):
                failures += int(not ev.succeeded)
            elif isinstance(ev, WorkerLost):
                lost.append(ev.worker_id)
            elif isinstance(ev, ModelSnapshot):
                trajectory.append((ev.time_ms, ev.objective))
        out: Dict[str, object] = {
            "rounds": n_rounds,
            "merges": merges,
            "accepted": accepted,
            "dropped_stale": merges - accepted,
            "workers_lost": lost,
            "task_failures": failures,
            "trajectory": trajectory,
            # torn records skipped by the tolerant replay (crash mid-write)
            "truncated_records": self.truncated_records,
        }
        if staleness:
            from asyncframework_tpu.metrics.system import Histogram

            s = sorted(staleness)
            out["staleness"] = {
                "max": s[-1],
                "mean": sum(s) / len(s),
                # nearest-rank, same rule as Histogram.snapshot (the old
                # int(q*n) indexing reported max as p95 for small logs)
                "p50": Histogram._pct(s, 0.50),
                "p95": Histogram._pct(s, 0.95),
            }
        if task_ms:
            from asyncframework_tpu.metrics.system import Histogram

            t = sorted(task_ms)
            out["task_ms"] = {
                "mean": sum(t) / len(t),
                "p50": Histogram._pct(t, 0.50),
                "max": t[-1],
            }
        return out
