"""JSONL event logging and replay (history-server analog).

Parity: ``EventLoggingListener`` (``scheduler/EventLoggingListener.scala:55``)
writes one JSON object per line per event; the history server's
``FsHistoryProvider`` replays the file to rebuild application state.  Here
:class:`EventLogWriter` is a bus listener streaming events to a JSONL file and
:class:`EventLogReader` replays a file back into typed events and summary
statistics (the ``AppStatusStore`` role, trimmed to this framework's event
vocabulary: rounds, merges, staleness distribution, worker health).
"""

from __future__ import annotations

import gzip
import json
import threading
from dataclasses import asdict
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Union

from asyncframework_tpu.metrics.bus import EVENT_TYPES, Event, Listener


def _open_log(path: Path, mode: str):
    """``.gz`` paths route through the zlib codec (the reference compresses
    event logs with its native lz4/zstd codecs --
    ``io/CompressionCodec.scala``; CPython's zlib is the native codec here)."""
    if path.suffix == ".gz":
        return gzip.open(path, mode + "t", encoding="utf-8")
    return path.open(mode, buffering=1 if "w" in mode else -1)


class EventLogWriter(Listener):
    """Streams every bus event to a JSONL file (``.gz`` = compressed);
    one line per event."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = _open_log(self.path, "w")
        self._lock = threading.Lock()
        self._closed = False

    def on_event(self, event: Event) -> None:
        rec = {"event": type(event).__name__, **asdict(event)}
        line = json.dumps(rec, separators=(",", ":"), default=_jsonable)
        with self._lock:
            if not self._closed:
                self._f.write(line + "\n")
                # flush per event: the log is a crash-forensics artifact, and
                # the gzip stream would otherwise buffer everything to close()
                self._f.flush()

    # per-type hooks all route to on_event for the writer
    def __getattr__(self, name: str):
        if name.startswith("on_"):
            return self.on_event
        raise AttributeError(name)

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()


def _jsonable(o):
    if isinstance(o, (tuple, set)):
        return list(o)
    return str(o)


class EventLogReader:
    """Replays a JSONL event log into typed events + summary statistics."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)

    @staticmethod
    def _lines(f) -> Iterator[str]:
        """Line iteration tolerating a crash-torn tail: a writer that died
        before close() leaves a gzip stream without its end marker; every
        fully-flushed line before the tear still replays."""
        try:
            yield from f
        except EOFError:
            return

    def replay(self, strict: bool = True) -> Iterator[Event]:
        """Yield events; with ``strict=False`` a torn tail (crash mid-write)
        ends the replay at the last valid line instead of raising -- the
        history server's inspect-a-dead-run case."""
        with _open_log(self.path, "r") as f:
            for line in self._lines(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    if strict:
                        raise
                    return  # torn tail: the valid prefix stands
                name = rec.pop("event", None)
                cls = EVENT_TYPES.get(name)
                if cls is None:
                    continue  # unknown event type: forward-compat skip
                fields = {
                    k: (tuple(v) if isinstance(v, list) else v)
                    for k, v in rec.items()
                }
                try:
                    yield cls(**fields)
                except TypeError:
                    continue  # schema drift: skip unreadable record

    def summary(self) -> Dict[str, object]:
        """History-server style aggregate view of one run's log."""
        from asyncframework_tpu.metrics.bus import (
            GradientMerged,
            JobEnd,
            ModelSnapshot,
            RoundSubmitted,
            TaskEnd,
            WorkerLost,
        )

        n_rounds = 0
        merges = 0
        accepted = 0
        staleness: List[int] = []
        task_ms: List[float] = []
        failures = 0
        lost: List[int] = []
        trajectory: List[tuple] = []
        for ev in self.replay():
            if isinstance(ev, RoundSubmitted):
                n_rounds += 1
            elif isinstance(ev, GradientMerged):
                merges += 1
                accepted += int(ev.accepted)
                staleness.append(ev.staleness)
            elif isinstance(ev, TaskEnd):
                task_ms.append(ev.run_ms)
                failures += int(not ev.succeeded)
            elif isinstance(ev, JobEnd):
                failures += int(not ev.succeeded)
            elif isinstance(ev, WorkerLost):
                lost.append(ev.worker_id)
            elif isinstance(ev, ModelSnapshot):
                trajectory.append((ev.time_ms, ev.objective))
        out: Dict[str, object] = {
            "rounds": n_rounds,
            "merges": merges,
            "accepted": accepted,
            "dropped_stale": merges - accepted,
            "workers_lost": lost,
            "task_failures": failures,
            "trajectory": trajectory,
        }
        if staleness:
            s = sorted(staleness)
            out["staleness"] = {
                "max": s[-1],
                "mean": sum(s) / len(s),
                "p50": s[len(s) // 2],
                "p95": s[min(len(s) - 1, int(0.95 * len(s)))],
            }
        if task_ms:
            t = sorted(task_ms)
            out["task_ms"] = {
                "mean": sum(t) / len(t),
                "p50": t[len(t) // 2],
                "max": t[-1],
            }
        return out
