"""Typed event stream with an asynchronous listener bus.

Parity: ``SparkListenerEvent`` case classes + ``LiveListenerBus``
(``scheduler/LiveListenerBus.scala:44``): producers post from hot threads;
a dispatch thread fans events out to registered listeners; the queue is
bounded and *drops* (counting) rather than blocking the producer when a slow
listener falls behind -- exactly the reference's drop-and-log policy.

The event vocabulary is this framework's: training rounds, gradient merges
(with staleness), model snapshots, worker loss -- the observable facts of the
async parameter-server loop, not Spark's stage/RDD taxonomy.
"""

from __future__ import annotations

import functools
import queue
import threading
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type


@dataclass(frozen=True)
class Event:
    time_ms: float


@dataclass(frozen=True)
class JobStart(Event):
    job_id: int
    worker_ids: tuple


@dataclass(frozen=True)
class JobEnd(Event):
    job_id: int
    succeeded: bool
    error: Optional[str] = None


@dataclass(frozen=True)
class TaskEnd(Event):
    job_id: int
    worker_id: int
    attempt: int
    run_ms: float
    succeeded: bool
    error: Optional[str] = None


@dataclass(frozen=True)
class RoundSubmitted(Event):
    round_idx: int
    cohort: tuple
    model_version: int


@dataclass(frozen=True)
class GradientMerged(Event):
    worker_id: int
    staleness: int
    accepted: bool
    iteration: int
    batch_size: int = 0


@dataclass(frozen=True)
class ModelSnapshot(Event):
    iteration: int
    objective: float


@dataclass(frozen=True)
class WorkerLost(Event):
    worker_id: int
    reason: str


@dataclass(frozen=True)
class ShardMoved(Event):
    """Elastic recovery re-homed a data shard (engine/recovery.py)."""

    shard_id: int
    new_owner: int
    device: str


@dataclass(frozen=True)
class SpeculativeLaunch(Event):
    """A speculative task copy was launched (engine/speculation.py)."""

    job_id: int
    worker_id: int


@dataclass(frozen=True)
class TraceSpan(Event):
    """One completed lifecycle-stage span of a traced update
    (metrics/trace.py): pull.wait / pull.rtt / compute / push.wait /
    push.rtt / merge.queue / merge.apply.  ``start_ms`` is wall-clock epoch
    milliseconds (cross-process comparable; ``time_ms`` stays the posting
    process's run-relative clock like every other event)."""

    stage: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    worker_id: int
    model_version: int
    start_ms: float
    dur_ms: float
    staleness: Optional[int] = None
    staleness_ms: Optional[float] = None
    accepted: Optional[bool] = None
    bytes: Optional[int] = None  # wire bytes of the RPC the span covers


EVENT_TYPES: Dict[str, Type[Event]] = {
    cls.__name__: cls
    for cls in (
        JobStart, JobEnd, TaskEnd, RoundSubmitted, GradientMerged,
        ModelSnapshot, WorkerLost, ShardMoved, SpeculativeLaunch, TraceSpan,
    )
}


class Listener:
    """Override ``on_event`` (catch-all) or per-type ``on_<snake_name>``."""

    def on_event(self, event: Event) -> None:  # pragma: no cover - default
        pass


@functools.lru_cache(maxsize=None)
def _snake(name: str) -> str:
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i > 0:
            out.append("_")
        out.append(c.lower())
    return "".join(out)


class ListenerBus:
    """Bounded async fan-out bus.

    ``post`` never blocks the producer: when the queue is full the event is
    dropped and counted (``dropped_events``), matching ``LiveListenerBus``'s
    behavior under backpressure.  ``stop`` drains what is queued.
    """

    def __init__(self, capacity: int = 10_000):
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue(capacity)
        self._listeners: List[Listener] = []
        self._lock = threading.Lock()
        self.dropped_events = 0
        self.posted_events = 0
        self._started = False
        self._stop_requested = False
        self._thread: Optional[threading.Thread] = None

    def add_listener(self, listener: Listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener: Listener) -> None:
        with self._lock:
            self._listeners.remove(listener)

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="listener-bus", daemon=True
        )
        self._thread.start()

    def post(self, event: Event) -> None:
        self.posted_events += 1
        if not self._started:
            self._deliver(event)  # synchronous mode (tests, simple tools)
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            self.dropped_events += 1

    def stop(self, timeout: float = 5.0) -> None:
        """Drain and stop.  Never blocks past ``timeout``: if the queue is
        full behind a wedged listener the sentinel is skipped (the dispatch
        loop also polls the stop flag) and the daemon thread is abandoned
        after the join timeout -- stop must obey the same never-block policy
        as post."""
        if not self._started:
            return
        self._stop_requested = True
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._started = False
        self._stop_requested = False

    # ------------------------------------------------------------- internals
    def _dispatch_loop(self) -> None:
        while True:
            try:
                ev = self._queue.get(timeout=0.1)
            except queue.Empty:
                if self._stop_requested:
                    return
                continue
            if ev is None:
                return
            self._deliver(ev)

    def _deliver(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        hook = "on_" + _snake(type(event).__name__)
        for lst in listeners:
            try:
                fn = getattr(lst, hook, None)
                if fn is not None:
                    fn(event)
                else:
                    lst.on_event(event)
            except Exception:  # noqa: BLE001 - a bad listener must not kill the bus
                pass
