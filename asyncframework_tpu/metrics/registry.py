"""Counter-family registry: the ONE list of process-global counter dicts.

Before this existed, each observability subsystem (net retries, wire
bytes, elastic recovery, shuffle spill, pipelined-loop counters, serving
QPS, ...) exported its own module-level ``*_totals()`` /
``reset_*_totals()`` pair, and THREE consumers had to enumerate them by
hand: ``metrics.reset_totals()`` (per-run isolation),
``metrics/live.LiveStateListener`` (per-run delta baselines, so a second
run's dashboard does not inherit the first run's counts), and now the
time-series sampler (``metrics/timeseries.py``) and the Prometheus
exposition (``metrics/prom.py``).  A family added to one list but
forgotten in another only surfaced as a flaky "second run inherits
counts" bug.  This module is the fix: every family is declared ONCE
here, every consumer iterates :func:`families`, and a tier-1 audit test
(``tests/test_telemetry.py``) introspects the package for stray
``*_totals`` providers that are not registered.

A family's ``totals`` must be a zero-arg callable returning a FLAT
``Dict[str, int|float]`` (the live UI's ``_delta`` machinery and the
Prometheus counter mapping both require flat numerics); ``reset`` zeroes
it.  ``high_water`` names keys that are maxima rather than monotone
counts -- per-run delta subtraction does not apply to them (the live UI
shows them raw, and the sampler's ``rate()`` is meaningless on them).
``baseline=False`` marks meta-families (the telemetry plane's own
counters) that the live UI does not delta-baseline.

Providers are referenced by (module, attr) strings and resolved lazily:
importing this registry must not import jax-heavy modules.
"""

from __future__ import annotations

import importlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, Tuple


@dataclass(frozen=True)
class CounterFamily:
    """One process-global flat counter dict and its reset."""

    name: str
    module: str        # dotted module owning the provider functions
    totals_attr: str   # zero-arg callable -> Dict[str, int|float]
    reset_attr: str    # zero-arg callable zeroing the totals
    high_water: Tuple[str, ...] = ()
    baseline: bool = True  # live UI captures a per-run delta baseline
    doc: str = ""

    def _resolve(self, attr: str) -> Callable:
        return getattr(importlib.import_module(self.module), attr)

    def totals(self) -> Dict[str, float]:
        return self._resolve(self.totals_attr)()

    def reset(self) -> None:
        self._resolve(self.reset_attr)()


_FAMILIES: "OrderedDict[str, CounterFamily]" = OrderedDict()


def _register(fam: CounterFamily) -> None:
    _FAMILIES[fam.name] = fam


def families() -> "OrderedDict[str, CounterFamily]":
    return OrderedDict(_FAMILIES)


def totals(name: str) -> Dict[str, float]:
    return _FAMILIES[name].totals()


def all_totals() -> "OrderedDict[str, Dict[str, float]]":
    """Every family's flat totals, registration order (the sampler's and
    the Prometheus exposition's walk)."""
    return OrderedDict((n, f.totals()) for n, f in _FAMILIES.items())


def reset_all() -> None:
    """Zero every registered family (``metrics.reset_totals`` core)."""
    for fam in _FAMILIES.values():
        fam.reset()


# --------------------------------------------------------------------------
# The families.  Order is presentation order (live UI, /metrics).
# --------------------------------------------------------------------------
_register(CounterFamily(
    "net", "asyncframework_tpu.net", "net_totals", "reset_net_totals",
    doc="DCN robustness: retries, breaker trips, dedup hits, faults "
        "fired (net/retry.py, net/session.py, net/faults.py).",
))
_register(CounterFamily(
    "net_bytes", "asyncframework_tpu.net.frame",
    "bytes_totals", "reset_bytes_totals",
    doc="Per-op frame bytes sent/received at the net/frame.py choke "
        "point (also zeroed by reset_net_totals; resets are idempotent).",
))
_register(CounterFamily(
    "recovery", "asyncframework_tpu.parallel.supervisor",
    "recovery_totals", "reset_recovery_totals",
    doc="Elastic plane: workers lost, shards adopted, rejoins, "
        "releases, PS resumes, plus the partition-tolerant membership "
        "counters -- suspicions, lease expiries, fencing-epoch bumps, "
        "fenced rejects (parallel/supervisor.py).",
))
_register(CounterFamily(
    "gray", "asyncframework_tpu.net.health",
    "gray_totals", "reset_gray_totals",
    doc="Gray-failure detection: latency-suspicion transitions "
        "(net/health.py RttSuspector).",
))
_register(CounterFamily(
    "shuffle", "asyncframework_tpu.data.spill",
    "shuffle_totals", "reset_shuffle_totals",
    doc="Driver-side shuffle routing/spill accounting (data/spill.py).",
))
_register(CounterFamily(
    "pipeline", "asyncframework_tpu.parallel.ps_dcn",
    "pipeline_totals", "reset_pipeline_totals",
    high_water=("inflight_max",),
    doc="Pipelined update loop: prefetch hits/waits, stale discards, "
        "async pushes, push errors; inflight_max is a high-water mark.",
))
_register(CounterFamily(
    "serving", "asyncframework_tpu.serving.metrics",
    "serving_totals", "reset_serving_totals",
    doc="Serving plane: predicts, failovers, unhealthy rejects, "
        "refresh shapes (serving/metrics.py).",
))
_register(CounterFamily(
    "relay", "asyncframework_tpu.relaycast.metrics",
    "relay_totals", "reset_relay_totals",
    doc="Relaycast distribution plane: fetches served per shape, "
        "offers sent/received, parent fetches vs root fallbacks, "
        "re-homes, fenced hops, CRC rejects "
        "(asyncframework_tpu/relaycast/).",
))
_register(CounterFamily(
    "codec", "asyncframework_tpu.net.wirecodec",
    "codec_totals", "reset_codec_totals",
    doc="Wire codecs: quantized-gradient encodes/decodes and raw "
        "fallbacks, raw-vs-wire byte totals, snapshot-delta "
        "compression hits (net/wirecodec.py).",
))
_register(CounterFamily(
    "native", "asyncframework_tpu.native_build",
    "native_totals", "reset_native_totals",
    doc="Native data plane: native vs Python codec dispatches per unit "
        "(native_calls.<unit>/python_calls.<unit>), wanted-but-missing "
        "fallbacks (python_fallbacks -- nonzero means the box is "
        "silently running the slow path), and the shm-ring transport's "
        "upgrades/refusals/degrades plus frame/byte flow "
        "(native_build.py, net/shmring.py).",
))
_register(CounterFamily(
    "shardgroup", "asyncframework_tpu.parallel.shardgroup",
    "shard_totals", "reset_shard_totals",
    doc="Sharded PS group: shard deaths/restarts, standby promotions/"
        "respawns, finish broadcasts, assembled pulls/pushes, map "
        "re-resolves, abandoned fan-out rounds "
        "(parallel/shardgroup.py).",
))
_register(CounterFamily(
    "replication", "asyncframework_tpu.parallel.replication",
    "repl_totals", "reset_repl_totals",
    doc="Hot-standby replication: batches/items streamed, syncs, "
        "resyncs, reconnects, queue overflows, fenced streams "
        "(primary sender); appends applied, sync installs, promotions "
        "(standby applier) (parallel/replication.py).",
))
_register(CounterFamily(
    "control", "asyncframework_tpu.parallel.controller",
    "control_totals", "reset_control_totals",
    doc="Adaptive asynchrony controller: decision ticks, knob changes "
        "(the controller_converged SLO watches their rate), bound "
        "clamps, oscillation-guard trips, stale CTRL installs refused "
        "(parallel/controller.py).",
))
_register(CounterFamily(
    "observer", "asyncframework_tpu.metrics.observer",
    "observer_totals", "reset_observer_totals",
    doc="Cluster observer: scrapes, scrape errors, roles discovered, "
        "flight dumps harvested, history persists, stragglers flagged "
        "(metrics/observer.py).",
))
_register(CounterFamily(
    "flight", "asyncframework_tpu.metrics.flightrec",
    "flight_totals", "reset_flight_totals",
    baseline=False,
    doc="Crash flight recorder meta-counters: events noted/dropped, "
        "cadence flushes, dumps written (metrics/flightrec.py).",
))
_register(CounterFamily(
    "profile", "asyncframework_tpu.metrics.profiler",
    "profile_totals", "reset_profile_totals",
    baseline=False,
    doc="Continuous profiling plane: stack samples total and per zone "
        "(samples.<zone>), exact zone nanoseconds/calls "
        "(zone_ns.<zone>/zone_calls.<zone>), jit compile/dispatch "
        "count+ns, dropped distinct stacks, sampler errors "
        "(metrics/profiler.py).  Empty while async.prof.enabled=0.",
))
_register(CounterFamily(
    "convergence", "asyncframework_tpu.metrics.timeseries",
    "convergence_totals", "reset_convergence",
    baseline=False,
    doc="Convergence telemetry meta-counters: samples folded, "
        "piggybacks received, compactions (metrics/timeseries.py).",
))
_register(CounterFamily(
    "timeseries", "asyncframework_tpu.metrics.timeseries",
    "timeseries_totals", "reset_timeseries",
    baseline=False,
    doc="Time-series store meta-counters: samples recorded, series "
        "live, evictions (metrics/timeseries.py).",
))


# --------------------------------------------------------------------------
# Series-family declarations.  Every time-series key written anywhere
# must parse as ``family.metric`` with the family declared here: either
# a counter family above (the sampler records each one under its own
# name) or one of the DYNAMIC source families below (register_source
# callers).  ``bin/async-lint`` enforces this statically
# (metrics-series-family, analysis/rules_metrics.py) -- the static twin
# of the runtime registration audit in tests/test_telemetry.py.
# --------------------------------------------------------------------------
#: dynamic register_source() families beside the counter families: the
#: PS core scalars, the shard-group controller, the always-on derived
#: sources (timeseries._builtin_sources), the cluster observer's
#: derived fleet signals, and the MetricsSystem queue-depth source.
DYNAMIC_SERIES_FAMILIES = (
    "ps", "ps_shards", "serving", "trace", "convergence", "observer",
    "queue",
)


def series_families() -> tuple:
    """Every declared series family name: counter families plus the
    dynamic source families (the metrics-series-family lint's table)."""
    return tuple(_FAMILIES) + DYNAMIC_SERIES_FAMILIES
