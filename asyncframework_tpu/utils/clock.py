"""Clock abstraction for deterministic tests.

Parity: the reference's streaming suites inject a ``ManualClock`` to make
time-driven logic deterministic (SURVEY.md section 4); the engine here takes a
:class:`Clock` everywhere it would otherwise read wall time, so scheduler and
heartbeat tests run with virtual time.
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now_ms(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait_for(self, event: "threading.Event", seconds: float) -> bool:
        """Sleep up to ``seconds`` but wake early when ``event`` is set.

        Returns True iff the event was set.  Periodic loops must use this
        instead of :meth:`sleep` so shutdown can interrupt them (a ManualClock
        ``sleep`` blocks until virtual time advances, which at shutdown it
        never does).
        """
        raise NotImplementedError


class SystemClock(Clock):
    def now_ms(self) -> float:
        return time.monotonic() * 1000.0

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait_for(self, event: "threading.Event", seconds: float) -> bool:
        return event.wait(timeout=seconds)


class ManualClock(Clock):
    """Virtual clock advanced explicitly by the test."""

    def __init__(self, start_ms: float = 0.0):
        self._ms = start_ms
        self._cond = threading.Condition()

    def now_ms(self) -> float:
        with self._cond:
            return self._ms

    def advance(self, ms: float) -> None:
        with self._cond:
            self._ms += ms
            self._cond.notify_all()

    def sleep(self, seconds: float) -> None:
        """Blocks until the clock is advanced past the deadline."""
        with self._cond:
            deadline = self._ms + seconds * 1000.0
            while self._ms < deadline:
                self._cond.wait(timeout=1.0)

    def wait_for(self, event, seconds: float) -> bool:
        """Virtual-time sleep that also wakes (promptly) on ``event``."""
        with self._cond:
            deadline = self._ms + seconds * 1000.0
            while self._ms < deadline:
                if event.is_set():
                    return True
                self._cond.wait(timeout=0.05)
        return event.is_set()
