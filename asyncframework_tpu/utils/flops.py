"""Achieved-FLOP/s and MFU accounting.

The reference never reports compute efficiency (its metric is wall-clock to
target loss); the TPU build records it so "matching-or-beating on perf"
carries an absolute number: solvers count the flops of every worker gradient
they merge, and the bench divides by elapsed time and the chip's peak.

Flop model (counted, not estimated): a dense worker step is two matmuls over
the full shard -- residual ``X @ w`` and gradient ``X^T @ (mask*r)`` -- i.e.
``4 * n_p * d`` flops (2 per multiply-add).  A sparse (padded-ELL) step is the
gather/scatter pair at ``4 * n_p * K`` (padding lanes execute real FMAs).  The
trajectory evaluation runs outside the timed region and is not counted.

Peak table: dense matmul peak per chip for bf16 inputs (MXU native; the
industry-standard MFU denominator).  f32 runs are still divided by the bf16
peak -- that is deliberate: MFU answers "what fraction of the chip's usable
matmul throughput did the run extract", and on TPU the usable peak IS the
bf16 MXU rate (f32 matmuls lower to multi-pass bf16).
"""

from __future__ import annotations

from typing import Optional

#: dense-matmul peak FLOP/s per chip by device_kind substring (public specs)
_PEAK_BF16 = (
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),        # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def chip_peak_flops(device) -> Optional[float]:
    """Best-effort bf16 dense-matmul peak for ``device``; None if unknown
    (CPU backends have no meaningful MXU peak -- MFU is reported null)."""
    kind = str(getattr(device, "device_kind", "")).lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    return 197e12 if kind else None  # unknown TPU: assume the v5e floor


def dense_task_flops(n_rows: int, d: int) -> float:
    """Flops of one dense worker gradient over an ``(n_rows, d)`` shard."""
    return 4.0 * n_rows * d


def sparse_task_flops(n_rows: int, k_padded: int) -> float:
    """Flops of one padded-ELL worker gradient (gather + scatter lanes)."""
    return 4.0 * n_rows * k_padded


def mfu(total_flops: float, elapsed_s: float, device) -> Optional[float]:
    """Model FLOP utilization in [0, 1]; None when the peak is unknown."""
    peak = chip_peak_flops(device)
    if peak is None or elapsed_s <= 0:
        return None
    return total_flops / elapsed_s / peak
