"""The repo's thread exception policy for fire-and-forget threads.

``async-lint``'s thread-hygiene rule requires every
``threading.Thread(...)`` site to name the thread, set daemonness
explicitly, and either RETAIN the thread object (someone can
join/reap/health-check it) or wrap its target here.  A fire-and-forget
thread whose target raises otherwise dies with a traceback on stderr at
best and silently at worst -- the PR 5-class reap gap, but for errors.

:func:`guarded` is deliberately tiny: log the exception loudly (both the
package logger and stderr -- daemons often run without logging
configured) and swallow it.  Threads that need richer policies (restart,
counters, supervision) should be retained and owned instead.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Any, Callable

_log = logging.getLogger("asyncframework_tpu.threads")


def guarded(fn: Callable[..., Any], what: str = "") -> Callable[..., None]:
    """Wrap a thread target so an escaping exception is reported, not
    swallowed by thread teardown.  ``what`` names the work in the report
    (defaults to the function's name)."""
    label = what or getattr(fn, "__name__", "thread target")

    def _run(*args: Any, **kwargs: Any) -> None:
        try:
            fn(*args, **kwargs)
        except Exception:  # noqa: BLE001 - the policy IS catch-everything
            _log.exception("unhandled exception in thread %r "
                           "(thread=%s)", label,
                           threading.current_thread().name)
            print(f"asyncframework_tpu: unhandled exception in thread "
                  f"{label!r} ({threading.current_thread().name})",
                  file=sys.stderr, flush=True)
            import traceback

            traceback.print_exc()

    _run.__name__ = f"guarded_{getattr(fn, '__name__', 'target')}"
    return _run
