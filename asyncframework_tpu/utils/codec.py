"""AZ1 block compression: C++ fast path + pure-Python fallback.

Role parity: the reference reaches lz4/snappy/zstd through JNI for shuffle,
broadcast, and event-log bytes (``core/.../io/CompressionCodec.scala:113``).
AZ1 is this framework's native codec -- an original LZ77-family block format
(greedy hash matching, byte-aligned tokens; see ``native/codec.cc`` for the
format spec).  Both backends produce interchangeable blocks and both
decoders are bounds-checked against hostile input.

Consumers: the write-ahead log's ``compress=True`` mode
(``streaming/wal.py``); any host blob can use :func:`compress` /
:func:`decompress` directly.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_MIN_MATCH = 4
_MAX_LIT = 0x7F
_MAX_MATCH = 0x7F + _MIN_MATCH
_MAX_OFFSET = 0xFFFF
_HASH_BITS = 15
_HASH_MUL = 2654435761

_NATIVE = None

#: native symbol -> pure-Python twin (native-oracle lint contract:
#: both backends produce interchangeable blocks, tests/test_codec.py)
NATIVE_ORACLES = {
    "az1_compress": "_py_compress",
    "az1_decompress": "_py_decompress",
    "az1_max_compressed_size": "max_compressed_size",
}


def _native_lib():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    lib = None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("codec")
        if built and os.path.exists(built):
            lib = ctypes.CDLL(built)
            lib.az1_max_compressed_size.restype = ctypes.c_longlong
            lib.az1_max_compressed_size.argtypes = [ctypes.c_longlong]
            lib.az1_compress.restype = ctypes.c_longlong
            lib.az1_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong,
            ]
            lib.az1_decompress.restype = ctypes.c_longlong
            lib.az1_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong,
            ]
    except Exception:  # noqa: BLE001 - fall back to Python
        lib = None
    _NATIVE = lib or False
    return lib


def max_compressed_size(n: int) -> int:
    return 4 + n + (n // _MAX_LIT + 1)


# ------------------------------------------------------------------ python
def _hash4(b: bytes, i: int) -> int:
    v = int.from_bytes(b[i : i + 4], "little")
    return ((v * _HASH_MUL) & 0xFFFFFFFF) >> (32 - _HASH_BITS)


def _py_compress(src: bytes) -> bytes:
    n = len(src)
    out = bytearray(n.to_bytes(4, "little"))
    table = [-1] * (1 << _HASH_BITS)
    i = 0
    lit_start = 0

    def flush(upto: int) -> None:
        nonlocal lit_start
        while lit_start < upto:
            run = min(upto - lit_start, _MAX_LIT)
            out.append(run)
            out.extend(src[lit_start : lit_start + run])
            lit_start += run

    while i + _MIN_MATCH <= n:
        h = _hash4(src, i)
        cand = table[h]
        table[h] = i
        if (
            cand >= 0
            and i - cand <= _MAX_OFFSET
            and src[cand : cand + _MIN_MATCH] == src[i : i + _MIN_MATCH]
        ):
            length = _MIN_MATCH
            max_len = min(n - i, _MAX_MATCH)
            while length < max_len and src[cand + length] == src[i + length]:
                length += 1
            flush(i)
            out.append(0x80 | (length - _MIN_MATCH))
            out.extend((i - cand).to_bytes(2, "little"))
            stop = i + length - _MIN_MATCH
            j = i + 1
            while j <= stop:
                table[_hash4(src, j)] = j
                j += 1
            i += length
            lit_start = i
        else:
            i += 1
    flush(n)
    return bytes(out)


def _py_decompress(blob: bytes) -> bytes:
    if len(blob) < 4:
        raise ValueError("AZ1: truncated header")
    raw = int.from_bytes(blob[:4], "little")
    out = bytearray()
    i = 4
    n = len(blob)
    while len(out) < raw:
        if i >= n:
            raise ValueError("AZ1: truncated token")
        c = blob[i]
        i += 1
        if c & 0x80:
            length = (c & 0x7F) + _MIN_MATCH
            if i + 2 > n:
                raise ValueError("AZ1: truncated match")
            off = int.from_bytes(blob[i : i + 2], "little")
            i += 2
            if off == 0 or off > len(out):
                raise ValueError("AZ1: bad offset")
            if len(out) + length > raw:
                raise ValueError("AZ1: overlong match")
            start = len(out) - off
            for j in range(length):  # may overlap forward (RLE)
                out.append(out[start + j])
        else:
            if c == 0:
                raise ValueError("AZ1: zero literal run")
            if i + c > n:
                raise ValueError("AZ1: truncated literals")
            if len(out) + c > raw:
                raise ValueError("AZ1: overlong literals")
            out.extend(blob[i : i + c])
            i += c
    if i != n:
        raise ValueError("AZ1: trailing garbage")
    return bytes(out)


# -------------------------------------------------------------------- API
def compress(data: bytes, backend: Optional[str] = None) -> bytes:
    """Compress one block; backend 'native'/'python'/None (auto)."""
    data = bytes(data)
    lib = _native_lib() if backend in (None, "native") else None
    if backend == "native" and lib is None:
        raise RuntimeError("native codec unavailable (build native/codec.cc)")
    if lib is not None:
        cap = max_compressed_size(len(data))
        buf = (ctypes.c_uint8 * cap)()
        got = lib.az1_compress(data, len(data), buf, cap)
        if got < 0:
            raise RuntimeError("AZ1 native compress failed")
        return bytes(bytearray(buf)[:got])
    return _py_compress(data)


def decompress(blob: bytes, backend: Optional[str] = None) -> bytes:
    """Decompress one block (raises ValueError on corrupt input)."""
    blob = bytes(blob)
    if len(blob) < 4:
        raise ValueError("AZ1: truncated header")
    raw = int.from_bytes(blob[:4], "little")
    if raw > 1 << 31:
        raise ValueError("AZ1: implausible raw length")
    lib = _native_lib() if backend in (None, "native") else None
    if backend == "native" and lib is None:
        raise RuntimeError("native codec unavailable (build native/codec.cc)")
    if lib is not None:
        buf = (ctypes.c_uint8 * max(raw, 1))()
        got = lib.az1_decompress(blob, len(blob), buf, raw)
        if got < 0:
            raise ValueError("AZ1: corrupt block")
        return bytes(bytearray(buf)[:got])
    return _py_decompress(blob)
