"""Probabilistic sketches: Count-Min and Bloom filter.

Parity: ``common/sketch`` (Java ``CountMinSketch`` / ``BloomFilter``, used by
SQL stat functions and join planning).  Both are mergeable -- the distributed
usage pattern is per-partition sketches combined on the driver, which is how
``DistributedDataset.aggregate`` consumes them here.

Vectorized NumPy throughout: updates take whole arrays (one hash broadcast
per row batch), not per-item loops.  Hashing is double hashing over two
xxhash-style integer mixes, ``h_i(x) = h1(x) + i * h2(x)`` -- the standard
Kirsch-Mitzenmacher construction the reference's Bloom filter also uses.
"""

from __future__ import annotations

from typing import Iterable, Union

import numpy as np

_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)


def _mix64(x: np.ndarray, seed: int) -> np.ndarray:
    """splitmix64-style avalanche over uint64 arrays."""
    with np.errstate(over="ignore"):
        h = x.astype(np.uint64) + np.uint64(seed) * np.uint64(
            0x9E3779B97F4A7C15
        )
        h ^= h >> np.uint64(33)
        h *= _M1
        h ^= h >> np.uint64(33)
        h *= _M2
        h ^= h >> np.uint64(33)
    return h


def _to_u64(items) -> np.ndarray:
    """Hash item arrays (or scalars) to 1-d uint64: ints pass through,
    floats via bit pattern, strings/bytes via an FNV-1a polynomial hash;
    object arrays dispatch per element by type."""
    a = np.atleast_1d(np.asarray(items))
    if a.dtype.kind in "iu":
        return a.astype(np.uint64)
    if a.dtype.kind == "f":
        return a.astype(np.float64).view(np.uint64)
    if a.dtype.kind in ("U", "S", "O"):
        out = np.empty(a.shape[0], np.uint64)
        with np.errstate(over="ignore"):
            for i, s in enumerate(a):
                if isinstance(s, (int, np.integer)):
                    out[i] = np.uint64(int(s) & 0xFFFFFFFFFFFFFFFF)
                    continue
                if isinstance(s, (float, np.floating)):
                    out[i] = np.asarray(float(s)).view(np.uint64)
                    continue
                if isinstance(s, str):
                    b = s.encode()
                elif isinstance(s, (bytes, np.bytes_)):
                    b = bytes(s)
                else:
                    raise TypeError(f"unhashable item type {type(s)}")
                h = np.uint64(1469598103934665603)
                for byte in b:
                    h = (h ^ np.uint64(byte)) * np.uint64(1099511628211)
                out[i] = h
        return out
    raise TypeError(f"unhashable dtype {a.dtype}")


class CountMinSketch:
    """Approximate frequency counting: overestimates, never underestimates.

    ``depth`` rows of ``width`` counters; estimate = min over rows.
    """

    def __init__(self, depth: int = 5, width: int = 1 << 12, seed: int = 42):
        if depth < 1 or width < 1:
            raise ValueError("depth and width must be >= 1")
        self.depth = depth
        self.width = width
        self.seed = seed
        self.table = np.zeros((depth, width), np.int64)
        self.total = 0

    def _slots(self, items) -> np.ndarray:
        keys = _to_u64(items)
        h1 = _mix64(keys, self.seed)
        h2 = _mix64(keys, self.seed + 1) | np.uint64(1)
        rows = np.arange(self.depth, dtype=np.uint64)[:, None]
        with np.errstate(over="ignore"):
            return ((h1[None, :] + rows * h2[None, :])
                    % np.uint64(self.width)).astype(np.intp)

    def add(self, items, counts: Union[int, np.ndarray] = 1) -> None:
        slots = self._slots(items)  # (depth, n)
        counts = np.broadcast_to(np.asarray(counts, np.int64), slots.shape[1:])
        for r in range(self.depth):
            np.add.at(self.table[r], slots[r], counts)
        self.total += int(counts.sum())

    def estimate(self, items) -> np.ndarray:
        slots = self._slots(items)
        ests = np.stack([self.table[r][slots[r]] for r in range(self.depth)])
        return ests.min(axis=0)

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        if (self.depth, self.width, self.seed) != (
            other.depth, other.width, other.seed
        ):
            raise ValueError("can only merge identically-configured sketches")
        self.table += other.table
        self.total += other.total
        return self


class BloomFilter:
    """Approximate membership: no false negatives, tunable false positives."""

    def __init__(self, capacity: int = 10_000, fpp: float = 0.03,
                 seed: int = 42):
        if not 0 < fpp < 1:
            raise ValueError("fpp must be in (0, 1)")
        # standard sizing: m = -n ln p / ln2^2, k = m/n ln2
        m = int(np.ceil(-capacity * np.log(fpp) / (np.log(2) ** 2)))
        self.num_bits = max(64, m)
        self.num_hashes = max(1, int(round(m / capacity * np.log(2))))
        self.seed = seed
        self.bits = np.zeros((self.num_bits + 63) // 64, np.uint64)

    def _positions(self, items) -> np.ndarray:
        keys = _to_u64(items)
        h1 = _mix64(keys, self.seed)
        h2 = _mix64(keys, self.seed + 1) | np.uint64(1)
        ks = np.arange(self.num_hashes, dtype=np.uint64)[:, None]
        with np.errstate(over="ignore"):
            return ((h1[None, :] + ks * h2[None, :])
                    % np.uint64(self.num_bits)).astype(np.intp)

    def add(self, items) -> None:
        pos = self._positions(items).ravel()
        np.bitwise_or.at(
            self.bits, pos >> 6, np.uint64(1) << (pos & 63).astype(np.uint64)
        )

    def might_contain(self, items) -> np.ndarray:
        pos = self._positions(items)  # (k, n)
        word = self.bits[pos >> 6]
        bit = (word >> (pos & 63).astype(np.uint64)) & np.uint64(1)
        return bit.all(axis=0)

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        if (self.num_bits, self.num_hashes, self.seed) != (
            other.num_bits, other.num_hashes, other.seed
        ):
            raise ValueError("can only merge identically-configured filters")
        self.bits |= other.bits
        return self


class HyperLogLog:
    """Cardinality sketch (``countApproxDistinct``'s engine).

    Parity: the reference uses stream-lib's HyperLogLogPlus
    (``rdd/RDD.scala`` countApproxDistinct); this is a clean classic HLL:
    2^p registers keeping the max leading-zero rank per bucket, harmonic
    mean estimate with small-range linear counting, mergeable by register
    max.  Standard error ~= 1.04 / sqrt(2^p).
    """

    def __init__(self, p: int = 14, seed: int = 42):
        if not 4 <= p <= 18:
            raise ValueError("p must be in [4, 18]")
        self.p = p
        self.m = 1 << p
        self.seed = seed
        self.registers = np.zeros(self.m, np.uint8)
        if self.m >= 128:
            self._alpha = 0.7213 / (1.0 + 1.079 / self.m)
        elif self.m == 64:
            self._alpha = 0.709
        elif self.m == 32:
            self._alpha = 0.697
        else:
            self._alpha = 0.673

    def add(self, items) -> None:
        h = _mix64(_to_u64(items), self.seed)
        bucket = (h >> np.uint64(64 - self.p)).astype(np.int64)
        rest = (h << np.uint64(self.p)) | np.uint64((1 << self.p) - 1)
        # rank = leading zeros of the remaining bits + 1
        lz = np.zeros(len(rest), np.uint8)
        probe = np.uint64(1) << np.uint64(63)
        cur = rest.copy()
        for _ in range(64 - self.p + 1):
            mask = (cur & probe) == 0
            lz[mask] += 1
            cur[mask] = cur[mask] << np.uint64(1)
            if not mask.any():
                break
        rank = lz + 1
        np.maximum.at(self.registers, bucket, rank)

    def estimate(self) -> float:
        regs = self.registers.astype(np.float64)
        raw = self._alpha * self.m * self.m / np.sum(2.0 ** (-regs))
        zeros = int(np.count_nonzero(self.registers == 0))
        if raw <= 2.5 * self.m and zeros:
            return float(self.m * np.log(self.m / zeros))  # linear counting
        return float(raw)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        if other.p != self.p or other.seed != self.seed:
            raise ValueError("can only merge HLLs with identical (p, seed)")
        self.registers = np.maximum(self.registers, other.registers)
        return self

    @property
    def relative_error(self) -> float:
        return 1.04 / np.sqrt(self.m)
