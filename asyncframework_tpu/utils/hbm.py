"""HBM budget planning helpers.

Parity: the reference's ``UnifiedMemoryManager`` (``memory/
UnifiedMemoryManager.scala:47``) arbitrates execution vs storage memory and
decides spill; on TPU the XLA allocator owns HBM, so the useful capability
is *planning*: will this dataset + model + history table fit per device, and
how many workers per device keep it that way.  Used by the data layer before
committing shards to HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax

#: conservative default per-chip budget when the runtime reports nothing
DEFAULT_HBM_BYTES = 16 * 1024**3


def nbytes(shape: Sequence[int], dtype=np.float32) -> int:
    return int(np.prod(shape)) * np.dtype(dtype).itemsize


def device_hbm_bytes(device=None) -> int:
    """Best-effort total HBM of a device; falls back to a conservative
    default (CPU/interpret backends report nothing useful)."""
    dev = device or jax.devices()[0]
    stats = {}
    try:
        stats = dev.memory_stats() or {}
    except (AttributeError, NotImplementedError, jax.errors.JaxRuntimeError):
        pass
    limit = stats.get("bytes_limit")
    return int(limit) if limit else DEFAULT_HBM_BYTES


def device_hbm_in_use(device=None) -> Optional[int]:
    dev = device or jax.devices()[0]
    try:
        stats = dev.memory_stats() or {}
    except (AttributeError, NotImplementedError, jax.errors.JaxRuntimeError):
        return None
    used = stats.get("bytes_in_use")
    return int(used) if used is not None else None


@dataclass(frozen=True)
class ShardPlan:
    """Outcome of :func:`plan_dataset`: per-device residency estimate."""

    bytes_per_device: int
    budget_bytes: int
    fits: bool
    utilization: float

    def require_fits(self) -> "ShardPlan":
        if not self.fits:
            raise MemoryError(
                f"planned shard residency {self.bytes_per_device / 1e9:.2f} GB "
                f"exceeds the {self.budget_bytes / 1e9:.2f} GB device budget"
            )
        return self


def plan_dataset(
    n: int,
    d: int,
    num_workers: int,
    num_devices: int,
    dtype=np.float32,
    with_labels: bool = True,
    history_table: bool = False,
    model_versions: int = 2,
    budget_bytes: Optional[int] = None,
    headroom: float = 0.85,
) -> ShardPlan:
    """Estimate per-device HBM residency for a sharded training setup.

    Accounts for: the data shards living on the device (workers sharing a
    device stack their shards), labels, the ASAGA history slice (one f32 per
    sample) when ``history_table``, and ``model_versions`` live copies of
    ``w`` (the versioned broadcast ring).  ``headroom`` reserves a fraction
    of the budget for XLA workspace/fusion temporaries.
    """
    if num_devices < 1 or num_workers < 1:
        raise ValueError("num_workers and num_devices must be >= 1")
    budget = budget_bytes if budget_bytes is not None else device_hbm_bytes()
    workers_per_device = -(-num_workers // num_devices)  # ceil
    rows_per_worker = -(-n // num_workers)
    per_worker = nbytes((rows_per_worker, d), dtype)
    if with_labels:
        per_worker += nbytes((rows_per_worker,), dtype)
    if history_table:
        per_worker += nbytes((rows_per_worker,), np.float32)
    total = workers_per_device * per_worker
    total += model_versions * nbytes((d,), np.float32)
    usable = int(budget * headroom)
    return ShardPlan(
        bytes_per_device=int(total),
        budget_bytes=usable,
        fits=total <= usable,
        utilization=total / usable if usable else float("inf"),
    )


def dataset_residency_bytes(ds) -> Dict[object, int]:
    """Actual per-device bytes of an already-placed sharded dataset
    (dense or sparse): what the shards occupy in each device's HBM."""
    per_dev: Dict[object, int] = {}
    for wid in range(ds.num_workers):
        s = ds.shard(wid)
        arrays = (
            (s.cols, s.vals, s.y) if hasattr(s, "cols") else (s.X, s.y)
        )
        dev = arrays[0].device
        per_dev[dev] = per_dev.get(dev, 0) + sum(
            int(np.prod(a.shape)) * a.dtype.itemsize for a in arrays
        )
    return per_dev


def plan_for_run(
    ds_or_shape,
    num_workers: int,
    num_devices: int,
    history_table: bool = False,
    model_versions: int = 2,
    budget_bytes: Optional[int] = None,
    headroom: float = 0.85,
) -> ShardPlan:
    """Placement plan for one training run.

    ``ds_or_shape`` is either a *placed* dataset (actual residency measured
    from its shards) or an ``(n, d)`` tuple for data not yet placed (planned
    from shapes).  Solvers call this before training and fail fast via
    :meth:`ShardPlan.require_fits` when the budget is oversubscribed.
    """
    if isinstance(ds_or_shape, tuple):
        n, d = ds_or_shape
        return plan_dataset(
            n, d, num_workers, num_devices,
            history_table=history_table, model_versions=model_versions,
            budget_bytes=budget_bytes, headroom=headroom,
        )
    ds = ds_or_shape
    budget = budget_bytes if budget_bytes is not None else device_hbm_bytes()
    per_dev = dataset_residency_bytes(ds)
    worst = max(per_dev.values()) if per_dev else 0
    extra = model_versions * nbytes((ds.d,), np.float32)
    if history_table:
        # one slice per WORKER; workers sharing a device stack their slices
        workers_per_device = -(-num_workers // num_devices)
        extra += workers_per_device * nbytes(
            (-(-ds.n // num_workers),), np.float32
        )
    total = worst + extra
    usable = int(budget * headroom)
    return ShardPlan(
        bytes_per_device=int(total),
        budget_bytes=usable,
        fits=total <= usable,
        utilization=total / usable if usable else float("inf"),
    )


def fmt_bytes(b: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{b} B"
        b /= 1024
    return f"{b:.1f} TiB"  # pragma: no cover
