"""Local multi-process cluster launcher.

Parity: ``deploy/LocalSparkCluster.scala:36`` -- the reference's
single-machine REAL cluster (actual Master/Worker processes, actual RPC,
no fake backends), used both as a test rig and a demo.  The TPU-native
analog: N OS processes on one machine joined through ``jax.distributed``
(loopback gRPC = the DCN control plane), each seeing the global device set;
the same mesh/``shard_map`` code that rides ICI in a slice rides the
process boundary here.

Every process runs the stock CLI (``asyncframework_tpu.cli``) with the
bring-up env vars set (``ASYNCTPU_COORDINATOR`` / ``ASYNCTPU_NUM_PROCESSES``
/ ``ASYNCTPU_PROCESS_ID``), so a recipe that works single-process works on
the cluster unchanged.  Two multi-process modes:

- ``sgd-mllib``: SPMD over a ``jax.distributed`` global mesh (collectives
  ride the loopback DCN);
- ``asgd``: the DCN parameter server (``parallel/ps_dcn.py``) -- process 0
  runs the PS (the driver IS the server, across the process boundary),
  the rest push tau-stamped gradients to it over TCP.

CLI: ``bin/async-cluster <N> [--devices-per-process K] -- <cli args...>``
e.g. ``bin/async-cluster 2 -- sgd-mllib synthetic synthetic 64 4096 8 100
1.0 0 0.5 0.5 25 0 42``
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Optional, Tuple


def _free_port() -> int:
    from asyncframework_tpu.net.frame import free_port

    return free_port()


def launch_local_cluster(
    num_processes: int,
    cli_args: List[str],
    devices_per_process: int = 2,
    timeout_s: float = 300.0,
    platform: str = "cpu",
) -> Tuple[int, List[str]]:
    """Spawn ``num_processes`` CLI processes joined via ``jax.distributed``.

    Returns ``(worst_returncode, [process-0 stdout lines])``.  Process 0's
    output is the run's output (every process computes identical results --
    SPMD); other processes' stdout is suppressed unless they fail.

    ``platform="cpu"`` forces ``devices_per_process`` virtual CPU devices
    per process (the LocalSparkCluster test-rig mode, no TPU needed); pass
    ``platform=None`` on real multi-host TPU deployments where each
    process owns its local chips.
    """
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = []
    for pid in range(num_processes):
        env = dict(os.environ)
        env["ASYNCTPU_COORDINATOR"] = coord
        env["ASYNCTPU_NUM_PROCESSES"] = str(num_processes)
        env["ASYNCTPU_PROCESS_ID"] = str(pid)
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["ASYNCTPU_FORCE_CPU"] = "1"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{devices_per_process}"
                ).strip()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.cli", *cli_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        ))
    # drain every process CONCURRENTLY: a sequential communicate() would
    # let a later process block on its full 64KB stdout pipe while we wait
    # on an earlier one stuck in the distributed barrier behind it
    import threading

    results: List[Optional[Tuple[str, str]]] = [None] * num_processes

    def drain(pid: int, p) -> None:
        try:
            results[pid] = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            results[pid] = p.communicate()

    threads = [
        threading.Thread(target=drain, args=(pid, p),
                         name=f"cluster-drain-{pid}", daemon=True)
        for pid, p in enumerate(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    outs: List[str] = []
    worst = 0
    for pid, p in enumerate(procs):
        out, err = results[pid] if results[pid] is not None else ("", "")
        if p.returncode:
            worst = p.returncode
            print(f"--- process {pid} rc={p.returncode} stderr tail ---",
                  file=sys.stderr)
            print("\n".join(err.splitlines()[-15:]), file=sys.stderr)
        if pid == 0:
            outs = out.splitlines()
    return worst, outs


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    if not argv or not argv[0].isdigit():
        print(
            "usage: async-cluster <num_processes> "
            "[--devices-per-process K] -- <cli args...>",
            file=sys.stderr,
        )
        return 2
    n = int(argv.pop(0))
    dpp = 2
    if argv and argv[0] == "--devices-per-process":
        argv.pop(0)
        if not argv or not argv[0].isdigit():
            print("--devices-per-process needs an integer", file=sys.stderr)
            return 2
        dpp = int(argv.pop(0))
    if argv and argv[0] == "--":
        argv.pop(0)
    rc, out = launch_local_cluster(n, argv, devices_per_process=dpp)
    for line in out:
        print(line)
    return rc


if __name__ == "__main__":
    sys.exit(main())
