"""Build helper for the native C++ components.

``python -m asyncframework_tpu.native_build`` compiles ``native/*.cc`` into
shared libraries next to their sources (the ctypes loaders look there).
Library code calls :func:`ensure_built` lazily and degrades to the
pure-Python fallbacks when no toolchain is available.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)

SOURCES = ("libsvm_parser", "kvstore", "codec")


def native_dir() -> str:
    return _NATIVE_DIR


def lib_path(name: str) -> str:
    return os.path.join(_NATIVE_DIR, f"{name}.so")


def is_built(name: str) -> bool:
    so = lib_path(name)
    src = os.path.join(_NATIVE_DIR, f"{name}.cc")
    return os.path.exists(so) and (
        not os.path.exists(src)
        or os.path.getmtime(so) >= os.path.getmtime(src)
    )


def ensure_built(name: str, quiet: bool = True) -> Optional[str]:
    """Build ``name``.so if stale/missing; returns its path or None when the
    build is impossible (no source tree, no compiler)."""
    if is_built(name):
        return lib_path(name)
    src = os.path.join(_NATIVE_DIR, f"{name}.cc")
    if not os.path.exists(src):
        return None
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-fPIC", "-shared", "-std=c++17", "-Wall",
           "-o", lib_path(name), src]
    try:
        res = subprocess.run(
            cmd, capture_output=True, text=True, cwd=_NATIVE_DIR, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if res.returncode != 0:
        if not quiet:
            sys.stderr.write(res.stderr)
        return None
    return lib_path(name)


def main() -> int:
    ok = True
    for name in SOURCES:
        path = ensure_built(name, quiet=False)
        print(f"{name}: {'built -> ' + path if path else 'FAILED'}")
        ok = ok and path is not None
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
