"""Build helper for the native C++ components.

``python -m asyncframework_tpu.native_build`` compiles ``native/*.cc`` into
shared libraries next to their sources (the ctypes loaders look there).
Library code calls :func:`ensure_built` lazily and degrades to the
pure-Python fallbacks when no toolchain is available.

Staleness is judged against the SOURCE mtime **and** the build recipe: a
``<name>.flags`` stamp next to each ``.so`` records the exact compile
command that produced it, so changing ``CXX``/``CXXFLAGS`` (or editing
``native/Makefile``, whose mtime is also considered) triggers a rebuild
instead of silently running old code under new flags.

``python -m asyncframework_tpu.native_build --check`` prints per-source
status (built / stale / missing-toolchain / no-source) without building
anything -- the operator's answer to "is this box actually running the
native data plane?".

This module also hosts the ``native`` counter family
(:func:`native_totals` / :func:`reset_native_totals`, registered in
``metrics/registry.py``): every native-vs-Python dispatch decision in the
wire hot paths bumps a counter here, so a silent fallback to the Python
oracle is *visible* in /api/status, /metrics, and async-top, not inferred
from speed.  It lives in this dependency-light module because both the
``net/`` loaders and ``net/shmring.py`` bump it and neither may import
the other.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import threading
from typing import Dict, Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)

SOURCES = ("libsvm_parser", "kvstore", "codec", "wiredelta", "wirecodec",
           "shmring")

# ------------------------------------------------------------ native totals
# Process-global counters (metrics/registry.py family "native"):
# native_calls.<unit> / python_calls.<unit> per dispatch site, plus
# python_fallbacks (conf WANTED native but the library is unavailable --
# the silent-degrade case this family exists to surface) and the shm-ring
# transport's frame/byte/upgrade/degrade counts (net/shmring.py).
_totals_lock = threading.Lock()
_totals: Dict[str, int] = {}


def bump_native(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] = _totals.get(key, 0) + n


def native_totals() -> Dict[str, int]:
    """Flat monotone counters: native_calls.<unit> / python_calls.<unit>
    (which implementation actually ran, per codec unit),
    python_fallbacks (native was enabled but unavailable), shm_upgrades /
    shm_upgrade_refused / shm_degrades, shm_frames_sent, shm_bytes_sent /
    shm_bytes_recv."""
    with _totals_lock:
        return dict(_totals)


def reset_native_totals() -> None:
    """Zero the native-plane counters (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    with _totals_lock:
        _totals.clear()


# ------------------------------------------------------------------- build
def native_dir() -> str:
    return _NATIVE_DIR


def lib_path(name: str) -> str:
    return os.path.join(_NATIVE_DIR, f"{name}.so")


def _flags_path(name: str) -> str:
    return os.path.join(_NATIVE_DIR, f"{name}.flags")


def _compile_cmd(name: str) -> list:
    cxx = os.environ.get("CXX", "g++")
    flags = os.environ.get(
        "CXXFLAGS", "-O3 -fPIC -shared -std=c++17 -Wall"
    ).split()
    return [cxx, *flags, "-o", lib_path(name),
            os.path.join(_NATIVE_DIR, f"{name}.cc")]


def _src_mtime(name: str) -> Optional[float]:
    """Newest mtime of the inputs that define the build: the source file
    and the Makefile (a flag edit there must rebuild too).  None when the
    source itself is absent (an installed tree shipping only ``.so``s --
    nothing to be stale against)."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cc")
    if not os.path.exists(src):
        return None
    newest = os.path.getmtime(src)
    mk = os.path.join(_NATIVE_DIR, "Makefile")
    if os.path.exists(mk):
        newest = max(newest, os.path.getmtime(mk))
    return newest


def is_built(name: str) -> bool:
    so = lib_path(name)
    if not os.path.exists(so):
        return False
    newest = _src_mtime(name)
    if newest is None:
        return True
    if os.path.getmtime(so) < newest:
        return False
    # recipe stamp: a CXX/CXXFLAGS change invalidates the artifact even
    # with identical mtimes.  A missing stamp (pre-stamp .so, or one
    # built by `make` directly) is accepted when the mtimes pass -- the
    # stamp only ever ADDS rebuild triggers, it never blocks loading.
    fp = _flags_path(name)
    if os.path.exists(fp):
        try:
            with open(fp, "r", encoding="utf-8") as f:
                return f.read() == " ".join(_compile_cmd(name))
        except OSError:
            return False
    return True


def ensure_built(name: str, quiet: bool = True) -> Optional[str]:
    """Build ``name``.so if stale/missing; returns its path or None when the
    build is impossible (no source tree, no compiler)."""
    if is_built(name):
        return lib_path(name)
    src = os.path.join(_NATIVE_DIR, f"{name}.cc")
    if not os.path.exists(src):
        return None
    cmd = _compile_cmd(name)
    try:
        res = subprocess.run(
            cmd, capture_output=True, text=True, cwd=_NATIVE_DIR, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if res.returncode != 0:
        if not quiet:
            sys.stderr.write(res.stderr)
        return None
    try:
        with open(_flags_path(name), "w", encoding="utf-8") as f:
            f.write(" ".join(cmd))
    except OSError:
        pass  # a read-only tree still serves the fresh .so
    return lib_path(name)


def check_status(name: str) -> str:
    """One source's build state WITHOUT building: ``built`` / ``stale``
    (source or recipe newer than the artifact) / ``missing`` (never
    built) / ``no-source`` -- each suffixed ``, no-toolchain`` when a
    (re)build could not run anyway."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cc")
    so = lib_path(name)
    if not os.path.exists(src):
        state = "no-source" if not os.path.exists(so) else "built"
        return state
    if not os.path.exists(so):
        state = "missing"
    elif is_built(name):
        return "built"
    else:
        state = "stale"
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        state += ", no-toolchain"
    return state


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--check" in argv:
        worst = 0
        for name in SOURCES:
            state = check_status(name)
            print(f"{name}: {state}")
            if state != "built":
                worst = 1
        return worst
    ok = True
    for name in SOURCES:
        path = ensure_built(name, quiet=False)
        print(f"{name}: {'built -> ' + path if path else 'FAILED'}")
        ok = ok and path is not None
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
