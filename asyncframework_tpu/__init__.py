"""asyncframework-tpu: a TPU-native bounded-staleness asynchronous optimization framework.

A brand-new framework with the capabilities of the ASYNC engine (a Spark 2.3.2
fork implementing asynchronous parameter-server optimization -- ASGD and ASAGA
with bounded staleness, IPDPS 2020, arXiv:1907.08526), re-designed for TPU:

- workers are JAX devices (or logical device slots); data shards live in HBM
- per-shard mini-batch gradients are jitted XLA computations dispatched
  asynchronously from a host-side executor pool
- the driver is a pair of host threads: a submitter (cohort selection, model
  publication) and an updater (tau-filtered parameter-server updates) sharing
  an AsyncContext (result queue + worker-state table + logical clock)
- synchronous data-parallelism runs as a single fused jit with `psum` over a
  `jax.sharding.Mesh`

Reference parity map: see ARCHITECTURE.md (every component of the reference's
SURVEY.md section-2 inventory is mapped to a module here).
"""

from asyncframework_tpu.version import __version__

from asyncframework_tpu.context import AsyncContext, WorkerState, PartialResult
from asyncframework_tpu.conf import AsyncConf, ConfigEntry
from asyncframework_tpu.checkpoint import (
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
)

__all__ = [
    "__version__",
    "AsyncContext",
    "WorkerState",
    "PartialResult",
    "AsyncConf",
    "ConfigEntry",
    "CheckpointManager",
    "load_checkpoint",
    "save_checkpoint",
]
