"""Data sources: CSV / JSON-lines / Parquet readers into ColumnarFrame.

Parity: ``sql/core/src/main/scala/.../DataFrameReader.scala:64`` (the
``spark.read.csv/json/parquet`` front door) and the format implementations
under ``sql/core/.../execution/datasources/``.

TPU-first mapping: a data source's job here is to land numeric columns as
device arrays (ready for the fused expression DSL / segment aggregates) and
keep string columns host-side.  CSV and JSON-lines are parsed natively
(stdlib); Parquet rides pyarrow when present (the environment ships it) and
fails with a clear message when not -- a columnar wire format needs a real
decoder, and vendoring one would be padding, not capability.
"""

from __future__ import annotations

import csv as _csv
import json as _json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from asyncframework_tpu.sql.frame import ColumnarFrame


_I32 = (np.iinfo(np.int32).min, np.iinfo(np.int32).max)
_F32_EXACT = 1 << 24  # float32 represents integers exactly up to 2**24


def _int_column(ints: List[int]):
    """int32 device column when every value fits; otherwise a HOST column of
    Python ints.  The frame's device dtype for integers is int32 (jax x64 is
    off), and silently wrapping a 64-bit ID would corrupt data -- wide
    integers are identifiers in practice, and identifiers are join/group
    keys, which host columns serve exactly."""
    if all(_I32[0] <= v <= _I32[1] for v in ints):
        return np.asarray(ints, np.int32)
    return np.asarray(ints, dtype=object)


def _to_column(values: List[str], name: str):
    """Infer int -> float -> string, with '' treated as missing (NaN for
    floats; kept as '' for strings; promotes int columns to float)."""
    has_missing = any(v == "" for v in values)
    if not has_missing:
        try:
            return _int_column([int(v) for v in values])
        except ValueError:
            pass
    else:
        # nullable int column: float32 only when every value is exactly
        # representable; wide IDs stay a host column with None for missing
        try:
            ints = [int(v) if v != "" else None for v in values]
            if any(
                v is not None and abs(v) > _F32_EXACT for v in ints
            ):
                return np.asarray(ints, dtype=object)
        except ValueError:
            pass
    try:
        return np.asarray(
            [float(v) if v != "" else np.nan for v in values], np.float32
        )
    except ValueError:
        return np.asarray(values, dtype=object)


def _apply_pushdown(
    cols: Dict[str, object],
    select: Optional[Sequence[str]],
    where,
    mask=None,
) -> ColumnarFrame:
    """Shared reader pushdown (Optimizer.scala:38's data-source rules, in
    spirit): the predicate filters HOST arrays before any device placement
    -- the chip never receives pruned rows -- and the projection drops
    unselected columns before the frame is built.  ``mask`` short-circuits
    a predicate the caller already evaluated."""
    if where is not None or mask is not None:
        if mask is None:
            mask = where(cols)
        mask = np.asarray(mask, bool)
        cols = {k: np.asarray(v)[mask] for k, v in cols.items()}
    if select is not None:
        missing = [c for c in select if c not in cols]
        if missing:
            raise KeyError(f"select columns not in source: {missing}")
        cols = {c: cols[c] for c in select}
    return ColumnarFrame(cols)


def _needed_for_predicate(where, materialize, names):
    """Discover the predicate's column set by evaluation: start empty,
    materialize each column the evaluation KeyErrors on.  Columns the
    predicate never touches are never parsed (projection pushdown reaches
    through the predicate).  Returns ``(cols, mask)`` -- the successful
    evaluation IS the row mask, so callers never re-evaluate."""
    cols: Dict[str, object] = {}
    while True:
        try:
            return cols, where(cols)
        except KeyError as e:
            name = e.args[0].split("'")[1] if "'" in str(e.args[0]) else None
            if name is None or name in cols or name not in names:
                raise
            cols[name] = materialize(name)


class _FastPathUnsupported(Exception):
    """Internal: this CSV needs the general python-csv path (quoted
    fields, exotic delimiters, no pandas)."""


def _to_column_fast(vals: np.ndarray, name: str):
    """Vectorized ``_to_column``: the SAME int -> float -> string inference
    over exact cell strings, with numpy's C parsers instead of per-cell
    Python.  Falls back to the reference implementation for corners the
    vector ops cannot reproduce (e.g. > 64-bit integers)."""
    s = np.asarray(vals).astype("U")  # fixed-width unicode: C compare/parse
    missing = s == ""
    has_missing = bool(missing.any())
    if not has_missing:
        try:
            return _int_column(s.astype(np.int64).tolist())
        except (ValueError, OverflowError):
            # looks integral but did not parse as int64 (e.g. wider than
            # 64 bits): the exact python path owns that corner
            stripped = np.char.lstrip(s, "+-")
            if stripped.size and bool(np.char.isdigit(stripped).all()):
                return _to_column([str(v) for v in s], name)
    else:
        try:
            nz = s[~missing].astype(np.int64)
        except (ValueError, OverflowError):
            nz = None
        if nz is not None and nz.size and int(np.abs(nz).max()) > _F32_EXACT:
            # nullable int column with wide IDs: host column, None missing
            out = np.empty(s.shape[0], dtype=object)
            out[~missing] = [int(v) for v in nz]
            return out
    try:
        return np.where(missing, "nan", s).astype(np.float32)
    except ValueError:
        return s.astype(object)


def _raise_ragged(path, text, delimiter, header, want_count):
    """Locate the first bad row for the python path's exact error shape."""
    lines = [l for l in text.splitlines() if l]
    data = lines[1:] if header else lines
    for i, line in enumerate(data):
        c = line.count(delimiter)
        if c != want_count:
            raise ValueError(
                f"{path}: row {i + 1} has {c + 1} fields, "
                f"expected {want_count + 1}"
            )
    raise ValueError(f"{path}: inconsistent field counts")


def _read_csv_fast(path, header, columns, delimiter, select, where):
    """pandas-C-parser fast path (~7x the python csv module at 1M rows,
    ROUND5.md): clean numeric columns parse typed in C
    (``keep_default_na=False`` keeps empty cells as '' so mixed/missing
    columns arrive as exact strings and run through the same inference).
    Restricted to quote-free single-char delimiters.  Ragged rows keep the
    python path's validation contract: the C parser rejects extra fields,
    and a whole-file delimiter count catches missing ones (an extra-field
    row cannot mask a short row -- it raises first)."""
    try:
        import pandas as pd
    except ImportError:  # pragma: no cover - pandas ships in this image
        raise _FastPathUnsupported("no pandas")
    if len(delimiter) != 1:
        raise _FastPathUnsupported("multi-char delimiter")
    with open(path, newline="") as f:
        text = f.read()
    if '"' in text:
        raise _FastPathUnsupported("quoted fields")
    if not text.strip():
        raise ValueError(f"{path}: empty CSV")
    if not header and columns is None:
        raise ValueError("header=False requires explicit column names")
    import io as _io

    kw = dict(keep_default_na=False, sep=delimiter, engine="c")
    try:
        if header:
            df = pd.read_csv(_io.StringIO(text), **kw)
            names = list(df.columns)
            if columns is not None:
                names = list(columns)
                df.columns = names
        else:
            names = list(columns)
            df = pd.read_csv(_io.StringIO(text), header=None, names=names,
                             **kw)
    except pd.errors.ParserError:
        _raise_ragged(path, text, delimiter, header,
                      len(columns) - 1 if columns is not None and not header
                      else text.split("\n", 1)[0].count(delimiter))
    except pd.errors.EmptyDataError:
        raise ValueError(f"{path}: empty CSV")
    want_count = len(names) - 1
    header_cnt = (text.split("\n", 1)[0].count(delimiter) if header else 0)
    if text.count(delimiter) != want_count * len(df) + header_cnt:
        _raise_ragged(path, text, delimiter, header, want_count)

    def materialize(name: str):
        a = df[name].to_numpy()
        if a.dtype.kind == "i":  # clean int64 parse: downcast rules only
            lo, hi = (int(a.min()), int(a.max())) if len(a) else (0, 0)
            if _I32[0] <= lo and hi <= _I32[1]:
                return a.astype(np.int32)
            return np.asarray(a.tolist(), dtype=object)
        if a.dtype.kind == "f":
            # the python path's float32(str) also rounds through float64
            # (float() then np.float32), so this is bit-identical
            return a.astype(np.float32)
        if a.dtype.kind != "O":  # bool or other pandas inference: bail
            raise _FastPathUnsupported(f"pandas dtype {a.dtype}")
        return _to_column_fast(a, name)

    wanted = list(select) if select is not None else names
    missing_cols = [c for c in wanted if c not in names]
    if missing_cols:
        raise KeyError(f"select columns not in source: {missing_cols}")
    cols: Dict[str, object] = {}
    mask = None
    if where is not None:
        cols, mask = _needed_for_predicate(where, materialize, set(names))
    for name in wanted:
        if name not in cols:
            cols[name] = materialize(name)
    return _apply_pushdown(cols, wanted, where, mask=mask)


def read_csv(
    path: Union[str, Path],
    header: bool = True,
    columns: Optional[Sequence[str]] = None,
    delimiter: str = ",",
    select: Optional[Sequence[str]] = None,
    where=None,
) -> ColumnarFrame:
    """Load a CSV into a ColumnarFrame.

    Numeric columns (int/float inference per column) become device arrays;
    anything else stays a host string column.  ``columns`` overrides/provides
    names (required when ``header=False``).

    Pushdown: ``select`` keeps only the named columns -- unselected columns
    (beyond those the predicate needs) are never parsed or inferred at all;
    ``where`` (a Column predicate) filters rows before device placement.

    Quote-free files take the pandas-C-parser fast path (same inference
    over exact cell strings); quoted fields and exotic delimiters use the
    python csv module below.
    """
    try:
        return _read_csv_fast(path, header, columns, delimiter, select,
                              where)
    except _FastPathUnsupported:
        pass
    with open(path, newline="") as f:
        reader = _csv.reader(f, delimiter=delimiter)
        rows = [r for r in reader if r]
    if not rows:
        raise ValueError(f"{path}: empty CSV")
    if header:
        names = rows[0]
        rows = rows[1:]
    else:
        if columns is None:
            raise ValueError("header=False requires explicit column names")
        names = list(columns)
    if columns is not None and header:
        names = list(columns)
    width = len(names)
    for i, r in enumerate(rows):
        if len(r) != width:
            raise ValueError(
                f"{path}: row {i + 1} has {len(r)} fields, expected {width}"
            )
    index = {name: j for j, name in enumerate(names)}

    def materialize(name: str):
        return _to_column([r[index[name]] for r in rows], name)

    wanted = list(select) if select is not None else names
    bad = [c for c in wanted if c not in index]
    if bad:
        raise KeyError(f"select columns not in source: {bad}")
    cols: Dict[str, object] = {}
    mask = None
    if where is not None:
        cols, mask = _needed_for_predicate(where, materialize, set(names))
    for name in wanted:
        if name not in cols:
            cols[name] = materialize(name)
    return _apply_pushdown(cols, wanted, where, mask=mask)


def read_json(
    path: Union[str, Path],
    select: Optional[Sequence[str]] = None,
    where=None,
) -> ColumnarFrame:
    """JSON-lines (one object per line) into a ColumnarFrame; the schema is
    the union of keys, missing values become NaN/''.  ``select``/``where``
    push projection and row filtering below device placement."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(_json.loads(line))
    if not records:
        raise ValueError(f"{path}: no records")
    names: List[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    cols: Dict[str, object] = {}
    for name in names:
        vals = [r.get(name) for r in records]
        if all(
            isinstance(v, int) and not isinstance(v, bool) for v in vals
        ):
            # pure-integer column: size-check BEFORE any float32 round trip
            # (float32 silently distorts ints above 2**24)
            cols[name] = _int_column(vals)
        elif all(isinstance(v, (int, float)) or v is None for v in vals):
            if any(
                isinstance(v, int) and not isinstance(v, bool)
                and abs(v) > _F32_EXACT
                for v in vals
            ):
                # nullable/mixed column with wide ints: a single null must
                # not reroute IDs through lossy float32
                cols[name] = np.asarray(vals, dtype=object)
            else:
                cols[name] = np.asarray(
                    [float(v) if v is not None else np.nan for v in vals],
                    np.float32,
                )
        else:
            cols[name] = np.asarray(
                ["" if v is None else str(v) for v in vals], dtype=object
            )
    if select is not None or where is not None:
        return _apply_pushdown(cols, select, where)
    return ColumnarFrame(cols)


def read_parquet(
    path: Union[str, Path],
    columns: Optional[Sequence[str]] = None,
    select: Optional[Sequence[str]] = None,
    where=None,
) -> ColumnarFrame:
    """Parquet into a ColumnarFrame via pyarrow.  ``select`` prunes columns
    AT the pyarrow layer (true columnar projection: unselected column
    chunks are never decoded, beyond what ``where`` needs); ``where``
    filters rows before device placement."""
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - environment ships pyarrow
        raise ImportError(
            "read_parquet requires pyarrow; install it or convert the data "
            "to CSV/JSON-lines for the native readers"
        ) from e
    def convert(arr: np.ndarray) -> np.ndarray:
        if arr.dtype == np.float64:
            return arr.astype(np.float32)
        if arr.dtype == np.int64:
            # downcast only when lossless; wide ints become host columns
            # (see _int_column -- silent int32 wraparound corrupts IDs)
            if len(arr) == 0 or (
                arr.min() >= _I32[0] and arr.max() <= _I32[1]
            ):
                return arr.astype(np.int32)
            return np.asarray([int(v) for v in arr], dtype=object)
        if not np.issubdtype(arr.dtype, np.number):
            return arr.astype(object)
        return arr

    want = list(select) if select is not None else (
        list(columns) if columns else None
    )
    schema_names = pq.read_schema(path).names

    def materialize(name: str):
        t = pq.read_table(path, columns=[name])
        return convert(t.column(name).to_numpy(zero_copy_only=False))

    cols: Dict[str, object] = {}
    mask = None
    if where is not None:
        cols, mask = _needed_for_predicate(
            where, materialize, set(schema_names)
        )
    remaining = [c for c in (want or schema_names) if c not in cols]
    if remaining:
        table = pq.read_table(path, columns=remaining)
        for name in table.column_names:
            cols[name] = convert(
                table.column(name).to_numpy(zero_copy_only=False)
            )
    return _apply_pushdown(cols, want, where, mask=mask)


class LazyTable:
    """A registered-but-unread data source: the optimizer pushes projection
    and predicates into ``reader(select=, where=)`` so unneeded columns are
    never parsed and filtered rows never reach the device (the
    datasource-v2 pushdown role, ``Optimizer.scala:38`` data-source rules).
    """

    def __init__(self, name: str, reader, schema: Optional[List[str]] = None):
        self.name = name
        self.reader = reader
        self.schema = schema

    def materialize(self) -> ColumnarFrame:
        """Full read -- the compatibility path for direct ``ctx.table()``
        callers that expect an eager frame."""
        return self.reader(select=None, where=None)


def lazy_csv(name: str, path: Union[str, Path], **kw) -> LazyTable:
    with open(path, newline="") as f:
        first = f.readline().strip()
    schema = (
        first.split(kw.get("delimiter", ",")) if kw.get("header", True)
        else list(kw.get("columns") or [])
    ) or None

    def reader(select=None, where=None):
        return read_csv(path, select=select, where=where, **kw)

    return LazyTable(name, reader, schema)


def lazy_json(name: str, path: Union[str, Path]) -> LazyTable:
    # JSON-lines schema is the union of keys -- unknown without a full
    # scan, so pruning is disabled (predicate pushdown still applies)
    def reader(select=None, where=None):
        return read_json(path, select=select, where=where)

    return LazyTable(name, reader, None)


def lazy_parquet(name: str, path: Union[str, Path]) -> LazyTable:
    try:
        import pyarrow.parquet as pq

        schema = list(pq.read_schema(path).names)
    except Exception:
        schema = None

    def reader(select=None, where=None):
        return read_parquet(path, select=select, where=where)

    return LazyTable(name, reader, schema)


def write_csv(frame: ColumnarFrame, path: Union[str, Path]) -> None:
    """Round-trip writer (tests / interchange)."""
    names = frame.columns
    host = {n: np.asarray(frame[n]) for n in names}
    with open(path, "w", newline="") as f:
        w = _csv.writer(f)
        w.writerow(names)
        for i in range(len(frame)):
            w.writerow([host[n][i] for n in names])
