"""ColumnarFrame: eager columnar relational ops over device arrays.

Parity: the DataFrame/Dataset surface of Spark SQL (``sql/core/.../
Dataset.scala:166`` -- select/filter/withColumn/groupBy-agg/sort/join).
The reference's 171k-LoC SQL stack exists to plan relational trees onto a
shuffle engine and codegen row kernels; on TPU the same user-facing
capability reduces to columnar array ops XLA already compiles well:

- projections and predicates: fused elementwise kernels (the expression
  tree in ``sql/expressions.py``);
- groupBy-agg: host-side key dictionary (``np.unique``) + device segment
  reductions -- the scatter-combine replacing a hash shuffle;
- join: host-side sort-based index build + device gathers;
- sort: argsort + gather.

Execution is EAGER (each op one XLA dispatch): filters and joins produce
data-dependent shapes, which is exactly what jit forbids -- the optimizer
the reference needs for lazy SQL plans has no analog worth building here.
Columns are jax arrays (numeric/bool); key columns for groupby/join may be
any numpy dtype including strings (they live host-side by design).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.sql.expressions import Column, col

_AGGS = ("sum", "mean", "count", "min", "max")


def _is_device_dtype(arr: np.ndarray) -> bool:
    return arr.dtype.kind in "fiub"


class ColumnarFrame:
    def __init__(self, columns: Dict[str, object]):
        if not columns:
            raise ValueError("a frame needs at least one column")
        self._cols: Dict[str, object] = {}
        n = None
        for name, arr in columns.items():
            a = np.asarray(arr)
            if a.ndim != 1:
                raise ValueError(f"column {name!r} must be 1-d, got {a.ndim}-d")
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    f"column {name!r} has {a.shape[0]} rows, expected {n}"
                )
            # numeric/bool columns live on device; anything else (strings,
            # objects) stays host-side -- valid as keys, not as expressions
            self._cols[name] = jnp.asarray(a) if _is_device_dtype(a) else a
        self._n = int(n)

    # ---------------------------------------------------------------- basics
    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def count(self) -> int:
        return self._n

    def __getitem__(self, name: str):
        return self._cols[name]

    def to_dict(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._cols.items()}

    def collect(self) -> List[Tuple]:
        """Row tuples, column order = self.columns (Dataset.collect)."""
        host = self.to_dict()
        cols = [host[c] for c in self.columns]
        return list(zip(*[c.tolist() for c in cols]))

    def __repr__(self) -> str:  # pragma: no cover
        return f"ColumnarFrame({self.columns}, rows={self._n})"

    # ------------------------------------------------------------ projection
    def _eval(self, expr: Union[str, Column]):
        if isinstance(expr, str):
            expr = col(expr)
        val = expr(self._cols)
        if np.ndim(val) == 0:
            # literal expressions (SELECT 1, COUNT(1)'s temp column, ...)
            # broadcast to the frame's length like SQL scalars do
            val = jnp.full((self._n,), val)
        return val, expr.name

    def select(self, *exprs: Union[str, Column]) -> "ColumnarFrame":
        out: Dict[str, object] = {}
        for e in exprs:
            val, name = self._eval(e)
            out[name] = val
        return ColumnarFrame(out)

    def with_column(self, name: str, expr: Union[str, Column]) -> "ColumnarFrame":
        out = dict(self._cols)
        out[name], _ = self._eval(expr)
        return ColumnarFrame(out)

    def with_window(
        self,
        name: str,
        fn: str,
        arg: Optional[str] = None,
        partition_by: Union[str, List[str], None] = None,
        order_by: Optional[str] = None,
        ascending: bool = True,
        offset: int = 1,
        default=np.nan,
    ) -> "ColumnarFrame":
        """Add a window-function column (Spark ``Window.partitionBy(...)``
        analog): row_number/rank/dense_rank, lag/lead, and running or
        whole-partition sum/mean/min/max/count; ``partition_by`` may be a
        list (multi-key partitions).  See ``sql/window.py``."""
        from asyncframework_tpu.sql.window import window_column

        out = dict(self._cols)
        out[name] = window_column(
            self, fn, arg, partition_by, order_by,
            ascending=ascending, offset=offset, default=default,
        )
        return ColumnarFrame(out)

    def rename(self, mapping: Dict[str, str]) -> "ColumnarFrame":
        return ColumnarFrame(
            {mapping.get(k, k): v for k, v in self._cols.items()}
        )

    # ------------------------------------------------------------- filtering
    def filter(self, predicate: Column) -> "ColumnarFrame":
        mask = np.asarray(predicate(self._cols), bool)
        if mask.shape != (self._n,):
            raise ValueError("predicate must produce one bool per row")
        idx = np.nonzero(mask)[0]
        return self._take(idx)

    where = filter

    def _take(self, idx: np.ndarray) -> "ColumnarFrame":
        out: Dict[str, object] = {}
        for name, arr in self._cols.items():
            out[name] = _gather(arr, idx)
        return ColumnarFrame(out)

    def _row_records(self) -> np.ndarray:
        """Rows packed as one comparable structured array (shared by
        distinct and the set operations).  Floats compare by bit pattern
        (-0.0 normalized) so duplicate NaN rows collapse; object/string
        columns compare by a stable per-value code."""
        arrays = [
            (f"f{i}", _comparable_column(np.asarray(self._cols[c])))
            for i, c in enumerate(self._cols)
        ]
        rec = np.empty(
            self._n, dtype=[(name, a.dtype) for name, a in arrays]
        )
        for name, a in arrays:
            rec[name] = a
        return rec

    def distinct(self) -> "ColumnarFrame":
        """Row dedup (``Dataset.distinct`` parity): keeps the FIRST
        occurrence of each distinct row, in first-seen order.  Vectorized:
        columns pack into one structured array and ``np.unique`` finds the
        first index of each distinct row; the row materialization is one
        device gather."""
        _vals, idx = np.unique(self._row_records(), return_index=True)
        return self._take(np.sort(idx))

    # ------------------------------------------------------- set operations
    def _aligned(self, other: "ColumnarFrame") -> "ColumnarFrame":
        if list(other.columns) == list(self.columns):
            return other
        if set(other.columns) != set(self.columns):
            raise ValueError(
                f"set operation needs matching columns: {self.columns} "
                f"vs {other.columns}"
            )
        return other.select(*self.columns)

    def union_all(self, other: "ColumnarFrame") -> "ColumnarFrame":
        """SQL UNION ALL: rows of self then rows of other (bag semantics).
        Columns match by NAME (order-insensitive, like Spark's
        unionByName).  Concatenation happens on host: the frame
        constructor re-stages device columns anyway, so a device concat
        would only add a readback."""
        other = self._aligned(other)
        out: Dict[str, object] = {}
        for name in self.columns:
            a = np.asarray(self._cols[name])
            b = np.asarray(other._cols[name])
            if a.dtype.kind == "O" or b.dtype.kind == "O":
                out[name] = np.concatenate(
                    [a.astype(object), b.astype(object)]
                )
            else:
                out[name] = np.concatenate([a, b])
        return ColumnarFrame(out)

    def union(self, other: "ColumnarFrame") -> "ColumnarFrame":
        """SQL UNION: concatenation + row dedup."""
        return self.union_all(other).distinct()

    def except_rows(self, other: "ColumnarFrame") -> "ColumnarFrame":
        """SQL EXCEPT: distinct rows of self absent from other."""
        other = self._aligned(other)
        mine = self._row_records()
        theirs = other._row_records()
        keep = ~np.isin(mine, theirs)
        return self._take(np.nonzero(keep)[0]).distinct()

    def intersect_rows(self, other: "ColumnarFrame") -> "ColumnarFrame":
        """SQL INTERSECT: distinct rows present in both."""
        other = self._aligned(other)
        mine = self._row_records()
        theirs = other._row_records()
        keep = np.isin(mine, theirs)
        return self._take(np.nonzero(keep)[0]).distinct()

    # --------------------------------------------------------------- sorting
    def sort(self, by, ascending=True) -> "ColumnarFrame":
        """Stable sort by one column or a list (``ORDER BY c1, c2 ...``
        parity); ``ascending`` may be one bool or one per column."""
        cols = [by] if isinstance(by, str) else list(by)
        asc = ([ascending] * len(cols) if isinstance(ascending, bool)
               else list(ascending))
        if len(asc) != len(cols):
            raise ValueError("one ascending flag per sort column")
        if len(cols) == 1 and asc[0]:
            order = np.argsort(np.asarray(self._cols[cols[0]]),
                               kind="stable")
            return self._take(order)
        # multi-column / descending: lexsort over per-column sort codes
        # (codes negate cleanly for DESC even on string columns, and a
        # stable code sort == a stable value sort)
        lex_keys = []
        for c, a in zip(reversed(cols), reversed(asc)):
            arr = np.asarray(self._cols[c])
            _u, codes = _factorize_sorted(arr)
            lex_keys.append(codes if a else -codes)
        return self._take(np.lexsort(lex_keys))

    # -------------------------------------------------------------- grouping
    def groupby(self, key) -> "GroupedFrame":
        """``key``: one column name or a list of them (multi-key grouping,
        ``Dataset.groupBy(col1, col2, ...)`` parity)."""
        return GroupedFrame(self, key)

    def agg(self, **spec) -> Dict[str, float]:
        """Whole-frame aggregates: ``agg(total=("v", "sum"), ...)``."""
        out = {}
        for name, (colname, fn) in spec.items():
            v = self._cols[colname]
            if fn == "sum":
                out[name] = float(jnp.sum(v))
            elif fn == "mean":
                out[name] = float(jnp.mean(v))
            elif fn == "count":
                out[name] = self._n
            elif fn == "min":
                out[name] = float(jnp.min(v))
            elif fn == "max":
                out[name] = float(jnp.max(v))
            else:
                raise ValueError(f"unknown aggregate {fn!r}; use {_AGGS}")
        return out

    # ----------------------------------------------------------------- joins
    def join(
        self, other: "ColumnarFrame", on: Union[str, List[str]],
        how: str = "inner"
    ) -> "ColumnarFrame":
        """Equi-join on column ``on`` -- one name or a list (multi-key:
        the sides are packed into comparable key records);
        ``how`` in ('inner', 'left', 'right', 'full', 'semi', 'anti').

        Index build is a host-side sort/searchsorted (keys may be strings);
        the row materialization is device gathers.  Duplicate right keys
        produce one output row per match, like SQL.  Outer-join rows with no
        match carry NaN in the other frame's float columns (other dtypes
        get 0/empty -- a columnar store has no NULL; document over invent).
        ``semi``/``anti`` return only left columns: rows with >=1 match /
        rows with none (no duplication), like Spark's LeftSemi/LeftAnti.
        """
        keys = [on] if isinstance(on, str) else list(on)
        if how == "right":
            # a right join IS a left join with the frames swapped.  Colliding
            # names must still follow the left-keeps-bare convention, so
            # left's collisions are parked under temp names through the swap
            # and the pair is renamed back afterwards.
            collide = [
                c for c in self.columns
                if c not in keys and c in other.columns
            ]
            lf = self.rename({c: f"__swap__{c}" for c in collide})
            j = other.join(lf, on, "left")
            j = j.rename(
                {c: f"{c}_right" for c in collide}
                | {f"__swap__{c}": c for c in collide}
            )
            order = keys + [c for c in self.columns if c not in keys] + [
                c for c in j.columns
                if c not in self.columns and c not in keys
            ]
            return ColumnarFrame({c: j._cols[c] for c in order})
        if how not in ("inner", "left", "full", "semi", "anti"):
            raise ValueError(
                "how must be one of inner/left/full/semi/anti (right is "
                "rewritten above)"
            )
        if how == "inner" and len(other) >= 4 * len(self) and len(
            other
        ) > 1024:
            # build-side selection (SortShuffleManager/hash-join build-side
            # role): index the SMALLER side -- sorting the big side costs
            # R log R, this swap makes it L log L + R log L.  Inner joins
            # are symmetric; the rename dance preserves the left-keeps-bare
            # column convention (row order is right-major after the swap --
            # SQL promises none).
            collide = [
                c for c in self.columns
                if c not in keys and c in other.columns
            ]
            lf = self.rename({c: f"__swap__{c}" for c in collide})
            j = other.join(lf, on, "inner")
            j = j.rename(
                {c: f"{c}_right" for c in collide}
                | {f"__swap__{c}": c for c in collide}
            )
            order = keys + [c for c in self.columns if c not in keys] + [
                c for c in j.columns
                if c not in self.columns and c not in keys
            ]
            return ColumnarFrame({c: j._cols[c] for c in order})
        if len(keys) == 1:
            lk = np.asarray(self._cols[keys[0]])
            rk = np.asarray(other._cols[keys[0]])
        else:
            lk, rk = _pack_join_keys(self, other, keys)
        if how in ("semi", "anti"):
            _s, cnt = _match_table(np.sort(rk), rk, lk)
            keep = (cnt > 0) if how == "semi" else (cnt == 0)
            return self._take(np.where(keep)[0])
        r_order = np.argsort(rk, kind="stable")
        rk_sorted = rk[r_order]
        start, counts = _match_table(rk_sorted, rk, lk)
        matched = counts > 0
        # expand: for left row i with c matches, right rows r_order[start_i..]
        keep_left = how in ("left", "full")
        rep_counts = np.where(matched, counts, 1 if keep_left else 0)
        left_idx = np.repeat(np.arange(len(lk)), rep_counts)
        total = int(rep_counts.sum())
        offs = np.arange(total) - np.repeat(
            np.cumsum(rep_counts) - rep_counts, rep_counts
        )
        right_pos = np.repeat(start, rep_counts) + offs
        has_match = np.repeat(matched, rep_counts)
        if len(rk):
            right_idx = np.where(
                has_match, r_order[np.minimum(right_pos, len(rk) - 1)], 0
            )
        else:
            # empty right frame: every surviving row (left join) is a miss
            right_idx = np.zeros(total, np.intp)

        out: Dict[str, object] = {}
        right_src: Dict[str, str] = {}  # out name -> original right column
        left_taken = self._take(left_idx)
        for name in self.columns:
            out[name] = left_taken._cols[name]
        for name in other.columns:
            if name in keys:
                continue
            out_name = name if name not in out else f"{name}_right"
            right_src[out_name] = name
            src = other._cols[name]
            if len(rk):
                v = _gather(src, right_idx)
            else:  # no rows to gather from: build fill directly
                v = (
                    jnp.zeros((total,), src.dtype)
                    if isinstance(src, jnp.ndarray)
                    else np.zeros(total, np.asarray(src).dtype)
                )
            if keep_left:
                # mask unmatched rows in EVERY right column: floats get NaN,
                # other device dtypes 0, host (string/object) columns the
                # dtype's zero ('' for strings) -- never row-0's real data
                v = _mask_fill(v, has_match)
            out[out_name] = v

        if how == "full":
            # append right rows no left row matched, with left-column fills
            r_hit = np.zeros(len(rk), bool)
            if len(rk) and total:
                r_hit[right_idx[has_match]] = True
            miss = np.where(~r_hit)[0]
            if len(miss):
                none = np.zeros(len(miss), bool)
                for name in list(out):
                    cur = out[name]
                    if name in keys:
                        # key survives from the right side (per column --
                        # rk may be a packed record array)
                        extra = np.asarray(other._cols[name])[miss]
                    elif name in right_src:
                        src = other._cols[right_src[name]]
                        extra = _gather(src, miss)
                    else:  # left-only column: all fills
                        src = self._cols[name]
                        extra = _mask_fill(
                            jnp.zeros((len(miss),), src.dtype)
                            if isinstance(src, jnp.ndarray)
                            else np.zeros(len(miss), np.asarray(src).dtype),
                            none,
                        )
                    if isinstance(cur, jnp.ndarray):
                        out[name] = jnp.concatenate(
                            [cur, jnp.asarray(extra, cur.dtype)]
                        )
                    else:
                        out[name] = np.concatenate(
                            [np.asarray(cur), np.asarray(extra)]
                        )
        return ColumnarFrame(out)


def _gather(src, idx):
    """Row gather routed by backend: ``jnp.take`` keeps device columns on
    an accelerator; on the CPU backend numpy fancy indexing is 4-6x faster
    (measured, ROUND5.md) and the frame constructor re-stages the result."""
    if isinstance(src, jnp.ndarray):
        import jax

        if jax.default_backend() == "cpu":
            return np.asarray(src)[np.asarray(idx)]
        return jnp.take(src, jnp.asarray(idx), axis=0)
    return np.asarray(src)[idx]


def _match_table(rk_sorted: np.ndarray, rk: np.ndarray, lk: np.ndarray):
    """(start, count) of each left key's match run in the sorted right
    keys.  Dense-enough integer keys take the O(1)-per-probe bincount
    table (two binary-search passes over 2M probes cost ~1.3 s; the table
    lookups ~70 ms -- ROUND5.md); anything else binary-searches."""
    if (
        lk.dtype.kind in "iu" and rk.dtype.kind in "iu"
        and lk.size and rk.size
    ):
        lo = min(int(lk.min()), int(rk.min()))
        hi = max(int(lk.max()), int(rk.max()))
        span = hi - lo + 1
        if span <= max(lk.size + rk.size, 1 << 20):
            counts_per_key = np.bincount(rk - lo, minlength=span)
            start_per_key = np.concatenate([
                np.zeros(1, np.intp),
                np.cumsum(counts_per_key)[:-1],
            ])
            probe = lk - lo
            return (start_per_key[probe].astype(np.intp),
                    counts_per_key[probe].astype(np.intp))
    start = np.searchsorted(rk_sorted, lk, "left")
    end = np.searchsorted(rk_sorted, lk, "right")
    return start, end - start


def _comparable_column(a: np.ndarray) -> np.ndarray:
    """ONE definition of the comparability normalization (shared by
    ``_row_records`` and the multi-key join pack): floats by normalized
    bit pattern (-0.0 collapsed), object columns as strings."""
    if a.dtype.kind == "f":
        a = np.where(a == 0, 0.0, a).astype(a.dtype)
        return a.view(f"u{a.dtype.itemsize}")
    if a.dtype.kind == "O":
        # structured dtypes reject object fields; encode as str
        return a.astype(str)
    return a


def _pack_join_keys(left: "ColumnarFrame", right: "ColumnarFrame", keys):
    """Both sides' key columns packed as ONE comparable structured array
    each (multi-key equi-join).  Per-key dtypes are unified across the two
    frames FIRST (string widths, numeric promotion) so record comparisons
    are well-defined, then each column runs the shared
    :func:`_comparable_column` normalization."""
    fields = []
    l_cols, r_cols = [], []
    for i, k in enumerate(keys):
        a = np.asarray(left._cols[k])
        b = np.asarray(right._cols[k])
        if a.dtype.kind in "OUS" or b.dtype.kind in "OUS":
            a = _comparable_column(a.astype(object))
            b = _comparable_column(b.astype(object))
            width = max(a.dtype.itemsize, b.dtype.itemsize) // 4
            dt = np.dtype(f"U{max(width, 1)}")
            a, b = a.astype(dt), b.astype(dt)
        else:
            dt = np.promote_types(a.dtype, b.dtype)
            a = _comparable_column(a.astype(dt))
            b = _comparable_column(b.astype(dt))
            dt = a.dtype
        fields.append((f"f{i}", dt))
        l_cols.append(a)
        r_cols.append(b)
    lrec = np.empty(len(left), dtype=fields)
    rrec = np.empty(len(right), dtype=fields)
    for (nm, _dt), a, b in zip(fields, l_cols, r_cols):
        lrec[nm] = a
        rrec[nm] = b
    return lrec, rrec


def _mask_fill(v, keep_mask: np.ndarray):
    """NULL emulation for non-matching join rows: floats NaN (device OR
    host-staged numpy -- the CPU gather path returns numpy for device
    columns), other numeric dtypes 0, host string/object columns the
    dtype's zero value."""
    if isinstance(v, jnp.ndarray) and jnp.issubdtype(v.dtype, jnp.floating):
        return jnp.where(jnp.asarray(keep_mask), v, jnp.nan)
    if isinstance(v, jnp.ndarray):
        return jnp.where(jnp.asarray(keep_mask), v, 0)
    v = np.asarray(v)
    if v.dtype.kind == "f":
        return np.where(keep_mask, v, np.nan)
    return np.where(keep_mask, v, np.zeros_like(v))


def _factorize_sorted(keys: np.ndarray):
    """(sorted uniques, codes) -- the group coding.

    ``pd.factorize`` (hashtable, O(n)) + a k-sized sort/remap replaces
    ``np.unique(return_inverse=True)`` (full n log n sort): measured 6x
    faster on 2M int keys and 47x on 2M string keys -- the coding was the
    whole gap to pandas in the round-3 GROUP BY benchmark.  Output
    contract unchanged: uniques ascend.
    """
    try:
        import pandas as pd
    except ImportError:          # pragma: no cover - image ships pandas
        return np.unique(keys, return_inverse=True)
    # use_na_sentinel=False: NaN keys get their OWN group code instead of
    # the -1 sentinel (which remap[codes] would wrap into an arbitrary
    # real group, silently mis-aggregating NaN rows).  np.unique semantics
    # preserved: one NaN group, sorted last.
    try:
        codes, uniques = pd.factorize(keys, use_na_sentinel=False)
    except TypeError:            # pragma: no cover - older pandas kwarg
        codes, uniques = pd.factorize(keys, na_sentinel=None)
    uniques = np.asarray(uniques)
    order = np.argsort(uniques, kind="stable")
    remap = np.empty(len(uniques), np.int64)
    remap[order] = np.arange(len(uniques))
    return uniques[order], remap[codes]


def multikey_partition_codes(frame, keys) -> np.ndarray:
    """Per-row partition codes for a multi-key grouping: EQUALITY only (no
    dense re-coding, no per-group key values) -- the window PARTITION BY
    need.  In the common case this is just the row-major combined integer;
    the int64-overflow fallback re-codes through a record array."""
    per_u = []
    per_c = []
    card_product = 1
    for k in keys:
        u, c = _factorize_sorted(np.asarray(frame[k]))
        per_u.append(u)
        per_c.append(c)
        card_product *= max(len(u), 1)
    if card_product < 2**62:
        combined = None
        for u, c in zip(per_u, per_c):
            combined = c if combined is None else combined * len(u) + c
        return combined
    # overflow: wrapped codes from distinct tuples could collide and
    # silently MERGE partitions -- re-code through a record array
    rec = np.empty(len(per_c[0]), dtype=[
        (f"f{i}", np.int64) for i in range(len(per_c))
    ])
    for i, c in enumerate(per_c):
        rec[f"f{i}"] = c
    _occ, codes = np.unique(rec, return_inverse=True)
    return codes


def multikey_group_codes(frame, keys):
    """(codes, {key: per-group values}) for a multi-key grouping.

    Factorize each key (sorted), combine the codes into one integer
    (row-major over per-key cardinalities), and factorize THAT -- integer
    work end-to-end, so string keys pay the hashtable once each, never a
    tuple sort.  Group order is lexicographic over the key list, like
    ``np.unique`` over a record array would give.
    """
    per_u = []
    per_c = []
    card_product = 1
    for k in keys:
        u, c = _factorize_sorted(np.asarray(frame[k]))
        per_u.append(u)
        per_c.append(c)
        card_product *= max(len(u), 1)
    if card_product < 2**62:
        combined = None
        for u, c in zip(per_u, per_c):
            combined = c if combined is None else combined * len(u) + c
        occupied, codes = np.unique(combined, return_inverse=True)
        rem = occupied
        key_cols = {}
        for k, u in zip(reversed(keys), reversed(per_u)):
            rem, idx = np.divmod(rem, len(u))
            key_cols[k] = u[idx]
    else:
        # cardinality product would overflow int64 (wrapped codes from
        # distinct tuples could collide and silently MERGE groups): sort
        # the per-key code columns as one record array instead -- slower,
        # never wrong
        rec = np.empty(len(per_c[0]), dtype=[
            (f"f{i}", np.int64) for i in range(len(per_c))
        ])
        for i, c in enumerate(per_c):
            rec[f"f{i}"] = c
        occ_rec, codes = np.unique(rec, return_inverse=True)
        key_cols = {
            k: u[occ_rec[f"f{i}"]]
            for i, (k, u) in enumerate(zip(keys, per_u))
        }
    return codes, {k: key_cols[k] for k in keys}


class GroupedFrame:
    """groupBy(...).agg(...): host hash coding + segment reductions.

    Engine routing by backend: on an accelerator the reductions are XLA
    segment ops on device (one fused scatter-add per aggregate, data never
    leaves HBM); on the CPU backend the same reductions run as host
    ``bincount``/``reduceat`` -- a jax dispatch per aggregate costs more
    than the reduction itself there (ROUND3.md's 17x gap to pandas was
    coding + CPU-backend dispatch overhead, not the math).
    """

    def __init__(self, frame: ColumnarFrame, key):
        self._frame = frame
        self._keys = [key] if isinstance(key, str) else list(key)
        self._key = self._keys[0]  # back-compat for single-key callers
        if len(self._keys) == 1:
            keys = np.asarray(frame[self._keys[0]])
            self._uniques, self._codes = _factorize_sorted(keys)
            self._key_columns = {self._keys[0]: self._uniques}
        else:
            self._codes, self._key_columns = multikey_group_codes(
                frame, self._keys
            )
            self._uniques = self._key_columns[self._keys[0]]

    def _host_agg(self, v: np.ndarray, fn: str, n_seg: int):
        codes = self._codes
        # float results cast back to the column dtype so the host and
        # accelerator engines produce IDENTICAL schemas (the device path
        # accumulates/returns in v.dtype)
        if fn == "sum":
            out = np.bincount(codes, weights=v, minlength=n_seg)
            return out.astype(v.dtype)
        if fn == "count":
            return np.bincount(codes, minlength=n_seg).astype(np.int32)
        if fn == "mean":
            s = np.bincount(codes, weights=v, minlength=n_seg)
            c = np.bincount(codes, minlength=n_seg)
            return (s / c).astype(
                v.dtype if v.dtype.kind == "f" else np.float64
            )
        # min/max: sort-based segment reduce (ufunc.at is near-serial)
        order = np.argsort(codes, kind="stable")
        bounds = np.searchsorted(codes[order], np.arange(n_seg), "left")
        red = np.minimum if fn == "min" else np.maximum
        return red.reduceat(np.asarray(v)[order], bounds)

    def agg(self, **spec) -> ColumnarFrame:
        """``gb.agg(total=("v", "sum"), avg=("v", "mean"), n=("v", "count"))``
        -> one row per group, first column the group key."""
        n_seg = len(self._uniques)
        out: Dict[str, object] = dict(self._key_columns)
        codes_dev = None
        for name, (colname, fn) in spec.items():
            v = self._frame[colname]
            if not isinstance(v, jnp.ndarray):
                raise TypeError(
                    f"aggregate over host column {colname!r} unsupported"
                )
            if fn not in _AGGS:
                raise ValueError(f"unknown aggregate {fn!r}; use {_AGGS}")
            if v.device.platform == "cpu":
                out[name] = self._host_agg(np.asarray(v), fn, n_seg)
                continue
            if codes_dev is None:
                codes_dev = jnp.asarray(self._codes)
            if fn == "sum":
                out[name] = jax.ops.segment_sum(v, codes_dev, n_seg)
            elif fn == "count":
                out[name] = jax.ops.segment_sum(
                    jnp.ones_like(v, jnp.int32), codes_dev, n_seg
                )
            elif fn == "mean":
                s = jax.ops.segment_sum(v, codes_dev, n_seg)
                c = jax.ops.segment_sum(jnp.ones_like(v), codes_dev, n_seg)
                out[name] = s / c
            elif fn == "min":
                out[name] = jax.ops.segment_min(v, codes_dev, n_seg)
            elif fn == "max":
                out[name] = jax.ops.segment_max(v, codes_dev, n_seg)
        return ColumnarFrame(out)

    def count(self) -> ColumnarFrame:
        counts = np.bincount(self._codes, minlength=len(self._uniques))
        return ColumnarFrame({**self._key_columns, "count": counts})
