"""SQL text front door: a small parser lowering onto the ColumnarFrame DSL.

Parity: the relational *front door* of the reference's SQL stack --
``sql/catalyst/src/main/scala/.../parser/AstBuilder.scala`` (ANTLR AST ->
logical plan) and ``SparkSession.sql``.  The reference needs 68k lines of
catalyst because it plans lazy trees onto a shuffle engine with codegen;
here the execution layer is the eager columnar frame (``sql/frame.py``)
whose ops are already fused XLA kernels, so the front door reduces to:
tokenize -> recursive-descent parse -> direct lowering.

Supported surface (the queries the reference's examples actually run):

    SELECT expr [AS name], ... | SELECT agg(expr), ...
    FROM table [INNER|LEFT|RIGHT|FULL|SEMI|ANTI] JOIN table2 ON key
    WHERE expr        -- arithmetic/comparison/AND/OR/NOT, strings, NULLs out
    GROUP BY k        -- lowered to the device segment aggregates
    ORDER BY c [ASC|DESC]
    LIMIT n

Aggregates: SUM, AVG, MEAN, MIN, MAX, COUNT(expr|*).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from asyncframework_tpu.sql.expressions import Column, col, lit
from asyncframework_tpu.sql.frame import ColumnarFrame

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<num>\d+\.\d*|\.\d+|\d+)
      | (?P<str>'(?:[^']|'')*')
      | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
      | (?P<op><>|<=|>=|==|!=|[(),*+\-/%<>=.])
    )""",
    re.VERBOSE,
)

_AGG_FNS = {"SUM": "sum", "AVG": "mean", "MEAN": "mean", "MIN": "min",
            "MAX": "max", "COUNT": "count"}
_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "ORDER", "BY", "LIMIT", "AS",
    "AND", "OR", "NOT", "JOIN", "ON", "INNER", "LEFT", "RIGHT", "FULL",
    "OUTER", "SEMI", "ANTI", "ASC", "DESC", "DISTINCT", "HAVING",
    "OVER", "PARTITION",
}

_WINDOW_ONLY_FNS = {
    "ROW_NUMBER": "row_number", "RANK": "rank", "DENSE_RANK": "dense_rank",
    "LAG": "lag", "LEAD": "lead",
}


def tokenize(text: str) -> List[str]:
    out: List[str] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"SQL syntax error near: {rest[:30]!r}")
        pos = m.end()
        tok = m.group().strip()
        if tok:
            out.append(tok)
    return out


class _Parser:
    def __init__(self, tokens: List[str]):
        self.toks = tokens
        self.i = 0

    # ------------------------------------------------------------- utilities
    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def peek_upper(self) -> Optional[str]:
        t = self.peek()
        return t.upper() if t is not None else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of SQL")
        self.i += 1
        return t

    def accept(self, kw: str) -> bool:
        if self.peek_upper() == kw:
            self.i += 1
            return True
        return False

    def expect(self, kw: str) -> None:
        t = self.next()
        if t.upper() != kw:
            raise ValueError(f"expected {kw}, got {t!r}")

    def ident(self) -> str:
        t = self.next()
        if not re.fullmatch(r"[A-Za-z_][A-Za-z_0-9]*", t):
            raise ValueError(f"expected identifier, got {t!r}")
        return t

    # ------------------------------------------------------------ expressions
    def expr(self) -> Column:
        return self._or()

    def _or(self) -> Column:
        e = self._and()
        while self.accept("OR"):
            e = e | self._and()
        return e

    def _and(self) -> Column:
        e = self._not()
        while self.accept("AND"):
            e = e & self._not()
        return e

    def _not(self) -> Column:
        if self.accept("NOT"):
            return ~self._not()
        return self._cmp()

    def _cmp(self) -> Column:
        e = self._add()
        op = self.peek()
        if op in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            rhs = self._add()
            if op in ("=", "=="):
                return e == rhs
            if op in ("!=", "<>"):
                return e != rhs
            return {"<": e < rhs, "<=": e <= rhs,
                    ">": e > rhs, ">=": e >= rhs}[op]
        return e

    def _add(self) -> Column:
        e = self._mul()
        while self.peek() in ("+", "-"):
            if self.next() == "+":
                e = e + self._mul()
            else:
                e = e - self._mul()
        return e

    def _mul(self) -> Column:
        e = self._unary()
        while self.peek() in ("*", "/", "%"):
            op = self.next()
            rhs = self._unary()
            e = e * rhs if op == "*" else (
                e / rhs if op == "/" else e % rhs
            )
        return e

    def _unary(self) -> Column:
        if self.peek() == "-":
            self.next()
            return -self._unary()
        return self._primary()

    def _primary(self) -> Column:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of expression")
        if t == "(":
            self.next()
            e = self.expr()
            self.expect(")")
            return e
        if re.fullmatch(r"\d+\.\d*|\.\d+|\d+", t):
            self.next()
            return lit(float(t) if ("." in t) else int(t))
        if t.startswith("'"):
            self.next()
            return lit(t[1:-1].replace("''", "'"))
        if (
            t.upper() in _AGG_FNS
            and self.i + 1 < len(self.toks)
            and self.toks[self.i + 1] == "("
        ):
            # aggregate-call syntax inside an expression (HAVING SUM(v) > 1)
            # references the aggregated OUTPUT column by its default label
            fn = _AGG_FNS[self.next().upper()]
            self.expect("(")
            arg = "*" if self.peek() == "*" else self.ident()
            if arg == "*":
                self.next()
                fn = "count"
            self.expect(")")
            return col(f"{fn}({arg})")
        name = self.ident()
        if name.upper() in _KEYWORDS:
            raise ValueError(f"unexpected keyword {name!r} in expression")
        # qualified name t.c: the frame is flat, keep the column part
        if self.peek() == ".":
            self.next()
            name = self.ident()
        return col(name)

    # --------------------------------------------------------------- clauses
    def select_items(self) -> List[Tuple[str, Any]]:
        """[(kind, payload)]: ('star', None) | ('agg', (fn, colname, out))
        | ('expr', (Column, out))."""
        items: List[Tuple[str, Any]] = []
        while True:
            if self.peek() == "*":
                self.next()
                items.append(("star", None))
            elif (
                self.peek_upper() in _WINDOW_ONLY_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1] == "("
            ):
                wfn = _WINDOW_ONLY_FNS[self.next().upper()]
                self.expect("(")
                warg = None
                woffset = 1
                if wfn in ("lag", "lead"):
                    warg = self.ident()
                    if self.accept(","):
                        woffset = int(self.next())
                self.expect(")")
                spec = self._over_clause()
                out = wfn
                if self.accept("AS"):
                    out = self.ident()
                items.append(
                    ("window", (wfn, warg, woffset, spec, out))
                )
            elif (
                self.peek_upper() in _AGG_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1] == "("
            ):
                fn = _AGG_FNS[self.next().upper()]
                self.expect("(")
                if self.peek() == "*":
                    self.next()
                    arg = None  # COUNT(*)
                else:
                    # full expression allowed: SUM(v * 2); a bare column
                    # reference stays a plain name, anything else (incl.
                    # literals like COUNT(1)) is a Column the lowering
                    # materializes first
                    start = self.i
                    e = self.expr()
                    ident_re = r"[A-Za-z_][A-Za-z_0-9]*"
                    if self.i == start + 1 and re.fullmatch(
                        ident_re, self.toks[start]
                    ):
                        arg = self.toks[start]
                    elif self.i == start + 3 and self.toks[start + 1] == ".":
                        arg = self.toks[start + 2]
                    else:
                        arg = e
                self.expect(")")
                if self.peek_upper() == "OVER":
                    # aggregate as a WINDOW function: SUM(v) OVER (...)
                    if not isinstance(arg, str) and arg is not None:
                        raise ValueError(
                            "window aggregates take a bare column argument"
                        )
                    spec = self._over_clause()
                    out = fn if arg is None else f"{fn}_{arg}"
                    if self.accept("AS"):
                        out = self.ident()
                    items.append(("window", (fn, arg, 1, spec, out)))
                    if not self.accept(","):
                        return items
                    continue
                # unaliased labels must be unique per item or later spec
                # entries silently overwrite earlier ones
                label = arg if isinstance(arg, str) else (
                    f"expr#{len(items)}" if arg is not None else "*"
                )
                out = f"{fn}({label})"
                if self.accept("AS"):
                    out = self.ident()
                items.append(("agg", (fn, arg, out)))
            else:
                start = self.i
                e = self.expr()
                out = e.name
                # a bare column reference keeps its own name
                if self.i == start + 1:
                    out = self.toks[start]
                elif self.i == start + 3 and self.toks[start + 1] == ".":
                    out = self.toks[start + 2]
                if self.accept("AS"):
                    out = self.ident()
                items.append(("expr", (e, out)))
            if not self.accept(","):
                return items

    def _over_clause(self) -> Tuple[Optional[str], Optional[str], bool]:
        """OVER ( [PARTITION BY k] [ORDER BY c [ASC|DESC]] ) ->
        (partition_by, order_by, ascending)."""
        self.expect("OVER")
        self.expect("(")
        partition_by = None
        order_by = None
        ascending = True
        if self.accept("PARTITION"):
            self.expect("BY")
            partition_by = self.ident()
        if self.accept("ORDER"):
            self.expect("BY")
            order_by = self.ident()
            if self.accept("DESC"):
                ascending = False
            else:
                self.accept("ASC")
        self.expect(")")
        return partition_by, order_by, ascending


class SQLContext:
    """Table registry + ``sql()`` entry point (SparkSession.sql analog)."""

    def __init__(self):
        self._tables: Dict[str, ColumnarFrame] = {}

    def register(self, name: str, frame: ColumnarFrame) -> None:
        """``createOrReplaceTempView`` analog."""
        self._tables[name.lower()] = frame

    def table(self, name: str) -> ColumnarFrame:
        key = name.lower()
        if key not in self._tables:
            raise KeyError(
                f"no table {name!r}; registered: {sorted(self._tables)}"
            )
        return self._tables[key]

    # ----------------------------------------------------------------- query
    def sql(self, text: str) -> ColumnarFrame:
        p = _Parser(tokenize(text))
        p.expect("SELECT")
        distinct = p.accept("DISTINCT")
        items = p.select_items()
        p.expect("FROM")
        frame = self.table(p.ident())

        # joins
        while True:
            how = "inner"
            if p.peek_upper() in ("INNER", "LEFT", "RIGHT", "FULL",
                                  "SEMI", "ANTI"):
                how = p.next().lower()
                p.accept("OUTER")
                p.expect("JOIN")
            elif p.peek_upper() == "JOIN":
                p.next()
            else:
                break
            right = self.table(p.ident())
            p.expect("ON")
            k1 = p.ident()
            if p.peek() == ".":
                p.next()
                k1 = p.ident()
            key = k1
            if p.accept("="):
                k2 = p.ident()
                if p.peek() == ".":
                    p.next()
                    k2 = p.ident()
                if k2 != k1:
                    raise ValueError(
                        f"equi-join keys must share a name: {k1!r} != {k2!r}"
                    )
            frame = frame.join(right, on=key, how=how)

        if p.accept("WHERE"):
            frame = frame.filter(p.expr())

        group_key = None
        having = None
        if p.accept("GROUP"):
            p.expect("BY")
            group_key = p.ident()
            if p.accept("HAVING"):
                # HAVING filters the AGGREGATED result, so its expression
                # references OUTPUT column names (the group key, aggregate
                # labels like sum(v), or AS aliases) -- the documented
                # subset; raw-aggregate syntax inside HAVING is not re-parsed
                having = p.expr()

        order_by = None
        ascending = True
        if p.accept("ORDER"):
            p.expect("BY")
            order_by = p.ident()
            if p.accept("DESC"):
                ascending = False
            else:
                p.accept("ASC")

        limit = None
        if p.accept("LIMIT"):
            limit = int(p.next())

        if p.peek() is not None:
            raise ValueError(f"trailing SQL tokens: {self_rest(p)}")

        if (
            order_by is not None
            and group_key is None
            and not aggs_present(items)
            and order_by in frame.columns
        ):
            # standard SQL: ORDER BY may reference an unprojected source
            # column -- sorting the source BEFORE projecting covers both
            # source columns and pass-through selections in one projection
            # (projection preserves row order)
            frame = frame.sort(order_by, ascending=ascending)
            order_by = None
        frame = self._project(frame, items, group_key)
        if having is not None:
            # HAVING may reference an aggregate by its CALL syntax (default
            # label "fn(arg)") even when the SELECT aliased it -- bridge the
            # default labels onto the aliased output columns for the filter,
            # then drop the bridges
            bridges = {}
            for kind, it in items:
                if kind != "agg":
                    continue
                fn, arg, out = it
                default = (
                    f"{fn}({arg})" if isinstance(arg, str)
                    else ("count(*)" if arg is None else None)
                )
                if (
                    default is not None
                    and default != out
                    and default not in frame.columns
                    and out in frame.columns
                ):
                    bridges[default] = out
            for default, out in bridges.items():
                frame = frame.with_column(default, col(out))
            frame = frame.filter(having)
            if bridges:
                frame = frame.select(
                    *[c for c in frame.columns if c not in bridges]
                )
        if distinct:
            frame = frame.distinct()
        if order_by is not None:
            if order_by not in frame.columns:
                raise ValueError(
                    f"ORDER BY {order_by!r}: not a result column"
                    + ("" if group_key is None else
                       " (aggregated queries sort by output columns only)")
                )
            frame = frame.sort(order_by, ascending=ascending)
        if limit is not None:
            frame = _limit(frame, limit)
        return frame

    # ---------------------------------------------------------------- lowering
    def _project(self, frame, items, group_key):
        aggs = [it for kind, it in items if kind == "agg"]
        exprs = [(e, name) for kind, (e, name) in (
            (k, v) for k, v in items if k == "expr"
        )]
        has_star = any(kind == "star" for kind, _ in items)
        windows = [it for kind, it in items if kind == "window"]

        if windows:
            if group_key is not None or aggs:
                raise ValueError(
                    "window functions cannot mix with GROUP BY aggregates"
                )
            for fn, arg, offset, (pby, oby, asc), out in windows:
                frame = frame.with_window(
                    out, fn, arg, partition_by=pby, order_by=oby,
                    ascending=asc, offset=offset,
                )
            if has_star:
                if not exprs:
                    return frame
                # star + extra expressions: same contract as the
                # non-window star path -- source columns, then windows,
                # then non-colliding aliased expressions
                sel = list(frame.columns) + [
                    e.alias(name) for e, name in exprs
                    if name not in frame.columns
                ]
                return frame.select(*sel)
            sel = []
            for kind, it in items:
                if kind == "expr":
                    sel.append(it[0].alias(it[1]))
                else:
                    sel.append(it[4])
            return frame.select(*sel)

        if group_key is not None:
            # SELECT key?, aggs FROM ... GROUP BY key
            if has_star:
                raise ValueError(
                    "SELECT * is not valid with GROUP BY; name the "
                    "group key and aggregates explicitly"
                )
            for e, name in exprs:
                if name != group_key:
                    raise ValueError(
                        "non-aggregate select item "
                        f"{name!r} must be the GROUP BY key"
                    )
            frame, spec = _agg_spec(frame, aggs)
            gb = frame.groupby(group_key)
            if not spec:
                return gb.count()
            return gb.agg(**spec)

        if aggs:
            if exprs or has_star:
                raise ValueError(
                    "mixing aggregates and plain columns needs GROUP BY"
                )
            frame, spec = _agg_spec(frame, aggs)
            scalars = frame.agg(**spec)
            return ColumnarFrame(
                {k: np.asarray([v]) for k, v in scalars.items()}
            )

        if has_star and not exprs:
            return frame
        if has_star:
            sel = list(frame.columns) + [
                e.alias(name) for e, name in exprs
                if name not in frame.columns
            ]
            return frame.select(*sel)
        return frame.select(*[e.alias(name) for e, name in exprs])


def aggs_present(items) -> bool:
    return any(kind == "agg" for kind, _ in items)


def _agg_spec(frame: ColumnarFrame, aggs):
    """Resolve aggregate arguments: bare columns pass through, expression
    arguments are materialized as temp columns, COUNT(*) counts rows."""
    spec = {}
    for i, (fn, arg, out) in enumerate(aggs):
        if arg is None:  # COUNT(*): count over any device column
            arg = _any_device_column(frame)
            fn = "count"
        elif isinstance(arg, Column):
            tmp = f"__agg_{i}"
            frame = frame.with_column(tmp, arg)
            arg = tmp
        spec[out] = (arg, fn)
    return frame, spec


def _any_device_column(frame: ColumnarFrame) -> str:
    import jax.numpy as jnp

    for name in frame.columns:
        if isinstance(frame[name], jnp.ndarray):
            return name
    raise ValueError("COUNT(*) needs at least one numeric column")


def _limit(frame: ColumnarFrame, n: int) -> ColumnarFrame:
    return frame._take(np.arange(min(n, len(frame))))


def self_rest(p: _Parser) -> str:
    return " ".join(p.toks[p.i : p.i + 8])


def sql(text: str, **tables: ColumnarFrame) -> ColumnarFrame:
    """One-shot convenience: ``sql("SELECT ...", t=frame)``."""
    ctx = SQLContext()
    for name, frame in tables.items():
        ctx.register(name, frame)
    return ctx.sql(text)
