"""SQL text front door: a small parser lowering onto the ColumnarFrame DSL.

Parity: the relational *front door* of the reference's SQL stack --
``sql/catalyst/src/main/scala/.../parser/AstBuilder.scala`` (ANTLR AST ->
logical plan) and ``SparkSession.sql``.  The reference needs 68k lines of
catalyst because it plans lazy trees onto a shuffle engine with codegen;
here the execution layer is the eager columnar frame (``sql/frame.py``)
whose ops are already fused XLA kernels, so the front door reduces to:
tokenize -> recursive-descent parse -> direct lowering.

Supported surface:

    [WITH name AS (query) [, ...]]
    SELECT [DISTINCT] expr [AS name] | agg(expr) | fn(args) | wfn() OVER ..
    FROM table | (query) [AS alias]
         [INNER|LEFT|RIGHT|FULL|SEMI|ANTI] JOIN t2 ON key
    WHERE expr     -- AND/OR/NOT, comparisons, BETWEEN, IN (list|subquery),
                   -- LIKE, IS [NOT] NULL, CASE WHEN, CAST, scalar subqueries
    GROUP BY k [, k2 ...] [HAVING expr]
    ORDER BY c [ASC|DESC] [, c2 ...]
    LIMIT n
    query UNION [ALL] query | EXCEPT | INTERSECT   (left-associative)

Aggregates: SUM, AVG, MEAN, MIN, MAX, COUNT(expr|*).  Scalar functions:
the ``expressions.FUNCTIONS`` library (ABS/SQRT/.../UPPER/SUBSTR/COALESCE)
plus user UDFs via ``SQLContext.register_udf`` (row-wise python, the same
contract as the reference's python UDFs).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from asyncframework_tpu.sql.expressions import (
    CaseBuilder,
    Column,
    FUNCTIONS,
    call_function,
    col,
    lit,
    udf_column,
    when,
)
from asyncframework_tpu.sql.frame import ColumnarFrame
from asyncframework_tpu.sql.io import LazyTable, lazy_csv, lazy_json, lazy_parquet
from asyncframework_tpu.sql import plan as _plan

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<num>\d+\.\d*|\.\d+|\d+)
      | (?P<str>'(?:[^']|'')*')
      | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
      | (?P<op><>|<=|>=|==|!=|[(),*+\-/%<>=.])
    )""",
    re.VERBOSE,
)

_AGG_FNS = {"SUM": "sum", "AVG": "mean", "MEAN": "mean", "MIN": "min",
            "MAX": "max", "COUNT": "count"}
_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "ORDER", "BY", "LIMIT", "AS",
    "AND", "OR", "NOT", "JOIN", "ON", "INNER", "LEFT", "RIGHT", "FULL",
    "OUTER", "SEMI", "ANTI", "ASC", "DESC", "DISTINCT", "HAVING",
    "OVER", "PARTITION", "UNION", "ALL", "EXCEPT", "INTERSECT", "CASE",
    "WHEN", "THEN", "ELSE", "END", "BETWEEN", "IN", "LIKE", "IS", "NULL",
    "CAST", "WITH", "EXPLAIN", "CREATE", "REPLACE", "TEMP", "VIEW", "DROP",
}

_WINDOW_ONLY_FNS = {
    "ROW_NUMBER": "row_number", "RANK": "rank", "DENSE_RANK": "dense_rank",
    "LAG": "lag", "LEAD": "lead",
}

_SET_OPS = {"UNION", "EXCEPT", "INTERSECT"}


class _NotPlannable(Exception):
    """Internal: this SELECT shape needs the eager lowering (HAVING label
    bridges, ORDER BY borrowing unprojected source columns, ...).  The
    parser rewinds and re-parses eagerly; never escapes the parser."""


def tokenize(text: str) -> List[str]:
    out: List[str] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"SQL syntax error near: {rest[:30]!r}")
        pos = m.end()
        tok = m.group().strip()
        if tok:
            out.append(tok)
    return out


class _Parser:
    def __init__(self, tokens: List[str], ctx: "SQLContext"):
        self.toks = tokens
        self.i = 0
        self.ctx = ctx
        self.local_tables: Dict[str, ColumnarFrame] = {}  # CTE scope

    # ------------------------------------------------------------- utilities
    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def peek_upper(self) -> Optional[str]:
        t = self.peek()
        return t.upper() if t is not None else None

    def peek2_upper(self) -> Optional[str]:
        j = self.i + 1
        return self.toks[j].upper() if j < len(self.toks) else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of SQL")
        self.i += 1
        return t

    def accept(self, kw: str) -> bool:
        if self.peek_upper() == kw:
            self.i += 1
            return True
        return False

    def expect(self, kw: str) -> None:
        t = self.next()
        if t.upper() != kw:
            raise ValueError(f"expected {kw}, got {t!r}")

    def ident(self) -> str:
        t = self.next()
        if not re.fullmatch(r"[A-Za-z_][A-Za-z_0-9]*", t):
            raise ValueError(f"expected identifier, got {t!r}")
        return t

    def _resolve_table(self, name: str):
        """Raw registry entry: an eager frame or a LazyTable (kept lazy so
        the optimizer can push work into its reader)."""
        key = name.lower()
        if key in self.local_tables:  # CTEs shadow registered tables
            return self.local_tables[key]
        if key not in self.ctx._tables:
            raise KeyError(
                f"no table {name!r}; registered: {sorted(self.ctx._tables)}"
            )
        return self.ctx._tables[key]

    # ------------------------------------------------------------ statements
    def statement(self) -> ColumnarFrame:
        """[WITH ...] set-expression -- the top-level entry.  Builds the
        full logical plan (CTEs as execute-once Shared nodes, derived
        tables lazy), optimizes, executes."""
        node = self.statement_plan()
        node = _plan.optimize(node, None)
        return _plan.execute(node)

    def statement_plan(self) -> "_plan.Node":
        if self.accept("WITH"):
            while True:
                name = self.ident()
                self.expect("AS")
                self.expect("(")
                sub = self._nested_statement_plan()  # sees earlier CTEs
                self.expect(")")
                # every FROM reference shares this instance: the body
                # executes at most once per statement (InlineCTE's
                # with-materialization side; single-use bodies inline in
                # plan.optimize so rewrites cross them)
                self.local_tables[name.lower()] = _plan.Shared(
                    sub, name=name.lower()
                )
                if not self.accept(","):
                    break
        return self.set_expr_plan()

    def _nested_statement_plan(self) -> "_plan.Node":
        """A statement inside a subquery/CTE body/derived table: its own
        WITH names are SCOPED to it -- they must neither leak into nor
        shadow the enclosing query's CTEs after it closes."""
        saved = dict(self.local_tables)
        try:
            return self.statement_plan()
        finally:
            self.local_tables = saved

    def _nested_statement(self) -> ColumnarFrame:
        """A statement in VALUE position (IN (...) / scalar subquery):
        plan, optimize, execute now -- its result folds into the enclosing
        expression as data.  Shared CTE boundaries stay intact
        (inline_shared=False) so executing one here populates the
        statement-wide cache instead of running a private inlined copy."""
        node = self._nested_statement_plan()
        node = _plan.optimize(node, None, inline_shared=False)
        return _plan.execute(node)

    def set_expr_plan(self) -> "_plan.Node":
        left = self._select_plan()
        seen_set_op = False
        while self.peek_upper() in _SET_OPS:
            seen_set_op = True
            op = self.next().upper()
            keep_all = op == "UNION" and self.accept("ALL")
            # a set-op operand may not consume ORDER BY/LIMIT: a trailing
            # ORDER BY applies to the WHOLE set expression (standard SQL)
            right = self._select_plan(consume_order=False)
            opname = ("union_all" if keep_all else
                      "union" if op == "UNION" else
                      "except" if op == "EXCEPT" else "intersect")
            left = _plan.SetOp(left, right, op=opname)
        if seen_set_op:
            if self.accept("ORDER"):
                self.expect("BY")
                by, asc = self._order_list()
                cols = _plan.node_columns(left)
                if cols is not None:
                    missing = [c for c in by if c not in cols]
                    if missing:
                        raise ValueError(
                            f"ORDER BY {missing[0]!r}: not a result column"
                        )
                left = _plan.Sort(left, by, asc)
            if self.accept("LIMIT"):
                left = _plan.Limit(left, int(self.next()))
        return left

    def _select_plan(self, consume_order: bool = True) -> "_plan.Node":
        """One select-core as a plan node; falls back to the eager lowering
        (rewinding the token stream) for the shapes the plan builder
        declines."""
        start = self.i
        saved_locals = dict(self.local_tables)
        try:
            return self._try_select_plan(consume_order)
        except _NotPlannable:
            self.i = start
            self.local_tables = saved_locals
            frame = self._select_eager(consume_order)
            return _plan.Scan("(eager)", frame=frame)

    def _join_key(self) -> str:
        """One equi-join key: ``k`` | ``t.k`` | ``k = k`` | ``t1.k = t2.k``
        (the two sides must share the column name)."""
        k1 = self.ident()
        if self.peek() == ".":
            self.next()
            k1 = self.ident()
        if self.peek() in ("<", "<=", ">", ">=", "!=", "<>"):
            # say it plainly instead of a downstream KeyError/trailing-token
            raise ValueError(
                f"ON supports equi-join conjuncts only (k = k); "
                f"{k1!r} {self.peek()} ... is not an equi-join -- express "
                "range conditions in WHERE"
            )
        if self.accept("="):
            k2 = self.ident()
            if self.peek() == ".":
                self.next()
                k2 = self.ident()
            if k2 != k1:
                raise ValueError(
                    f"equi-join keys must share a name: {k1!r} != {k2!r}"
                )
        return k1

    def _order_list(self):
        """Parse ``c [ASC|DESC] [, c2 ...]`` after ORDER BY."""
        cols, asc = [], []
        while True:
            cols.append(self.ident())
            if self.accept("DESC"):
                asc.append(False)
            else:
                self.accept("ASC")
                asc.append(True)
            if not self.accept(","):
                return cols, asc

    def _parse_select_clauses(self, consume_order: bool = True) -> dict:
        """The select-core clause grammar, shared by the plan builder and
        the eager fallback (ONE definition: the fallback re-parses the same
        language).  Starts at SELECT; the FROM/JOIN/WHERE core arrives as a
        plan node."""
        self.expect("SELECT")
        distinct = self.accept("DISTINCT")
        items = self.select_items()
        self.expect("FROM")
        node = self._from_item()
        while True:
            how = "inner"
            if self.peek_upper() in ("INNER", "LEFT", "RIGHT", "FULL",
                                     "SEMI", "ANTI"):
                how = self.next().lower()
                self.accept("OUTER")
                self.expect("JOIN")
            elif self.peek_upper() == "JOIN":
                self.next()
            else:
                break
            right = self._from_item()
            self.expect("ON")
            join_keys = [self._join_key()]
            while self.accept("AND"):
                join_keys.append(self._join_key())
            node = _plan.Join(
                node, right,
                on=join_keys[0] if len(join_keys) == 1 else join_keys,
                how=how,
            )
        if self.accept("WHERE"):
            node = _plan.Filter(node, self.expr())
        group_key = None
        having = None
        if self.accept("GROUP"):
            self.expect("BY")
            group_key = [self.ident()]
            while self.accept(","):
                group_key.append(self.ident())
            if len(group_key) == 1:
                group_key = group_key[0]
            if self.accept("HAVING"):
                # HAVING filters the AGGREGATED result, so its expression
                # references OUTPUT column names (the group key, aggregate
                # labels like sum(v), or AS aliases)
                having = self.expr()
        order_by = None
        ascending = True
        if consume_order and self.accept("ORDER"):
            self.expect("BY")
            order_by, ascending = self._order_list()
        limit = None
        if consume_order and self.accept("LIMIT"):
            limit = int(self.next())
        return dict(
            node=node, items=items, distinct=distinct,
            group_key=group_key, having=having,
            order_by=order_by, ascending=ascending, limit=limit,
        )

    def _try_select_plan(self, consume_order: bool = True) -> "_plan.Node":
        """Parse one select-core into a COMPLETE plan (projection, windows,
        aggregation, HAVING, DISTINCT, ORDER BY, LIMIT all as nodes), so
        the optimizer's rewrites cross every clause and derived tables stay
        lazy.  Raises _NotPlannable for shapes only the eager path lowers."""
        if self.peek() == "(":
            self.next()
            node = self._nested_statement_plan()
            self.expect(")")
            return node
        c = self._parse_select_clauses(consume_order)
        return self._build_select_plan(
            c["node"], c["items"], c["distinct"], c["group_key"],
            c["having"], c["order_by"], c["ascending"], c["limit"],
        )

    def _build_select_plan(self, node, items, distinct, group_key, having,
                           order_by, ascending, limit) -> "_plan.Node":
        aggs = [it for kind, it in items if kind == "agg"]
        exprs = [it for kind, it in items if kind == "expr"]
        has_star = any(kind == "star" for kind, _ in items)
        windows = [it for kind, it in items if kind == "window"]

        # pre-projection source sort (standard SQL: ORDER BY may reference
        # an unprojected source column; projection preserves row order) --
        # same precedence as the eager path
        core_cols = _plan.node_columns(node)
        if (
            order_by is not None
            and group_key is None
            and not aggs
        ):
            if core_cols is None:
                raise _NotPlannable("unknown core schema under ORDER BY")
            if all(c in core_cols for c in order_by):
                node = _plan.Sort(node, list(order_by), list(ascending))
                order_by = None

        if windows:
            if group_key is not None or aggs:
                raise ValueError(
                    "window functions cannot mix with GROUP BY aggregates"
                )
            node = _plan.Window(node, list(windows))
            if has_star:
                extra = [(e, out) for (e, out, _bare) in exprs]
                if extra:
                    node = _plan.Compute(node, extra, star=True)
            else:
                plist = []
                passthrough = set()
                for kind, it in items:
                    if kind == "expr":
                        e, out, bare = it
                        plist.append((e, out))
                        if bare is not None and bare == out:
                            passthrough.add(out)
                    elif kind == "window":
                        out = it[4]
                        plist.append((col(out), out))
                        passthrough.add(out)
                node = _plan.Compute(node, plist, star=False,
                                     passthrough=frozenset(passthrough))
        elif group_key is not None:
            if has_star:
                raise ValueError(
                    "SELECT * is not valid with GROUP BY; name the "
                    "group key and aggregates explicitly"
                )
            keys = group_key if isinstance(group_key, list) else [group_key]
            for _e, out, _bare in exprs:
                if out not in keys:
                    raise ValueError(
                        "non-aggregate select item "
                        f"{out!r} must be a GROUP BY key"
                    )
            node, spec = self._plan_agg_spec(node, aggs)
            node = _plan.Aggregate(node, group_key, spec)
            if having is not None:
                out_cols = _plan.node_columns(node)
                refs = getattr(having, "refs", None)
                if refs is None or out_cols is None:
                    raise _NotPlannable("HAVING refs unknown")
                missing = set(refs) - set(out_cols)
                if missing:
                    # HAVING references an aggregate by its CALL-syntax
                    # default label while the SELECT aliased it: bridge the
                    # labels onto the aliased outputs, filter, drop the
                    # bridges (as plan nodes -- no eager fallback)
                    bridges = {}
                    for fn, arg, out in aggs:
                        default = (
                            f"{fn}({arg})" if isinstance(arg, str)
                            else ("count(*)" if arg is None else None)
                        )
                        if (
                            default is not None and default != out
                            and default in missing and out in out_cols
                        ):
                            bridges[default] = out
                    if missing - set(bridges):
                        raise _NotPlannable("HAVING unknown columns")
                    node = _plan.Compute(
                        node,
                        [(col(out), default)
                         for default, out in bridges.items()],
                        star=True,
                    )
                    node = _plan.Filter(node, having)
                    node = _plan.Project(node, list(out_cols))
                else:
                    node = _plan.Filter(node, having)
        elif aggs:
            if exprs or has_star:
                raise ValueError(
                    "mixing aggregates and plain columns needs GROUP BY"
                )
            node, spec = self._plan_agg_spec(node, aggs)
            node = _plan.Aggregate(node, None, spec)
        else:
            if has_star:
                extra = [(e, out) for (e, out, _bare) in exprs]
                if extra:
                    node = _plan.Compute(node, extra, star=True)
            else:
                plist = [(e, out) for (e, out, _bare) in exprs]
                passthrough = frozenset(
                    out for (_e, out, bare) in exprs
                    if bare is not None and bare == out
                )
                node = _plan.Compute(node, plist, star=False,
                                     passthrough=passthrough)

        if distinct:
            node = _plan.Distinct(node)
        if order_by is not None:
            out_cols = _plan.node_columns(node)
            if out_cols is None:
                raise _NotPlannable("unknown output schema under ORDER BY")
            missing = [c for c in order_by if c not in out_cols]
            if not missing:
                node = _plan.Sort(node, list(order_by), list(ascending))
            elif (
                group_key is None and not aggs and not distinct
                and core_cols is not None
                and all(c in core_cols for c in missing)
                and isinstance(node, _plan.Compute) and not node.star
            ):
                # ORDER BY mixing output aliases with unprojected source
                # columns: borrow the source columns THROUGH the projection
                # for the sort, then drop them (projection preserves row
                # order, so the borrowed values stay row-aligned)
                final_cols = [o for _e, o in node.exprs]
                node.exprs = list(node.exprs) + [
                    (col(c), c) for c in missing
                ]
                node.passthrough = frozenset(
                    set(node.passthrough) | set(missing)
                )
                node = _plan.Sort(node, list(order_by), list(ascending))
                node = _plan.Project(node, final_cols)
            else:
                raise _NotPlannable("ORDER BY outside result columns")
        if limit is not None:
            node = _plan.Limit(node, limit)
        return node

    def _plan_agg_spec(self, node, aggs):
        """Plan analog of ``_agg_spec``: Column-expression arguments
        materialize as temp columns via a star Compute below the
        Aggregate; COUNT(*) carries colname None, resolved at execution."""
        spec = {}
        temps = []
        for i, (fn, arg, out) in enumerate(aggs):
            if arg is None:
                spec[out] = (None, fn)
            elif isinstance(arg, Column):
                tmp = f"__agg_{i}"
                temps.append((arg, tmp))
                spec[out] = (tmp, fn)
            else:
                spec[out] = (arg, fn)
        if temps:
            node = _plan.Compute(node, temps, star=True)
        return node, spec

    def _select_eager(self, consume_order: bool = True) -> ColumnarFrame:
        """The eager lowering for shapes the plan builder declines (HAVING
        label bridges, ORDER BY borrowing unprojected source columns).
        Clause grammar is the SHARED ``_parse_select_clauses`` -- the
        fallback parses the same language by construction."""
        if self.peek() == "(":
            self.next()
            f = self._nested_statement()
            self.expect(")")
            return f
        c = self._parse_select_clauses(consume_order)
        node = c["node"]
        items = c["items"]
        distinct = c["distinct"]
        group_key = c["group_key"]
        having = c["having"]
        order_by = c["order_by"]
        ascending = c["ascending"]
        limit = c["limit"]

        # rewrite the FROM/JOIN/WHERE core before executing: predicate
        # pushdown (through joins, into readers) + projection pruning
        # (Optimizer.scala:38 role; see sql/plan.py)
        node = _plan.optimize(
            node, _required_source_columns(items, group_key, order_by)
        )
        frame = _plan.execute(node)

        if (
            order_by is not None
            and group_key is None
            and not aggs_present(items)
            and all(c in frame.columns for c in order_by)
        ):
            # standard SQL: ORDER BY may reference an unprojected source
            # column -- sorting the source BEFORE projecting covers both
            # source columns and pass-through selections in one projection
            # (projection preserves row order)
            frame = frame.sort(order_by, ascending=ascending)
            order_by = None
        source_frame = frame  # for ORDER BY columns mixing source + alias
        frame = self._project(frame, items, group_key)
        if having is not None:
            # HAVING may reference an aggregate by its CALL syntax (default
            # label "fn(arg)") even when the SELECT aliased it -- bridge the
            # default labels onto the aliased output columns for the filter,
            # then drop the bridges
            bridges = {}
            for kind, it in items:
                if kind != "agg":
                    continue
                fn, arg, out = it
                default = (
                    f"{fn}({arg})" if isinstance(arg, str)
                    else ("count(*)" if arg is None else None)
                )
                if (
                    default is not None
                    and default != out
                    and default not in frame.columns
                    and out in frame.columns
                ):
                    bridges[default] = out
            for default, out in bridges.items():
                frame = frame.with_column(default, col(out))
            frame = frame.filter(having)
            if bridges:
                frame = frame.select(
                    *[c for c in frame.columns if c not in bridges]
                )
        if distinct:
            frame = frame.distinct()
        if order_by is not None:
            missing = [c for c in order_by if c not in frame.columns]
            borrowed = []
            if (
                missing
                and group_key is None
                and not aggs_present(items)
                and not distinct
                and all(c in source_frame.columns for c in missing)
                and len(source_frame) == len(frame)
            ):
                # ORDER BY mixing SELECT aliases with unprojected source
                # columns: projection preserved row order, so the missing
                # columns ride along for the sort and drop after
                from asyncframework_tpu.sql.frame import ColumnarFrame as _CF

                cols = {c: frame[c] for c in frame.columns}
                for c in missing:
                    cols[c] = source_frame[c]
                frame = _CF(cols)
                borrowed = missing
                missing = []
            if missing:
                raise ValueError(
                    f"ORDER BY {missing[0]!r}: not a result column"
                    + ("" if group_key is None else
                       " (aggregated queries sort by output columns only)")
                )
            frame = frame.sort(order_by, ascending=ascending)
            if borrowed:
                frame = frame.select(
                    *[c for c in frame.columns if c not in borrowed]
                )
        if limit is not None:
            frame = _limit(frame, limit)
        return frame

    def _from_item(self) -> "_plan.Node":
        """table name | ( query ) [AS alias] -> a plan node.  Derived
        tables stay LAZY (their sub-plan joins the enclosing plan, so
        pushdown/pruning cross the boundary); CTE references return the
        statement's execute-once Shared node; registered lazy sources stay
        lazy so pushdown reaches the reader."""
        if self.peek() == "(":
            self.next()
            sub = self._nested_statement_plan()
            self.expect(")")
            if self.accept("AS"):
                self.ident()  # alias accepted; frames are flat, name unused
            elif (
                self.peek() is not None
                and self.peek_upper() not in _KEYWORDS
                and re.fullmatch(r"[A-Za-z_][A-Za-z_0-9]*", self.peek())
            ):
                self.next()  # bare alias
            return sub
        name = self.ident()
        t = self._resolve_table(name)
        if isinstance(t, _plan.Shared):
            return t  # the SAME node every reference: body runs once
        if isinstance(t, LazyTable):
            return _plan.Scan(name, reader=t.reader, schema=t.schema)
        return _plan.Scan(name, frame=t)

    def _subquery_values(self) -> np.ndarray:
        """A subquery used as a value source (IN / scalar): must produce
        exactly one column."""
        f = self._nested_statement()
        if len(f.columns) != 1:
            raise ValueError(
                f"subquery must return one column, got {f.columns}"
            )
        return np.asarray(f[f.columns[0]])

    # ------------------------------------------------------------ expressions
    def expr(self) -> Column:
        return self._or()

    def _or(self) -> Column:
        e = self._and()
        while self.accept("OR"):
            e = e | self._and()
        return e

    def _and(self) -> Column:
        e = self._not()
        while self.accept("AND"):
            e = e & self._not()
        return e

    def _not(self) -> Column:
        if (
            self.peek_upper() == "NOT"
            and self.peek2_upper() not in ("BETWEEN", "IN", "LIKE")
        ):
            self.next()
            return ~self._not()
        return self._cmp()

    def _cmp(self) -> Column:
        e = self._add()
        negate = False
        if (
            self.peek_upper() == "NOT"
            and self.peek2_upper() in ("BETWEEN", "IN", "LIKE")
        ):
            self.next()
            negate = True
        t = self.peek_upper()
        if t == "BETWEEN":
            self.next()
            lo = self._add()
            self.expect("AND")
            hi = self._add()
            e = e.between(lo, hi)
        elif t == "IN":
            self.next()
            self.expect("(")
            if self.peek_upper() in ("SELECT", "WITH"):
                values = self._subquery_values()
                self.expect(")")
                e = e.isin(values.tolist())
            else:
                vals = []
                while True:
                    vals.append(self.expr()({}))  # literals evaluate closed
                    if not self.accept(","):
                        break
                self.expect(")")
                e = e.isin(vals)
        elif t == "LIKE":
            self.next()
            pat = self.next()
            if not pat.startswith("'"):
                raise ValueError("LIKE needs a string literal pattern")
            e = e.like(pat[1:-1].replace("''", "'"))
        elif t == "IS":
            self.next()
            neg = self.accept("NOT")
            self.expect("NULL")
            e = e.is_null()
            if neg:
                e = ~e
        else:
            op = self.peek()
            if op in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
                self.next()
                rhs = self._add()
                if op in ("=", "=="):
                    e = e == rhs
                elif op in ("!=", "<>"):
                    e = e != rhs
                else:
                    e = {"<": e < rhs, "<=": e <= rhs,
                         ">": e > rhs, ">=": e >= rhs}[op]
        if negate:
            e = ~e
        return e

    def _add(self) -> Column:
        e = self._mul()
        while self.peek() in ("+", "-"):
            if self.next() == "+":
                e = e + self._mul()
            else:
                e = e - self._mul()
        return e

    def _mul(self) -> Column:
        e = self._unary()
        while self.peek() in ("*", "/", "%"):
            op = self.next()
            rhs = self._unary()
            e = e * rhs if op == "*" else (
                e / rhs if op == "/" else e % rhs
            )
        return e

    def _unary(self) -> Column:
        if self.peek() == "-":
            self.next()
            return -self._unary()
        return self._primary()

    def _case_expr(self) -> Column:
        """CASE [base] WHEN v THEN r ... [ELSE d] END (searched + simple)."""
        base = None
        if self.peek_upper() != "WHEN":
            base = self.expr()
        builder: Optional[CaseBuilder] = None
        while self.accept("WHEN"):
            cond = self.expr()
            if base is not None:
                cond = base == cond
            self.expect("THEN")
            val = self.expr()
            builder = (when(cond, val) if builder is None
                       else builder.when(cond, val))
        if builder is None:
            raise ValueError("CASE needs at least one WHEN")
        if self.accept("ELSE"):
            out = builder.otherwise(self.expr())
        else:
            out = builder.end()
        self.expect("END")
        return out

    def _primary(self) -> Column:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of expression")
        if t == "(":
            self.next()
            if self.peek_upper() in ("SELECT", "WITH"):
                # scalar subquery: one column, one row
                values = self._subquery_values()
                self.expect(")")
                if values.shape[0] != 1:
                    raise ValueError(
                        "scalar subquery must return exactly one row, got "
                        f"{values.shape[0]}"
                    )
                v = values[0]
                return lit(v.item() if hasattr(v, "item") else v)
            e = self.expr()
            self.expect(")")
            return e
        if t.upper() == "CASE":
            self.next()
            return self._case_expr()
        if t.upper() == "CAST":
            self.next()
            self.expect("(")
            e = self.expr()
            self.expect("AS")
            target = self.ident()
            self.expect(")")
            return e.cast(target)
        if re.fullmatch(r"\d+\.\d*|\.\d+|\d+", t):
            self.next()
            return lit(float(t) if ("." in t) else int(t))
        if t.startswith("'"):
            self.next()
            return lit(t[1:-1].replace("''", "'"))
        if (
            t.upper() in _AGG_FNS
            and self.i + 1 < len(self.toks)
            and self.toks[self.i + 1] == "("
        ):
            # aggregate-call syntax inside an expression (HAVING SUM(v) > 1)
            # references the aggregated OUTPUT column by its default label
            fn = _AGG_FNS[self.next().upper()]
            self.expect("(")
            arg = "*" if self.peek() == "*" else self.ident()
            if arg == "*":
                self.next()
                fn = "count"
            self.expect(")")
            return col(f"{fn}({arg})")
        if (
            self.i + 1 < len(self.toks)
            and self.toks[self.i + 1] == "("
            and (t.upper() in FUNCTIONS or t.lower() in self.ctx._udfs)
        ):
            name = self.next()
            self.expect("(")
            args: List[Column] = []
            if self.peek() != ")":
                while True:
                    args.append(self.expr())
                    if not self.accept(","):
                        break
            self.expect(")")
            if name.lower() in self.ctx._udfs:
                return udf_column(
                    self.ctx._udfs[name.lower()], args, name.lower()
                )
            return call_function(name, args)
        name = self.ident()
        if name.upper() in _KEYWORDS:
            raise ValueError(f"unexpected keyword {name!r} in expression")
        # qualified name t.c: the frame is flat, keep the column part
        if self.peek() == ".":
            self.next()
            name = self.ident()
        return col(name)

    # --------------------------------------------------------------- clauses
    def select_items(self) -> List[Tuple[str, Any]]:
        """[(kind, payload)]: ('star', None) | ('agg', (fn, colname, out))
        | ('expr', (Column, out, bare)) -- ``bare`` is the source column
        name when the expression is a bare reference, else None
        | ('window', (fn, arg, offset, spec, out))."""
        items: List[Tuple[str, Any]] = []
        while True:
            if self.peek() == "*":
                self.next()
                items.append(("star", None))
            elif (
                self.peek_upper() in _WINDOW_ONLY_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1] == "("
            ):
                wfn = _WINDOW_ONLY_FNS[self.next().upper()]
                self.expect("(")
                warg = None
                woffset = 1
                if wfn in ("lag", "lead"):
                    warg = self.ident()
                    if self.accept(","):
                        woffset = int(self.next())
                self.expect(")")
                spec = self._over_clause()
                out = wfn
                if self.accept("AS"):
                    out = self.ident()
                items.append(
                    ("window", (wfn, warg, woffset, spec, out))
                )
            elif (
                self.peek_upper() in _AGG_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1] == "("
            ):
                fn = _AGG_FNS[self.next().upper()]
                self.expect("(")
                if self.peek() == "*":
                    self.next()
                    arg = None  # COUNT(*)
                else:
                    # full expression allowed: SUM(v * 2); a bare column
                    # reference stays a plain name, anything else (incl.
                    # literals like COUNT(1)) is a Column the lowering
                    # materializes first
                    start = self.i
                    e = self.expr()
                    ident_re = r"[A-Za-z_][A-Za-z_0-9]*"
                    if self.i == start + 1 and re.fullmatch(
                        ident_re, self.toks[start]
                    ):
                        arg = self.toks[start]
                    elif self.i == start + 3 and self.toks[start + 1] == ".":
                        arg = self.toks[start + 2]
                    else:
                        arg = e
                self.expect(")")
                if self.peek_upper() == "OVER":
                    # aggregate as a WINDOW function: SUM(v) OVER (...)
                    if not isinstance(arg, str) and arg is not None:
                        raise ValueError(
                            "window aggregates take a bare column argument"
                        )
                    spec = self._over_clause()
                    out = fn if arg is None else f"{fn}_{arg}"
                    if self.accept("AS"):
                        out = self.ident()
                    items.append(("window", (fn, arg, 1, spec, out)))
                    if not self.accept(","):
                        return items
                    continue
                # unaliased labels must be unique per item or later spec
                # entries silently overwrite earlier ones
                label = arg if isinstance(arg, str) else (
                    f"expr#{len(items)}" if arg is not None else "*"
                )
                out = f"{fn}({label})"
                if self.accept("AS"):
                    out = self.ident()
                items.append(("agg", (fn, arg, out)))
            else:
                start = self.i
                e = self.expr()
                out = e.name
                bare = None  # the SOURCE column name when e is a bare ref
                # a bare column reference keeps its own name
                if self.i == start + 1:
                    out = self.toks[start]
                    bare = out
                elif self.i == start + 3 and self.toks[start + 1] == ".":
                    out = self.toks[start + 2]
                    bare = out
                if self.accept("AS"):
                    out = self.ident()
                items.append(("expr", (e, out, bare)))
            if not self.accept(","):
                return items

    def _over_clause(self) -> Tuple[Optional[str], Optional[str], bool]:
        """OVER ( [PARTITION BY k] [ORDER BY c [ASC|DESC]] ) ->
        (partition_by, order_by, ascending)."""
        self.expect("OVER")
        self.expect("(")
        partition_by = None
        order_by = None
        ascending = True
        if self.accept("PARTITION"):
            self.expect("BY")
            partition_by = [self.ident()]
            while self.accept(","):
                partition_by.append(self.ident())
            if len(partition_by) == 1:
                partition_by = partition_by[0]
        if self.accept("ORDER"):
            self.expect("BY")
            order_by = self.ident()
            if self.accept("DESC"):
                ascending = False
            else:
                self.accept("ASC")
        self.expect(")")
        return partition_by, order_by, ascending

    # ---------------------------------------------------------------- lowering
    def _project(self, frame, items, group_key):
        aggs = [it for kind, it in items if kind == "agg"]
        exprs = [(e, name) for kind, (e, name, _bare) in (
            (k, v) for k, v in items if k == "expr"
        )]
        has_star = any(kind == "star" for kind, _ in items)
        windows = [it for kind, it in items if kind == "window"]

        if windows:
            if group_key is not None or aggs:
                raise ValueError(
                    "window functions cannot mix with GROUP BY aggregates"
                )
            for fn, arg, offset, (pby, oby, asc), out in windows:
                frame = frame.with_window(
                    out, fn, arg, partition_by=pby, order_by=oby,
                    ascending=asc, offset=offset,
                )
            if has_star:
                if not exprs:
                    return frame
                # star + extra expressions: same contract as the
                # non-window star path -- source columns, then windows,
                # then non-colliding aliased expressions
                sel = list(frame.columns) + [
                    e.alias(name) for e, name in exprs
                    if name not in frame.columns
                ]
                return frame.select(*sel)
            sel = []
            for kind, it in items:
                if kind == "expr":
                    sel.append(it[0].alias(it[1]))
                else:
                    sel.append(it[4])
            return frame.select(*sel)

        if group_key is not None:
            # SELECT key?, aggs FROM ... GROUP BY key
            if has_star:
                raise ValueError(
                    "SELECT * is not valid with GROUP BY; name the "
                    "group key and aggregates explicitly"
                )
            keys = group_key if isinstance(group_key, list) else [group_key]
            for e, name in exprs:
                if name not in keys:
                    raise ValueError(
                        "non-aggregate select item "
                        f"{name!r} must be a GROUP BY key"
                    )
            frame, spec = _agg_spec(frame, aggs)
            gb = frame.groupby(group_key)
            if not spec:
                return gb.count()
            return gb.agg(**spec)

        if aggs:
            if exprs or has_star:
                raise ValueError(
                    "mixing aggregates and plain columns needs GROUP BY"
                )
            frame, spec = _agg_spec(frame, aggs)
            scalars = frame.agg(**spec)
            return ColumnarFrame(
                {k: np.asarray([v]) for k, v in scalars.items()}
            )

        if has_star and not exprs:
            return frame
        if has_star:
            sel = list(frame.columns) + [
                e.alias(name) for e, name in exprs
                if name not in frame.columns
            ]
            return frame.select(*sel)
        return frame.select(*[e.alias(name) for e, name in exprs])


class SQLContext:
    """Table registry + ``sql()`` entry point (SparkSession.sql analog)."""

    def __init__(self):
        self._tables: Dict[str, ColumnarFrame] = {}
        self._udfs: Dict[str, Any] = {}
        # names created by CREATE VIEW DDL: DROP VIEW may only remove
        # these -- a base table registered via register()/register_csv/...
        # must survive a stray DROP VIEW (it would silently delete data
        # the caller still holds a name for)
        self._views: set = set()

    def register(self, name: str, frame: ColumnarFrame) -> None:
        """``createOrReplaceTempView`` analog (registers a BASE table: not
        droppable via DROP VIEW)."""
        self._tables[name.lower()] = frame
        self._views.discard(name.lower())

    def register_udf(self, name: str, fn) -> None:
        """Row-wise python UDF (``spark.udf.register`` analog): callable in
        any expression position as ``name(args...)``."""
        self._udfs[name.lower()] = fn

    def register_csv(self, name: str, path, **kw) -> None:
        """Register a CSV as a LAZY table: queries push projection and
        predicates into the reader, so unused columns are never parsed and
        filtered rows never reach the device."""
        self._tables[name.lower()] = lazy_csv(name, path, **kw)
        self._views.discard(name.lower())

    def register_json(self, name: str, path) -> None:
        self._tables[name.lower()] = lazy_json(name, path)
        self._views.discard(name.lower())

    def register_parquet(self, name: str, path) -> None:
        self._tables[name.lower()] = lazy_parquet(name, path)
        self._views.discard(name.lower())

    def table(self, name: str) -> ColumnarFrame:
        key = name.lower()
        if key not in self._tables:
            raise KeyError(
                f"no table {name!r}; registered: {sorted(self._tables)}"
            )
        t = self._tables[key]
        return t.materialize() if isinstance(t, LazyTable) else t

    # ----------------------------------------------------------------- query
    def sql(self, text: str) -> ColumnarFrame:
        p = _Parser(tokenize(text), self)
        if p.peek_upper() in ("CREATE", "DROP"):
            return self._ddl(p)
        if p.accept("EXPLAIN"):
            # SQL-surface EXPLAIN (Spark's `EXPLAIN SELECT ...`): the
            # optimized plan as a one-column frame, without executing the
            # FROM-position relations
            lines = self._explain_parser(p).splitlines()
            return ColumnarFrame({"plan": np.asarray(lines, object)})
        frame = p.statement()
        if p.peek() is not None:
            raise ValueError(f"trailing SQL tokens: {self_rest(p)}")
        return frame

    def explain(self, text: str) -> str:
        """The OPTIMIZED logical plan for a statement, as text -- the
        public plan-shape artifact (``Dataset.explain`` analog).  Value
        subqueries (IN (...) / scalar) still execute during planning;
        FROM-position relations do not."""
        return self._explain_parser(_Parser(tokenize(text), self))

    def _ddl(self, p: "_Parser") -> ColumnarFrame:
        """View DDL (the SQL-surface form of ``createOrReplaceTempView``):
        ``CREATE [OR REPLACE] [TEMP] VIEW name AS <statement>`` registers
        the statement's RESULT under the name; ``DROP VIEW [IF EXISTS]
        name`` unregisters.  Returns a one-row status frame."""
        import numpy as np

        if p.accept("CREATE"):
            replace = False
            if p.accept("OR"):
                p.expect("REPLACE")
                replace = True
            p.accept("TEMP")
            p.expect("VIEW")
            name = p.ident()
            p.expect("AS")
            if name.lower() in self._tables and not replace:
                raise ValueError(
                    f"view {name!r} exists; use CREATE OR REPLACE VIEW"
                )
            frame = p.statement()
            if p.peek() is not None:
                raise ValueError(f"trailing SQL tokens: {self_rest(p)}")
            self.register(name, frame)
            self._views.add(name.lower())
            return ColumnarFrame({"view": np.asarray([name], object)})
        p.expect("DROP")
        p.expect("VIEW")
        if_exists = False
        if p.peek_upper() == "IF":
            p.next()
            p.expect("EXISTS")
            if_exists = True
        name = p.ident()
        if p.peek() is not None:
            raise ValueError(f"trailing SQL tokens: {self_rest(p)}")
        if name.lower() not in self._tables:
            if not if_exists:
                raise KeyError(f"no view {name!r}")
        elif name.lower() not in self._views:
            # IF EXISTS excuses absence, never the wrong object kind: the
            # name is a registered BASE table, and DROP VIEW deleting it
            # would destroy data the caller never created through SQL
            raise ValueError(
                f"{name!r} is a base table, not a view; DROP VIEW refuses"
            )
        else:
            del self._tables[name.lower()]
            self._views.discard(name.lower())
        return ColumnarFrame({"view": np.asarray([name], object)})

    @staticmethod
    def _explain_parser(p: "_Parser") -> str:
        """Plan text from an already-positioned parser (one pipeline for
        both ``explain()`` and ``EXPLAIN SELECT ...``)."""
        node = p.statement_plan()
        if p.peek() is not None:
            raise ValueError(f"trailing SQL tokens: {self_rest(p)}")
        return _plan.optimize(node, None).explain()


def aggs_present(items) -> bool:
    return any(kind == "agg" for kind, _ in items)


def _required_source_columns(items, group_key, order_by):
    """Transitive set of SOURCE columns the select list needs, for the
    optimizer's pruning pass.  None = unknown (star, COUNT(*), or an
    expression whose refs can't be inferred) -- pruning disabled."""
    names = set()
    for kind, it in items:
        if kind == "star":
            return None
        if kind == "agg":
            _fn, arg, _out = it
            if arg is None:
                return None  # COUNT(*) touches an arbitrary device column
            if isinstance(arg, str):
                names.add(arg)
            elif arg.refs is None:
                return None
            else:
                names |= set(arg.refs)
        elif kind == "window":
            _wfn, warg, _off, (pby, oby, _asc), _out = it
            names |= {c for c in (warg, oby) if c}
            if pby:
                names.update([pby] if isinstance(pby, str) else pby)
        else:
            e = it[0]
            if e.refs is None:
                return None
            names |= set(e.refs)
    if group_key is not None:
        names.update(
            group_key if isinstance(group_key, list) else [group_key]
        )
    if order_by is not None:
        names.update(
            order_by if isinstance(order_by, list) else [order_by]
        )
    return names


def _agg_spec(frame: ColumnarFrame, aggs):
    """Resolve aggregate arguments: bare columns pass through, expression
    arguments are materialized as temp columns, COUNT(*) counts rows."""
    spec = {}
    for i, (fn, arg, out) in enumerate(aggs):
        if arg is None:  # COUNT(*): count over any device column
            arg = _any_device_column(frame)
            fn = "count"
        elif isinstance(arg, Column):
            tmp = f"__agg_{i}"
            frame = frame.with_column(tmp, arg)
            arg = tmp
        spec[out] = (arg, fn)
    return frame, spec


def _any_device_column(frame: ColumnarFrame) -> str:
    import jax.numpy as jnp

    for name in frame.columns:
        if isinstance(frame[name], jnp.ndarray):
            return name
    raise ValueError("COUNT(*) needs at least one numeric column")


def _limit(frame: ColumnarFrame, n: int) -> ColumnarFrame:
    return _plan.limit_frame(frame, n)


def self_rest(p: _Parser) -> str:
    return " ".join(p.toks[p.i : p.i + 8])


def sql(text: str, **tables: ColumnarFrame) -> ColumnarFrame:
    """One-shot convenience: ``sql("SELECT ...", t=frame)``."""
    ctx = SQLContext()
    for name, frame in tables.items():
        ctx.register(name, frame)
    return ctx.sql(text)
