"""Window functions over ColumnarFrame partitions.

Parity (studied, not copied): Spark SQL's window operators
(``sql/core/src/main/scala/org/apache/spark/sql/execution/window/
WindowExec.scala`` and the ``Window.partitionBy(...).orderBy(...)`` API) --
``row_number``/``rank``/``dense_rank``, ``lag``/``lead``, and running /
whole-partition aggregates.

TPU mapping: one host ``lexsort`` groups rows into contiguous partitions
(the sort that WindowExec gets from its shuffle); every function is then a
vectorized segment expression -- running aggregates are cumulative ops with
the segment offset subtracted, ranks are comparisons against the previous
row -- and the result scatters back to the original row order.  No per-row
host loop anywhere.

Frames supported: the two Spark defaults -- whole partition (aggregate
without ORDER BY) and UNBOUNDED PRECEDING..CURRENT ROW (aggregate with
ORDER BY, the "running" form).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_RANKING = ("row_number", "rank", "dense_rank")
_OFFSETS = ("lag", "lead")
_AGGS = ("sum", "mean", "avg", "min", "max", "count")


def window_column(
    frame,
    fn: str,
    arg: Optional[str],
    partition_by: "str | list | None",
    order_by: Optional[str],
    ascending: bool = True,
    offset: int = 1,
    default=np.nan,
) -> np.ndarray:
    """Compute one window column, aligned with ``frame``'s row order.

    ``fn``: row_number / rank / dense_rank / lag / lead / sum / mean /
    min / max / count.  ``arg`` names the value column (None for ranking
    functions and count).  With ``order_by`` set, aggregates are RUNNING
    (unbounded preceding .. current row); without it they are
    whole-partition.
    """
    fn = {"avg": "mean"}.get(fn, fn)
    if fn not in _RANKING + _OFFSETS + ("sum", "mean", "min", "max", "count"):
        raise ValueError(f"unknown window function {fn!r}")
    if fn in _RANKING + _OFFSETS and order_by is None:
        raise ValueError(f"{fn} requires ORDER BY")
    n = len(frame)
    if n == 0:
        if fn in _RANKING or fn == "count":
            return np.empty(0, np.int64)
        return np.empty(0, np.float64)
    if partition_by is None:
        part = np.zeros(n, np.int8)
    elif isinstance(partition_by, str):
        part = np.asarray(frame[partition_by])
    else:
        # multi-key PARTITION BY: one combined code per row -- equality
        # only, no dense re-coding (the groupby path's extra work)
        from asyncframework_tpu.sql.frame import multikey_partition_codes

        part = multikey_partition_codes(frame, list(partition_by))
    okey = np.asarray(frame[order_by]) if order_by is not None else None

    # contiguous partitions; stable within-partition order
    if okey is not None:
        ok = okey if ascending else _descending_key(okey)
        order = np.lexsort((ok, part))
    else:
        order = np.lexsort((part,))
    p_sorted = part[order]
    new_seg = np.empty(n, bool)
    new_seg[0] = True
    new_seg[1:] = p_sorted[1:] != p_sorted[:-1]
    seg_id = np.cumsum(new_seg) - 1
    seg_start = np.nonzero(new_seg)[0][seg_id]  # start index of own segment
    pos = np.arange(n) - seg_start               # 0-based position in segment

    if fn == "row_number":
        out_sorted = (pos + 1).astype(np.int64)
    elif fn in ("rank", "dense_rank"):
        o_sorted = okey[order]
        tie_prev = np.empty(n, bool)
        tie_prev[0] = False
        tie_prev[1:] = (o_sorted[1:] == o_sorted[:-1]) & ~new_seg[1:]
        if fn == "rank":
            # rank = position of the first row of the tie run, +1
            run_start = np.where(~tie_prev, np.arange(n), 0)
            np.maximum.accumulate(run_start, out=run_start)
            out_sorted = (run_start - seg_start + 1).astype(np.int64)
        else:
            # dense_rank = #distinct values seen in segment so far
            steps = (~tie_prev).astype(np.int64)
            csum = np.cumsum(steps)
            out_sorted = csum - csum[seg_start] + 1
    elif fn in _OFFSETS:
        vals = np.asarray(frame[arg])[order]
        shift = offset if fn == "lag" else -offset
        out_sorted = np.full(n, default, dtype=np.result_type(vals, type(default)))
        if shift >= 0:
            src = np.arange(n) - shift
        else:
            src = np.arange(n) + offset
        valid = (src >= 0) & (src < n)
        # offset source must stay inside the row's own partition
        valid &= np.where(valid, seg_id[np.clip(src, 0, n - 1)] == seg_id,
                          False)
        out_sorted[valid] = vals[np.clip(src, 0, n - 1)][valid]
    else:
        if fn == "count":
            vals = np.ones(n, np.float64)
        else:
            vals = np.asarray(frame[arg])[order].astype(np.float64)
        if order_by is None:
            # whole-partition aggregate, broadcast to every row
            out_sorted = _segment_reduce_broadcast(vals, seg_id, seg_start, fn)
        else:
            out_sorted = _running(vals, seg_id, seg_start, fn)
        if fn == "count":
            out_sorted = out_sorted.astype(np.int64)

    out = np.empty(n, out_sorted.dtype)
    out[order] = out_sorted
    return out


def _descending_key(okey: np.ndarray):
    if okey.dtype.kind == "f":
        return -okey.astype(np.float64)
    # ints and strings: rank-invert through the sorted unique table --
    # negating through float64 would collapse distinct int64 values above
    # 2^53 (e.g. nanosecond timestamps) into spurious ties
    _u, inv = np.unique(okey, return_inverse=True)
    return -inv


def _segment_reduce_broadcast(vals, seg_id, seg_start, fn):
    n_seg = seg_id[-1] + 1 if len(seg_id) else 0
    if fn in ("sum", "mean", "count"):
        tot = np.bincount(seg_id, weights=vals, minlength=n_seg)
        if fn == "mean":
            cnt = np.bincount(seg_id, minlength=n_seg)
            tot = tot / np.maximum(cnt, 1)
        return tot[seg_id]
    op = np.minimum if fn == "min" else np.maximum
    acc = np.full(n_seg, np.inf if fn == "min" else -np.inf)
    op.at(acc, seg_id, vals)
    return acc[seg_id]


def _running(vals, seg_id, seg_start, fn):
    n = len(vals)
    if fn in ("sum", "mean", "count"):
        c = np.cumsum(vals)
        seg_base = c[seg_start] - vals[seg_start]
        run = c - seg_base
        if fn == "mean":
            run = run / (np.arange(n) - seg_start + 1)
        return run
    op = np.minimum.accumulate if fn == "min" else np.maximum.accumulate
    # segment-wise cumulative min/max: reset at segment starts by running
    # the accumulate on a copy where each segment start re-seeds
    out = np.empty(n, vals.dtype)
    # vectorized reset trick: process via np.ufunc on offset-adjusted array
    # is messy for min/max; segments are contiguous, so accumulate per
    # segment via reduceat-style spans (few segments >> rows each)
    starts = np.unique(seg_start)
    for s, e in zip(starts, np.append(starts[1:], n)):
        out[s:e] = op(vals[s:e])
    return out
