"""Logical plan + optimizer above the eager frame layer.

Parity (studied, not copied): ``sql/catalyst/.../optimizer/Optimizer.scala:38``
-- the reference's rule-based optimizer over catalyst logical plans, plus the
planner entry ``AstBuilder.scala``.  The reference needs hundreds of rules
because its execution is lazy whole-query codegen onto a shuffle engine; the
TPU build executes eagerly on fused columnar kernels, so the rules that pay
for themselves here are the DATA-MOVEMENT rules:

- **PushFilterThroughJoin**: a conjunct referencing only one join side
  filters that side before the join's index build + gathers (safe sides
  depend on join type; see ``_push_filters``).
- **PushFilterIntoScan / through Aggregate**: predicates travel into the
  reader (rows never reach the device) or below a GROUP BY when they only
  reference the group key.
- **PruneColumns**: the transitive closure of referenced columns shrinks
  every scan -- a reader-backed scan never parses unused columns.
- **Constant folding** happens at expression-construction time
  (``expressions.Column._binop``: const x const folds to a literal), so by
  the time a plan exists, ``WHERE x > 1 + 2`` is already ``x > 3``; the
  plan-level fold handles the degenerate all-constant predicate (drop the
  Filter / empty relation).
- **Join build-side selection** is an execution-time rule (``frame.join``
  sorts the smaller side); the plan records sizes when known.

The plan is deliberately tiny: Scan / Filter / Project / Join / Aggregate
over a tree, built by the SQL parser's FROM/JOIN/WHERE/GROUP BY core and
executed straight onto ``ColumnarFrame`` ops after rewriting.  Plan shape is
a public artifact (``explain()``) so tests assert rewrites structurally.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from asyncframework_tpu.sql.expressions import Column
from asyncframework_tpu.sql.frame import ColumnarFrame


# ------------------------------------------------------------------- nodes
@dataclass
class Node:
    def children(self) -> List["Node"]:
        return []

    def explain(self, depth: int = 0) -> str:
        pad = "  " * depth
        lines = [pad + self._label()]
        for c in self.children():
            lines.append(c.explain(depth + 1))
        return "\n".join(lines)

    def _label(self) -> str:  # pragma: no cover - overridden
        return type(self).__name__


@dataclass
class Scan(Node):
    """A named table: either an in-memory frame or a lazy reader-backed
    source that accepts (select, where) pushdown."""

    name: str
    frame: Optional[ColumnarFrame] = None
    reader: Optional[Callable[..., ColumnarFrame]] = None  # (select=, where=)
    schema: Optional[List[str]] = None  # known columns (for pruning)
    pushed_where: Optional[Column] = None
    pushed_select: Optional[List[str]] = None

    def _label(self) -> str:
        bits = [f"Scan({self.name}"]
        if self.pushed_select is not None:
            bits.append(f", select={self.pushed_select}")
        if self.pushed_where is not None:
            bits.append(f", where={self.pushed_where.name}")
        bits.append(")")
        return "".join(bits)

    def columns(self) -> Optional[List[str]]:
        if self.pushed_select is not None:
            return list(self.pushed_select)
        if self.frame is not None:
            return list(self.frame.columns)
        return list(self.schema) if self.schema is not None else None


@dataclass
class Filter(Node):
    child: Node
    predicate: Column

    def children(self):
        return [self.child]

    def _label(self):
        return f"Filter({self.predicate.name})"


@dataclass
class Project(Node):
    child: Node
    cols: List[str]

    def children(self):
        return [self.child]

    def _label(self):
        return f"Project({self.cols})"


@dataclass
class Join(Node):
    left: Node
    right: Node
    on: "str | List[str]"
    how: str = "inner"

    def children(self):
        return [self.left, self.right]

    def keys(self) -> List[str]:
        """The equi-join key list (``on`` normalized once, here)."""
        return [self.on] if isinstance(self.on, str) else list(self.on)

    def _label(self):
        return f"Join(on={self.on}, how={self.how})"


@dataclass
class Aggregate(Node):
    child: Node
    key: str
    # out name -> (column name, fn); built by the parser's _agg_spec
    spec: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def children(self):
        return [self.child]

    def _label(self):
        return f"Aggregate(key={self.key}, aggs={list(self.spec)})"


# --------------------------------------------------------------- utilities
def split_conjuncts(pred: Column) -> List[Column]:
    """Flatten a top-level AND chain (recorded at construction by
    ``Column.__and__``) into its conjuncts."""
    parts = getattr(pred, "_and_parts", None)
    if not parts:
        return [pred]
    out: List[Column] = []
    for p in parts:
        out.extend(split_conjuncts(p))
    return out


def and_all(preds: Sequence[Column]) -> Optional[Column]:
    it = list(preds)
    if not it:
        return None
    acc = it[0]
    for p in it[1:]:
        acc = acc & p
    return acc


def node_columns(node: Node) -> Optional[List[str]]:
    """Output columns of a plan node, None when unknown (opaque source)."""
    if isinstance(node, Scan):
        return node.columns()
    if isinstance(node, Filter):
        return node_columns(node.child)
    if isinstance(node, Project):
        return list(node.cols)
    if isinstance(node, Aggregate):
        return [node.key] + list(node.spec)
    if isinstance(node, Join):
        lc = node_columns(node.left)
        rc = node_columns(node.right)
        if lc is None or rc is None:
            return None
        if node.how in ("semi", "anti"):
            return list(lc)
        keys = node.keys()
        out = list(lc)
        for c in rc:
            if c in keys:
                continue
            out.append(c if c not in out else f"{c}_right")
        return out
    return None


# -------------------------------------------------------------- optimizer
def optimize(plan: Node, required: Optional[Sequence[str]] = None) -> Node:
    """Rule pipeline: fold degenerate predicates, push filters down, prune
    columns.  ``required`` is the set of columns the consumer needs (select
    items + order keys ...); None = keep everything."""
    plan = _fold_trivial_filters(plan)
    plan = _push_filters(plan)
    plan = _prune_columns(plan, set(required) if required is not None
                          else None)
    return plan


def _fold_trivial_filters(node: Node) -> Node:
    """A predicate with no column references is a constant: True drops the
    Filter, False empties the relation (kept as a Filter on an impossible
    mask -- the executor handles it; correctness over cleverness)."""
    if isinstance(node, Filter):
        child = _fold_trivial_filters(node.child)
        keep: List[Column] = []
        for c in split_conjuncts(node.predicate):
            if not getattr(c, "refs", None) and not getattr(
                c, "volatile", False
            ):
                try:
                    val = c({})
                except Exception:  # can't fold: keep it
                    keep.append(c)
                    continue
                if np.ndim(val) == 0 and bool(val):
                    continue  # tautology: drop
                keep.append(c)  # contradiction or odd shape: keep for exec
            else:
                keep.append(c)
        pred = and_all(keep)
        return child if pred is None else Filter(child, pred)
    for name, child in _child_fields(node):
        setattr(node, name, _fold_trivial_filters(child))
    return node


def _child_fields(node: Node) -> List[Tuple[str, Node]]:
    if isinstance(node, (Filter, Project, Aggregate)):
        return [("child", node.child)]
    if isinstance(node, Join):
        return [("left", node.left), ("right", node.right)]
    return []


def _push_filters(node: Node) -> Node:
    if isinstance(node, Filter):
        child = _push_filters(node.child)
        remaining: List[Column] = []
        for conj in split_conjuncts(node.predicate):
            child, pushed = _push_one(child, conj)
            if not pushed:
                remaining.append(conj)
        pred = and_all(remaining)
        node = child if pred is None else Filter(child, pred)
        return node
    for name, child in _child_fields(node):
        setattr(node, name, _push_filters(child))
    return node


def _push_one(node: Node, conj: Column) -> Tuple[Node, bool]:
    """Try to sink one conjunct into ``node``; returns (new node, pushed?).
    Volatile predicates (UDFs) and host-evaluated constructs never move --
    a moved side effect changes observable behavior."""
    refs = getattr(conj, "refs", None)
    if refs is None or getattr(conj, "volatile", False):
        return node, False
    if isinstance(node, Scan):
        if node.reader is not None:
            # into the reader: rows are filtered before device placement
            node.pushed_where = (
                conj if node.pushed_where is None
                else node.pushed_where & conj
            )
            return node, True
        # in-memory frame: a Filter directly above the scan is as far down
        # as the predicate can travel; still a win when above sat a join
        return Filter(node, conj), True
    if isinstance(node, Filter):
        child, pushed = _push_one(node.child, conj)
        if pushed:
            node.child = child
            return node, True
        return node, False
    if isinstance(node, Project):
        if set(refs) <= set(node.cols):
            node.child, pushed = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, Aggregate):
        # only group-key predicates commute with aggregation
        if set(refs) <= {node.key}:
            node.child, _ = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, Join):
        lc, rc = node_columns(node.left), node_columns(node.right)
        # which sides may receive pushdown without changing semantics:
        #  inner: both; left/semi/anti: left only; right: right only;
        #  full: neither (filters see NULL-extended rows)
        allow_left = node.how in ("inner", "left", "semi", "anti")
        allow_right = node.how in ("inner", "right")
        if allow_left and lc is not None and set(refs) <= set(lc):
            node.left, _ = _ensure_pushed(node.left, conj)
            return node, True
        if allow_right and rc is not None and set(refs) <= set(rc):
            node.right, _ = _ensure_pushed(node.right, conj)
            return node, True
        return node, False
    return node, False


def _ensure_pushed(node: Node, conj: Column) -> Tuple[Node, bool]:
    """Sink ``conj`` into ``node``, wrapping in a Filter when it cannot go
    deeper (the push must not be lost)."""
    new, pushed = _push_one(node, conj)
    if pushed:
        return new, True
    return Filter(new, conj), True


def _prune_columns(node: Node, required: Optional[set]) -> Node:
    """Top-down: shrink every scan to the transitive closure of columns the
    plan above it uses.  ``required=None`` disables pruning (unknown
    consumer)."""
    if isinstance(node, Scan):
        if required is None:
            return node
        cols = node.columns()
        want = [c for c in (cols or [])
                if c in required] if cols is not None else None
        if want is not None and not want and cols:
            # nothing referenced (SELECT 1 FROM t): keep one column so the
            # source's ROW COUNT survives -- a zero-column read would
            # collapse the relation
            want = cols[:1]
        if node.reader is not None:
            # predicate columns are discovered by the reader itself
            # (sql/io.py _needed_for_predicate), so pushed_select only
            # needs the plan's requirements
            node.pushed_select = want
        elif node.frame is not None and want is not None and set(
            want
        ) != set(cols):
            if want:
                return Project(node, want)
        return node
    if isinstance(node, Filter):
        child_req = None
        if required is not None:
            child_req = set(required) | set(
                getattr(node.predicate, "refs", set()) or set()
            )
            # un-inferable refs (None) poison pruning below this node
            if getattr(node.predicate, "refs", None) is None:
                child_req = None
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Project):
        node.child = _prune_columns(
            node.child,
            set(node.cols) if required is not None else None,
        )
        return node
    if isinstance(node, Aggregate):
        child_req = None
        if required is not None:
            child_req = {node.key} | {
                colname for (colname, _fn) in node.spec.values()
            }
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Join):
        if required is None:
            node.left = _prune_columns(node.left, None)
            node.right = _prune_columns(node.right, None)
            return node
        req = set(required) | set(node.keys())
        # a suffixed output column c_right requires right-side c -- AND the
        # left-side c must survive too: the _right suffix only exists while
        # the names collide, so pruning the left copy would silently rename
        # the right column to bare c and break the consumer's reference
        base = {c[: -len("_right")] for c in required if
                c.endswith("_right")}
        node.left = _prune_columns(node.left, req | base)
        node.right = _prune_columns(node.right, req | base)
        return node
    return node


# --------------------------------------------------------------- execution
def execute(node: Node) -> ColumnarFrame:
    if isinstance(node, Scan):
        if node.reader is not None:
            return node.reader(
                select=node.pushed_select, where=node.pushed_where
            )
        assert node.frame is not None
        return node.frame
    if isinstance(node, Filter):
        return execute(node.child).filter(node.predicate)
    if isinstance(node, Project):
        return execute(node.child).select(*node.cols)
    if isinstance(node, Aggregate):
        frame = execute(node.child)
        gb = frame.groupby(node.key)
        if not node.spec:
            return gb.count()
        return gb.agg(**node.spec)
    if isinstance(node, Join):
        return execute(node.left).join(
            execute(node.right), on=node.on, how=node.how
        )
    raise TypeError(f"unknown plan node {type(node).__name__}")
