"""Logical plan + optimizer above the eager frame layer.

Parity (studied, not copied): ``sql/catalyst/.../optimizer/Optimizer.scala:38``
-- the reference's rule-based optimizer over catalyst logical plans, plus the
planner entry ``AstBuilder.scala``.  The reference needs hundreds of rules
because its execution is lazy whole-query codegen onto a shuffle engine; the
TPU build executes eagerly on fused columnar kernels, so the rules that pay
for themselves here are the DATA-MOVEMENT rules:

- **PushFilterThroughJoin**: a conjunct referencing only one join side
  filters that side before the join's index build + gathers (safe sides
  depend on join type; see ``_push_filters``).
- **PushFilterIntoScan / through Aggregate**: predicates travel into the
  reader (rows never reach the device) or below a GROUP BY when they only
  reference the group key.
- **PruneColumns**: the transitive closure of referenced columns shrinks
  every scan -- a reader-backed scan never parses unused columns.
- **Constant folding** happens at expression-construction time
  (``expressions.Column._binop``: const x const folds to a literal), so by
  the time a plan exists, ``WHERE x > 1 + 2`` is already ``x > 3``; the
  plan-level fold handles the degenerate all-constant predicate (drop the
  Filter / empty relation).
- **Join build-side selection** is an execution-time rule (``frame.join``
  sorts the smaller side); the plan records sizes when known.

Round 5 extends the plan PAST the FROM/JOIN/WHERE core (VERDICT r4 #3/#4):

- **Compute / Window / Sort / Limit / Distinct / SetOp** nodes cover the
  full SELECT shape, so pushdown and pruning cross projection, window
  functions (predicates on PARTITION BY keys sink below the window),
  UNION ALL (pruning and predicates reach both branches), ORDER BY and
  DISTINCT -- the ``Optimizer.scala:38`` batches that rewrite whole
  queries rather than just the join core.
- **Join reordering** (``joins.scala:37`` ``ReorderJoin`` role): inner-join
  chains re-order greedily by estimated size -- smallest relation first,
  then the smallest relation connected by a join key -- so a badly written
  3-table star query builds its indexes on the small sides.  Rebuilds are
  guarded: unknown schemas, colliding non-key columns, or ``_right``
  suffixes in the output keep the written order.
- **Shared** is an execute-once CTE body (``CostBasedJoinReorder``'s
  sibling concern ``InlineCTE``): every reference holds the SAME node, the
  frame caches on first execution; single-use Shared nodes inline (as a
  structural clone, so consumer-specific pruning never mutates the stored
  body) and multi-use ones stay opaque boundaries.

The plan remains a tree executed straight onto ``ColumnarFrame`` ops after
rewriting.  Plan shape is a public artifact (``explain()``) so tests assert
rewrites structurally.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from asyncframework_tpu.sql.expressions import Column
from asyncframework_tpu.sql.frame import ColumnarFrame


# ------------------------------------------------------------------- nodes
@dataclass
class Node:
    def children(self) -> List["Node"]:
        return []

    def explain(self, depth: int = 0) -> str:
        pad = "  " * depth
        lines = [pad + self._label()]
        for c in self.children():
            lines.append(c.explain(depth + 1))
        return "\n".join(lines)

    def _label(self) -> str:  # pragma: no cover - overridden
        return type(self).__name__


@dataclass
class Scan(Node):
    """A named table: either an in-memory frame or a lazy reader-backed
    source that accepts (select, where) pushdown."""

    name: str
    frame: Optional[ColumnarFrame] = None
    reader: Optional[Callable[..., ColumnarFrame]] = None  # (select=, where=)
    schema: Optional[List[str]] = None  # known columns (for pruning)
    pushed_where: Optional[Column] = None
    pushed_select: Optional[List[str]] = None

    def _label(self) -> str:
        bits = [f"Scan({self.name}"]
        if self.pushed_select is not None:
            bits.append(f", select={self.pushed_select}")
        if self.pushed_where is not None:
            bits.append(f", where={self.pushed_where.name}")
        bits.append(")")
        return "".join(bits)

    def columns(self) -> Optional[List[str]]:
        if self.pushed_select is not None:
            return list(self.pushed_select)
        if self.frame is not None:
            return list(self.frame.columns)
        return list(self.schema) if self.schema is not None else None


@dataclass
class Filter(Node):
    child: Node
    predicate: Column

    def children(self):
        return [self.child]

    def _label(self):
        return f"Filter({self.predicate.name})"


@dataclass
class Project(Node):
    child: Node
    cols: List[str]

    def children(self):
        return [self.child]

    def _label(self):
        return f"Project({self.cols})"


@dataclass
class Join(Node):
    left: Node
    right: Node
    on: "str | List[str]"
    how: str = "inner"

    def children(self):
        return [self.left, self.right]

    def keys(self) -> List[str]:
        """The equi-join key list (``on`` normalized once, here)."""
        return [self.on] if isinstance(self.on, str) else list(self.on)

    def _label(self):
        return f"Join(on={self.on}, how={self.how})"


@dataclass
class Aggregate(Node):
    """GROUP BY (``key``: one name, a list, or None for whole-frame scalar
    aggregates).  ``spec``: out name -> (column name | None for COUNT(*),
    fn); built by the parser."""

    child: Node
    key: "str | List[str] | None"
    spec: Dict[str, Tuple[Optional[str], str]] = field(default_factory=dict)

    def children(self):
        return [self.child]

    def group_keys(self) -> List[str]:
        if self.key is None:
            return []
        return [self.key] if isinstance(self.key, str) else list(self.key)

    def _label(self):
        return f"Aggregate(key={self.key}, aggs={list(self.spec)})"


@dataclass
class Compute(Node):
    """Projection with expressions (the SELECT list).  ``star`` keeps every
    child column and appends non-colliding aliased expressions (the
    parser's ``SELECT *, expr AS x`` contract).  ``passthrough`` names the
    outputs that are bare same-named source columns -- predicates on them
    may sink below."""

    child: Node
    exprs: List[Tuple[Column, str]] = field(default_factory=list)
    star: bool = False
    passthrough: frozenset = frozenset()

    def children(self):
        return [self.child]

    def _label(self):
        outs = [o for _e, o in self.exprs]
        return f"Compute({'*, ' if self.star else ''}{outs})"


@dataclass
class Window(Node):
    """Window-function columns appended to the child.  ``items``:
    [(fn, arg, offset, (partition_by, order_by, ascending), out)] -- the
    parser's window payload verbatim."""

    child: Node
    items: List[Tuple] = field(default_factory=list)

    def children(self):
        return [self.child]

    def partition_keys(self) -> Optional[set]:
        """Intersection of every item's PARTITION BY key set; None when any
        item is unpartitioned (nothing can sink below a global window)."""
        acc: Optional[set] = None
        for _fn, _arg, _off, (pby, _oby, _asc), _out in self.items:
            if not pby:
                return None
            keys = {pby} if isinstance(pby, str) else set(pby)
            acc = keys if acc is None else (acc & keys)
        return acc

    def outputs(self) -> List[str]:
        return [it[4] for it in self.items]

    def _label(self):
        return f"Window({self.outputs()})"


@dataclass
class Sort(Node):
    child: Node
    by: List[str] = field(default_factory=list)
    ascending: List[bool] = field(default_factory=list)

    def children(self):
        return [self.child]

    def _label(self):
        bits = [f"{c}{'' if a else ' DESC'}"
                for c, a in zip(self.by, self.ascending)]
        return f"Sort({bits})"


@dataclass
class Limit(Node):
    child: Node
    n: int = 0

    def children(self):
        return [self.child]

    def _label(self):
        return f"Limit({self.n})"


@dataclass
class Distinct(Node):
    child: Node

    def children(self):
        return [self.child]

    def _label(self):
        return "Distinct"


@dataclass
class SetOp(Node):
    """union | union_all | except | intersect.  Output columns are the left
    side's (``union_all`` matches by name, Spark unionByName)."""

    left: Node
    right: Node
    op: str = "union_all"

    def children(self):
        return [self.left, self.right]

    def _label(self):
        return f"SetOp({self.op})"


@dataclass
class Shared(Node):
    """Execute-once CTE body: every FROM reference holds the SAME instance
    and the frame caches on first execution.  Multi-referenced Shared nodes
    are opaque to consumer-specific rewrites (pruning); single-use ones are
    inlined as clones by ``optimize``."""

    child: Node
    name: str = "cte"
    _cache: Optional[ColumnarFrame] = field(
        default=None, repr=False, compare=False
    )

    def children(self):
        return [self.child]

    def _label(self):
        return f"Shared({self.name})"


# --------------------------------------------------------------- utilities
def split_conjuncts(pred: Column) -> List[Column]:
    """Flatten a top-level AND chain (recorded at construction by
    ``Column.__and__``) into its conjuncts."""
    parts = getattr(pred, "_and_parts", None)
    if not parts:
        return [pred]
    out: List[Column] = []
    for p in parts:
        out.extend(split_conjuncts(p))
    return out


def and_all(preds: Sequence[Column]) -> Optional[Column]:
    it = list(preds)
    if not it:
        return None
    acc = it[0]
    for p in it[1:]:
        acc = acc & p
    return acc


def node_columns(node: Node) -> Optional[List[str]]:
    """Output columns of a plan node, None when unknown (opaque source)."""
    if isinstance(node, Scan):
        return node.columns()
    if isinstance(node, (Filter, Limit, Distinct)):
        return node_columns(node.child)
    if isinstance(node, Sort):
        return node_columns(node.child)
    if isinstance(node, Shared):
        return node_columns(node.child)
    if isinstance(node, Project):
        return list(node.cols)
    if isinstance(node, Aggregate):
        return node.group_keys() + list(node.spec)
    if isinstance(node, Compute):
        outs = [o for _e, o in node.exprs]
        if not node.star:
            return outs
        child_cols = node_columns(node.child)
        if child_cols is None:
            return None
        return list(child_cols) + [o for o in outs if o not in child_cols]
    if isinstance(node, Window):
        child_cols = node_columns(node.child)
        if child_cols is None:
            return None
        out = list(child_cols)
        for o in node.outputs():
            if o not in out:
                out.append(o)
        return out
    if isinstance(node, SetOp):
        return node_columns(node.left)
    if isinstance(node, Join):
        lc = node_columns(node.left)
        rc = node_columns(node.right)
        if lc is None or rc is None:
            return None
        if node.how in ("semi", "anti"):
            return list(lc)
        keys = node.keys()
        out = list(lc)
        for c in rc:
            if c in keys:
                continue
            out.append(c if c not in out else f"{c}_right")
        return out
    return None


# -------------------------------------------------------------- optimizer
def optimize(plan: Node, required: Optional[Sequence[str]] = None,
             inline_shared: bool = True) -> Node:
    """Rule pipeline: inline single-use CTEs, fold degenerate predicates,
    push filters down, reorder inner-join chains, prune columns.
    ``required`` is the set of columns the consumer needs (select items +
    order keys ...); None = keep everything (Compute nodes re-seed the
    requirement below themselves).  ``inline_shared=False`` keeps every
    Shared boundary intact -- value-position subqueries use it so a CTE
    they execute populates the statement-wide cache instead of running a
    private inlined copy (the execute-once contract)."""
    counts: Dict[int, int] = {}
    _count_shared(plan, counts, set())
    plan = _inline_shared(plan, counts, inline_shared)
    plan = _fold_trivial_filters(plan)
    plan = _push_filters(plan)
    plan = _reorder_joins(plan, set())
    plan = _prune_columns(plan, set(required) if required is not None
                          else None)
    plan = _collapse_computes(plan)
    return plan


def _collapse_computes(node: Node) -> Node:
    """Adjacent projections fuse (CollapseProject role): a Compute whose
    every item is a bare pass-through of the child Compute's output
    substitutes the child's expressions directly -- one select pass
    instead of two (a derived table re-projected by its consumer)."""
    for name, child in _child_fields(node):
        setattr(node, name, _collapse_computes(child))
    if (
        isinstance(node, Compute) and not node.star
        and isinstance(node.child, Compute) and not node.child.star
    ):
        inner = node.child
        inner_map = {o: e for e, o in inner.exprs}
        if all(
            o in node.passthrough and o in inner_map
            for _e, o in node.exprs
        ):
            new_exprs = [(inner_map[o], o) for _e, o in node.exprs]
            pt = frozenset(
                o for _e, o in node.exprs if o in inner.passthrough
            )
            return Compute(inner.child, new_exprs, star=False,
                           passthrough=pt)
    return node


def _count_shared(node: Node, counts: Dict[int, int], seen: set) -> None:
    if isinstance(node, Shared):
        counts[id(node)] = counts.get(id(node), 0) + 1
        if id(node) in seen:
            return  # count each REFERENCE, but walk the body once
        seen.add(id(node))
    for _name, child in _child_fields(node):
        _count_shared(child, counts, seen)


def _inline_shared(node: Node, counts: Dict[int, int],
                   allow: bool = True) -> Node:
    """Already-executed Shared bodies substitute their cached frame (a
    value-position subquery may have run them during parse); single-use
    un-executed bodies inline as structural CLONES so the consumer's
    pushdown/pruning can cross them without mutating the parser-held body
    (a fallback re-parse may reference the same Shared again)."""
    if isinstance(node, Shared):
        if node._cache is not None:
            return Scan(node.name, frame=node._cache)
        if allow and counts.get(id(node), 0) <= 1:
            return _inline_shared(clone_plan(node.child), counts, allow)
    for name, child in _child_fields(node):
        setattr(node, name, _inline_shared(child, counts, allow))
    return node


def clone_plan(node: Node) -> Node:
    """Structural copy of the plan tree: nodes are rebuilt, leaf payloads
    (frames, readers, Column expressions) are shared -- they are immutable
    to the optimizer.  Shared nodes keep their IDENTITY (cloning one would
    defeat its execute-once cache)."""
    if isinstance(node, Shared):
        return node
    if isinstance(node, Scan):
        return Scan(node.name, frame=node.frame, reader=node.reader,
                    schema=list(node.schema) if node.schema else node.schema,
                    pushed_where=node.pushed_where,
                    pushed_select=(list(node.pushed_select)
                                   if node.pushed_select else
                                   node.pushed_select))
    if isinstance(node, Filter):
        return Filter(clone_plan(node.child), node.predicate)
    if isinstance(node, Project):
        return Project(clone_plan(node.child), list(node.cols))
    if isinstance(node, Join):
        return Join(clone_plan(node.left), clone_plan(node.right),
                    on=node.on, how=node.how)
    if isinstance(node, Aggregate):
        return Aggregate(clone_plan(node.child), node.key, dict(node.spec))
    if isinstance(node, Compute):
        return Compute(clone_plan(node.child), list(node.exprs),
                       star=node.star, passthrough=node.passthrough)
    if isinstance(node, Window):
        return Window(clone_plan(node.child), list(node.items))
    if isinstance(node, Sort):
        return Sort(clone_plan(node.child), list(node.by),
                    list(node.ascending))
    if isinstance(node, Limit):
        return Limit(clone_plan(node.child), node.n)
    if isinstance(node, Distinct):
        return Distinct(clone_plan(node.child))
    if isinstance(node, SetOp):
        return SetOp(clone_plan(node.left), clone_plan(node.right),
                     op=node.op)
    return node  # pragma: no cover - unknown node: share it


def _fold_trivial_filters(node: Node) -> Node:
    """A predicate with no column references is a constant: True drops the
    Filter, False empties the relation (kept as a Filter on an impossible
    mask -- the executor handles it; correctness over cleverness)."""
    if isinstance(node, Filter):
        child = _fold_trivial_filters(node.child)
        keep: List[Column] = []
        for c in split_conjuncts(node.predicate):
            if not getattr(c, "refs", None) and not getattr(
                c, "volatile", False
            ):
                try:
                    val = c({})
                except Exception:  # can't fold: keep it
                    keep.append(c)
                    continue
                if np.ndim(val) == 0 and bool(val):
                    continue  # tautology: drop
                keep.append(c)  # contradiction or odd shape: keep for exec
            else:
                keep.append(c)
        pred = and_all(keep)
        return child if pred is None else Filter(child, pred)
    for name, child in _child_fields(node):
        setattr(node, name, _fold_trivial_filters(child))
    return node


def _child_fields(node: Node) -> List[Tuple[str, Node]]:
    if isinstance(node, (Filter, Project, Aggregate, Compute, Window, Sort,
                         Limit, Distinct, Shared)):
        return [("child", node.child)]
    if isinstance(node, (Join, SetOp)):
        return [("left", node.left), ("right", node.right)]
    return []


def _push_filters(node: Node) -> Node:
    if isinstance(node, Filter):
        child = _push_filters(node.child)
        remaining: List[Column] = []
        for conj in split_conjuncts(node.predicate):
            child, pushed = _push_one(child, conj)
            if not pushed:
                remaining.append(conj)
        pred = and_all(remaining)
        node = child if pred is None else Filter(child, pred)
        return node
    for name, child in _child_fields(node):
        setattr(node, name, _push_filters(child))
    return node


def _push_one(node: Node, conj: Column) -> Tuple[Node, bool]:
    """Try to sink one conjunct into ``node``; returns (new node, pushed?).
    Volatile predicates (UDFs) and host-evaluated constructs never move --
    a moved side effect changes observable behavior."""
    refs = getattr(conj, "refs", None)
    if refs is None or getattr(conj, "volatile", False):
        return node, False
    if isinstance(node, Scan):
        if node.reader is not None:
            # into the reader: rows are filtered before device placement
            node.pushed_where = (
                conj if node.pushed_where is None
                else node.pushed_where & conj
            )
            return node, True
        # in-memory frame: a Filter directly above the scan is as far down
        # as the predicate can travel; still a win when above sat a join
        return Filter(node, conj), True
    if isinstance(node, Filter):
        child, pushed = _push_one(node.child, conj)
        if pushed:
            node.child = child
            return node, True
        return node, False
    if isinstance(node, Project):
        if set(refs) <= set(node.cols):
            node.child, pushed = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, Aggregate):
        # only group-key predicates commute with aggregation
        if node.key is not None and set(refs) <= set(node.group_keys()):
            node.child, _ = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, (Sort, Distinct)):
        # filtering commutes with a stable sort and with row dedup
        node.child, _ = _ensure_pushed(node.child, conj)
        return node, True
    if isinstance(node, Limit):
        return node, False  # filtering before LIMIT changes which rows win
    if isinstance(node, Shared):
        return node, False  # multi-consumer boundary
    if isinstance(node, Window):
        # safe only when the conjunct references PARTITION BY keys of EVERY
        # window item: whole partitions then filter together, leaving each
        # surviving partition's window values unchanged
        pkeys = node.partition_keys()
        outs = set(node.outputs())
        if pkeys is not None and set(refs) <= pkeys and not (
            set(refs) & outs
        ):
            node.child, _ = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, Compute):
        # a predicate sinks below a projection when every referenced name
        # passes through unchanged (bare same-named source column, or a
        # star-projected child column no expression overrides)
        outs = {o for _e, o in node.exprs}
        if all(
            (r in node.passthrough) or (node.star and r not in outs)
            for r in refs
        ):
            node.child, _ = _ensure_pushed(node.child, conj)
            return node, True
        return node, False
    if isinstance(node, SetOp):
        # a row-value predicate filters each branch identically; valid for
        # UNION [ALL] / INTERSECT / EXCEPT because membership and dedup
        # compare whole rows the (non-volatile) predicate already
        # determines uniformly.  union_all matches columns BY NAME, so a
        # name-resolved predicate means the same thing on both sides.
        lc, rc = node_columns(node.left), node_columns(node.right)
        if (
            lc is not None and rc is not None
            and set(refs) <= set(lc) and set(refs) <= set(rc)
        ):
            node.left, _ = _ensure_pushed(node.left, conj)
            node.right, _ = _ensure_pushed(node.right, conj)
            return node, True
        return node, False
    if isinstance(node, Join):
        lc, rc = node_columns(node.left), node_columns(node.right)
        # which sides may receive pushdown without changing semantics:
        #  inner: both; left/semi/anti: left only; right: right only;
        #  full: neither (filters see NULL-extended rows)
        allow_left = node.how in ("inner", "left", "semi", "anti")
        allow_right = node.how in ("inner", "right")
        if allow_left and lc is not None and set(refs) <= set(lc):
            node.left, _ = _ensure_pushed(node.left, conj)
            return node, True
        if allow_right and rc is not None and set(refs) <= set(rc):
            node.right, _ = _ensure_pushed(node.right, conj)
            return node, True
        return node, False
    return node, False


def _ensure_pushed(node: Node, conj: Column) -> Tuple[Node, bool]:
    """Sink ``conj`` into ``node``, wrapping in a Filter when it cannot go
    deeper (the push must not be lost)."""
    new, pushed = _push_one(node, conj)
    if pushed:
        return new, True
    return Filter(new, conj), True


def _prune_columns(node: Node, required: Optional[set]) -> Node:
    """Top-down: shrink every scan to the transitive closure of columns the
    plan above it uses.  ``required=None`` disables pruning (unknown
    consumer)."""
    if isinstance(node, Scan):
        if required is None:
            return node
        cols = node.columns()
        want = [c for c in (cols or [])
                if c in required] if cols is not None else None
        if want is not None and not want and cols:
            # nothing referenced (SELECT 1 FROM t): keep one column so the
            # source's ROW COUNT survives -- a zero-column read would
            # collapse the relation
            want = cols[:1]
        if node.reader is not None:
            # predicate columns are discovered by the reader itself
            # (sql/io.py _needed_for_predicate), so pushed_select only
            # needs the plan's requirements
            node.pushed_select = want
        elif node.frame is not None and want is not None and set(
            want
        ) != set(cols):
            if want:
                return Project(node, want)
        return node
    if isinstance(node, Filter):
        child_req = None
        if required is not None:
            child_req = set(required) | set(
                getattr(node.predicate, "refs", set()) or set()
            )
            # un-inferable refs (None) poison pruning below this node
            if getattr(node.predicate, "refs", None) is None:
                child_req = None
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Project):
        if required is not None:
            # narrow to what the consumer needs (keeps pruning alive below
            # the join-reorder's column-order-restoring wrapper); keep one
            # column so the row count survives
            want = [c for c in node.cols if c in required]
            if want:
                node.cols = want
        node.child = _prune_columns(
            node.child,
            set(node.cols) if required is not None else None,
        )
        return node
    if isinstance(node, Aggregate):
        # aggregation defines its inputs exactly (keys + agg columns), so
        # it RE-SEEDS the requirement even under an unknown consumer
        child_req: Optional[set] = set(node.group_keys())
        for colname, _fn in node.spec.values():
            if colname is None:  # COUNT(*): touches an arbitrary column
                child_req = None
                break
            child_req.add(colname)
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Compute):
        if required is not None and not node.star:
            kept = [(e, o) for e, o in node.exprs if o in required]
            if kept:
                node.exprs = kept
        refs: set = set()
        unknown = False
        for e, _o in node.exprs:
            if getattr(e, "refs", None) is None:
                unknown = True
                break
            refs |= set(e.refs)
        if node.star:
            child_cols = node_columns(node.child)
            if required is None or unknown or child_cols is None:
                child_req = None
            else:
                child_req = (set(required) | refs) & set(child_cols)
        else:
            child_req = None if unknown else refs
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Window):
        child_req = None
        if required is not None:
            child_req = set(required) - set(node.outputs())
            for _fn, arg, _off, (pby, oby, _asc), _out in node.items:
                child_req |= {c for c in (arg, oby) if c}
                if pby:
                    child_req.update(
                        [pby] if isinstance(pby, str) else pby
                    )
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Sort):
        child_req = (None if required is None
                     else set(required) | set(node.by))
        node.child = _prune_columns(node.child, child_req)
        return node
    if isinstance(node, Limit):
        node.child = _prune_columns(node.child, required)
        return node
    if isinstance(node, Distinct):
        # row identity depends on EVERY column: the child keeps its full
        # output (deeper scans still prune to that full set)
        cols = node_columns(node.child)
        node.child = _prune_columns(
            node.child, set(cols) if cols is not None else None
        )
        return node
    if isinstance(node, SetOp):
        lc, rc = node_columns(node.left), node_columns(node.right)
        if (
            node.op == "union_all" and required is not None
            and lc is not None and rc is not None and set(lc) == set(rc)
        ):
            # bag semantics never compare whole rows, so pruning crosses
            # UNION ALL; both sides prune to the SAME name set to keep the
            # by-name alignment intact
            req2 = set(required) & set(lc)
            if not req2:
                req2 = {lc[0]}
            node.left = _prune_columns(node.left, req2)
            node.right = _prune_columns(node.right, req2)
        else:
            # distinct set ops compare whole rows: children keep their
            # full outputs
            node.left = _prune_columns(
                node.left, set(lc) if lc is not None else None
            )
            node.right = _prune_columns(
                node.right, set(rc) if rc is not None else None
            )
        return node
    if isinstance(node, Shared):
        return node  # multi-consumer boundary: no per-consumer pruning
    if isinstance(node, Join):
        if required is None:
            node.left = _prune_columns(node.left, None)
            node.right = _prune_columns(node.right, None)
            return node
        req = set(required) | set(node.keys())
        # a suffixed output column c_right requires right-side c -- AND the
        # left-side c must survive too: the _right suffix only exists while
        # the names collide, so pruning the left copy would silently rename
        # the right column to bare c and break the consumer's reference
        base = {c[: -len("_right")] for c in required if
                c.endswith("_right")}
        node.left = _prune_columns(node.left, req | base)
        node.right = _prune_columns(node.right, req | base)
        return node
    return node


# --------------------------------------------------------- join reordering
_FILTER_SELECTIVITY = 0.25  # per-conjunct row-survival guess (no stats)


def _estimate_rows(node: Node) -> Optional[float]:
    """Row-count estimate for join ordering; None = unknown.  In-memory
    frames are exact; filters decay by a fixed per-conjunct selectivity
    (the reference's ``CostBasedJoinReorder`` uses real stats -- this build
    has live frame sizes, which already decide the common star shapes)."""
    if isinstance(node, Scan):
        if node.frame is not None:
            return float(len(node.frame))
        return None  # lazy reader: size unknown until read
    if isinstance(node, Filter):
        base = _estimate_rows(node.child)
        if base is None:
            return None
        k = len(split_conjuncts(node.predicate))
        return max(base * (_FILTER_SELECTIVITY ** k), 1.0)
    if isinstance(node, (Project, Compute, Window, Sort, Distinct)):
        return _estimate_rows(node.child)
    if isinstance(node, Limit):
        base = _estimate_rows(node.child)
        return float(node.n) if base is None else min(base, float(node.n))
    if isinstance(node, Shared):
        if node._cache is not None:
            return float(len(node._cache))
        return _estimate_rows(node.child)
    if isinstance(node, Aggregate):
        base = _estimate_rows(node.child)
        return None if base is None else max(base * 0.1, 1.0)
    return None


def _reorder_joins(node: Node, done: set) -> Node:
    """Greedy reorder of maximal inner-join chains (``ReorderJoin``,
    ``joins.scala:37``): start from the smallest estimated relation, then
    repeatedly join the smallest relation CONNECTED by a declared key.
    Constraint-set equivalence holds because every pair of chain relations
    sharing a column shares only declared keys (checked; otherwise the
    written order stands), so any connected order enforces the same
    equalities.  Output column order is restored with a Project when the
    rebuild permutes it."""
    if (
        isinstance(node, Join) and node.how == "inner"
        and id(node) not in done
    ):
        rebuilt = _reorder_chain(node)
        for j in _walk_inner_joins(rebuilt):
            done.add(id(j))
        node = rebuilt
    for name, child in _child_fields(node):
        setattr(node, name, _reorder_joins(child, done))
    return node


def _walk_inner_joins(node: Node) -> List[Join]:
    out: List[Join] = []
    if isinstance(node, Project):  # the column-order restoring wrapper
        node = node.child
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, Join) and n.how == "inner":
            out.append(n)
            stack.extend([n.left, n.right])
    return out


def _reorder_chain(top: Join) -> Node:
    leaves: List[Node] = []
    key_order: List[str] = []

    def collect(n: Node) -> None:
        if isinstance(n, Join) and n.how == "inner":
            collect(n.left)
            collect(n.right)
            for k in n.keys():
                if k not in key_order:
                    key_order.append(k)
        else:
            leaves.append(n)

    collect(top)
    if len(leaves) < 3:
        return top  # 2-way join: build-side selection already handles it
    cols = [node_columns(l) for l in leaves]
    if any(c is None for c in cols):
        return top
    orig_cols = node_columns(top)
    if orig_cols is None or any(c.endswith("_right") for c in orig_cols):
        return top  # suffixed collisions: order decides naming; keep it
    keyset = set(key_order)
    for i in range(len(leaves)):
        for j in range(i + 1, len(leaves)):
            if (set(cols[i]) & set(cols[j])) - keyset:
                return top  # non-key shared column: semantics order-bound
    sizes = [_estimate_rows(l) for l in leaves]
    if all(s is None for s in sizes):
        return top  # no signal to order by
    inf = float("inf")
    szs = [inf if s is None else s for s in sizes]
    remaining = list(range(len(leaves)))
    start = min(remaining, key=lambda i: (szs[i], i))
    order = [start]
    remaining.remove(start)
    acc_cols = set(cols[start])
    steps: List[Tuple[int, List[str]]] = []
    while remaining:
        cands = [i for i in remaining if set(cols[i]) & acc_cols & keyset]
        if not cands:
            return top  # disconnected under this start: keep written order
        nxt = min(cands, key=lambda i: (szs[i], i))
        jk = [k for k in key_order if k in cols[nxt] and k in acc_cols]
        steps.append((nxt, jk))
        acc_cols |= set(cols[nxt])
        remaining.remove(nxt)
        order.append(nxt)
    if order == list(range(len(leaves))):
        return top  # already in the greedy order: keep the original tree
    new: Node = leaves[order[0]]
    for leaf_idx, jk in steps:
        new = Join(new, leaves[leaf_idx],
                   on=jk[0] if len(jk) == 1 else jk, how="inner")
    new_cols = node_columns(new)
    if new_cols != orig_cols:
        new = Project(new, list(orig_cols))
    return new


# --------------------------------------------------------------- execution
def execute(node: Node) -> ColumnarFrame:
    if isinstance(node, Scan):
        if node.reader is not None:
            return node.reader(
                select=node.pushed_select, where=node.pushed_where
            )
        assert node.frame is not None
        return node.frame
    if isinstance(node, Filter):
        return execute(node.child).filter(node.predicate)
    if isinstance(node, Project):
        return execute(node.child).select(*node.cols)
    if isinstance(node, Aggregate):
        frame = execute(node.child)
        spec = _resolve_count_star(frame, node.spec)
        if node.key is None:  # whole-frame scalar aggregates: one row
            scalars = frame.agg(**spec)
            return ColumnarFrame(
                {k: np.asarray([v]) for k, v in scalars.items()}
            )
        gb = frame.groupby(node.key)
        if not spec:
            return gb.count()
        return gb.agg(**spec)
    if isinstance(node, Compute):
        frame = execute(node.child)
        if node.star:
            if not node.exprs:
                return frame
            sel = list(frame.columns) + [
                e.alias(o) for e, o in node.exprs if o not in frame.columns
            ]
            return frame.select(*sel)
        return frame.select(*[e.alias(o) for e, o in node.exprs])
    if isinstance(node, Window):
        frame = execute(node.child)
        for fn, arg, offset, (pby, oby, asc), out in node.items:
            frame = frame.with_window(
                out, fn, arg, partition_by=pby, order_by=oby,
                ascending=asc, offset=offset,
            )
        return frame
    if isinstance(node, Sort):
        frame = execute(node.child)
        missing = [c for c in node.by if c not in frame.columns]
        if missing:  # schema was unknown at parse: say it plainly here
            raise ValueError(
                f"ORDER BY {missing[0]!r}: not a result column"
            )
        return frame.sort(node.by, ascending=node.ascending)
    if isinstance(node, Limit):
        return limit_frame(execute(node.child), node.n)
    if isinstance(node, Distinct):
        return execute(node.child).distinct()
    if isinstance(node, SetOp):
        left = execute(node.left)
        right = execute(node.right)
        if node.op == "union_all":
            return left.union_all(right)
        if node.op == "union":
            return left.union(right)
        if node.op == "except":
            return left.except_rows(right)
        if node.op == "intersect":
            return left.intersect_rows(right)
        raise ValueError(f"unknown set op {node.op!r}")
    if isinstance(node, Shared):
        if node._cache is None:
            node._cache = execute(node.child)
        return node._cache
    if isinstance(node, Join):
        return execute(node.left).join(
            execute(node.right), on=node.on, how=node.how
        )
    raise TypeError(f"unknown plan node {type(node).__name__}")


def limit_frame(frame: ColumnarFrame, n: int) -> ColumnarFrame:
    """LIMIT n: the first n rows (one definition, shared by the plan
    executor and the parser's eager path)."""
    return frame._take(np.arange(min(n, len(frame))))


def _resolve_count_star(frame: ColumnarFrame, spec):
    """COUNT(*) entries carry colname None; resolve to any device column at
    execution (the parser's ``_any_device_column`` contract)."""
    if not any(colname is None for colname, _fn in spec.values()):
        return spec
    import jax.numpy as jnp

    anycol = None
    for name in frame.columns:
        if isinstance(frame[name], jnp.ndarray):
            anycol = name
            break
    if anycol is None:
        raise ValueError("COUNT(*) needs at least one numeric column")
    return {
        out: ((anycol, fn) if colname is None else (colname, fn))
        for out, (colname, fn) in spec.items()
    }
