"""Column expressions: a small lazy expression tree over device arrays.

Parity: Spark SQL's ``Column`` DSL (``sql/core/.../Column.scala`` /
catalyst expression trees).  The reference compiles expression trees to JVM
bytecode (whole-stage codegen); here the SAME role -- turn a tree of
column refs, literals, arithmetic, comparisons, and boolean logic into one
fused kernel -- is filled by tracing the tree into a jitted XLA computation,
which is the TPU's whole-stage codegen.  No SQL parser: the experiments the
reference ships never issue SQL text, and the DSL is the capability layer
Spark's own DataFrame API sits on.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict

import jax.numpy as jnp


class Column:
    """A lazy expression evaluated against a dict of named arrays."""

    def __init__(self, fn: Callable[[Dict[str, Any]], Any], name: str):
        self._fn = fn
        self.name = name

    def __call__(self, columns: Dict[str, Any]):
        return self._fn(columns)

    def alias(self, name: str) -> "Column":
        return Column(self._fn, name)

    # ------------------------------------------------------------- operators
    def _binop(self, other, op, sym: str, reflect: bool = False) -> "Column":
        other_c = other if isinstance(other, Column) else lit(other)
        a, b = (other_c, self) if reflect else (self, other_c)

        def fn(cols):
            return op(a(cols), b(cols))

        return Column(fn, f"({a.name} {sym} {b.name})")

    def __add__(self, o):
        return self._binop(o, operator.add, "+")

    def __radd__(self, o):
        return self._binop(o, operator.add, "+", reflect=True)

    def __sub__(self, o):
        return self._binop(o, operator.sub, "-")

    def __rsub__(self, o):
        return self._binop(o, operator.sub, "-", reflect=True)

    def __mul__(self, o):
        return self._binop(o, operator.mul, "*")

    def __rmul__(self, o):
        return self._binop(o, operator.mul, "*", reflect=True)

    def __truediv__(self, o):
        return self._binop(o, operator.truediv, "/")

    def __rtruediv__(self, o):
        return self._binop(o, operator.truediv, "/", reflect=True)

    def __mod__(self, o):
        return self._binop(o, operator.mod, "%")

    def __neg__(self):
        return Column(lambda cols: -self(cols), f"(-{self.name})")

    # comparisons produce boolean columns
    def __eq__(self, o):  # type: ignore[override]
        return self._binop(o, operator.eq, "==")

    def __ne__(self, o):  # type: ignore[override]
        return self._binop(o, operator.ne, "!=")

    def __lt__(self, o):
        return self._binop(o, operator.lt, "<")

    def __le__(self, o):
        return self._binop(o, operator.le, "<=")

    def __gt__(self, o):
        return self._binop(o, operator.gt, ">")

    def __ge__(self, o):
        return self._binop(o, operator.ge, ">=")

    # boolean logic (use & | ~ like Spark/pandas)
    def __and__(self, o):
        return self._binop(o, jnp.logical_and, "AND")

    def __or__(self, o):
        return self._binop(o, jnp.logical_or, "OR")

    def __invert__(self):
        return Column(
            lambda cols: jnp.logical_not(self(cols)), f"(NOT {self.name})"
        )

    __hash__ = object.__hash__  # __eq__ is overridden for the DSL

    def __repr__(self) -> str:  # pragma: no cover
        return f"Column<{self.name}>"


def col(name: str) -> Column:
    """Reference a frame column by name."""

    def fn(cols):
        if name not in cols:
            raise KeyError(
                f"no column {name!r}; have {sorted(cols)}"
            )
        return cols[name]

    return Column(fn, name)


def lit(value) -> Column:
    """A literal broadcast against the frame's rows."""
    return Column(lambda cols: value, repr(value))
