"""Column expressions: a small lazy expression tree over device arrays.

Parity: Spark SQL's ``Column`` DSL (``sql/core/.../Column.scala`` /
catalyst expression trees).  The reference compiles expression trees to JVM
bytecode (whole-stage codegen); here the SAME role -- turn a tree of
column refs, literals, arithmetic, comparisons, and boolean logic into one
fused kernel -- is filled by tracing the tree into a jitted XLA computation,
which is the TPU's whole-stage codegen.  No SQL parser: the experiments the
reference ships never issue SQL text, and the DSL is the capability layer
Spark's own DataFrame API sits on.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict

import jax.numpy as jnp


class Column:
    """A lazy expression evaluated against a dict of named arrays.

    Optimizer metadata (sql/plan.py reads it, never requires it):
    ``refs`` -- the frozenset of column names the expression reads (None =
    unknown, blocks plan rewrites); ``volatile`` -- evaluation has effects
    or non-determinism (UDFs), blocking both movement and folding.
    """

    def __init__(self, fn: Callable[[Dict[str, Any]], Any], name: str,
                 refs: "frozenset | None" = None, volatile: bool = False):
        self._fn = fn
        self.name = name
        self.refs = refs
        self.volatile = volatile

    def __call__(self, columns: Dict[str, Any]):
        return self._fn(columns)

    def alias(self, name: str) -> "Column":
        out = Column(self._fn, name, refs=self.refs, volatile=self.volatile)
        out._and_parts = getattr(self, "_and_parts", None)
        return out

    # ------------------------------------------------------------- operators
    def _binop(self, other, op, sym: str, reflect: bool = False) -> "Column":
        other_c = other if isinstance(other, Column) else lit(other)
        a, b = (other_c, self) if reflect else (self, other_c)
        refs = _union_refs(a, b)
        volatile = a.volatile or b.volatile
        label = f"({a.name} {sym} {b.name})"
        if refs == frozenset() and not volatile:
            # constant folding (Optimizer.scala:38 ConstantFolding, done at
            # construction): a ref-free pure tree evaluates once, now
            try:
                v = op(a({}), b({}))
                return Column(lambda cols: v, label, refs=frozenset())
            except Exception:
                pass  # fold failed (e.g. div by zero): stay lazy

        def fn(cols):
            return op(a(cols), b(cols))

        return Column(fn, label, refs=refs, volatile=volatile)

    def __add__(self, o):
        return self._binop(o, operator.add, "+")

    def __radd__(self, o):
        return self._binop(o, operator.add, "+", reflect=True)

    def __sub__(self, o):
        return self._binop(o, operator.sub, "-")

    def __rsub__(self, o):
        return self._binop(o, operator.sub, "-", reflect=True)

    def __mul__(self, o):
        return self._binop(o, operator.mul, "*")

    def __rmul__(self, o):
        return self._binop(o, operator.mul, "*", reflect=True)

    def __truediv__(self, o):
        return self._binop(o, operator.truediv, "/")

    def __rtruediv__(self, o):
        return self._binop(o, operator.truediv, "/", reflect=True)

    def __mod__(self, o):
        return self._binop(o, operator.mod, "%")

    def __neg__(self):
        return Column(lambda cols: -self(cols), f"(-{self.name})",
                      refs=self.refs, volatile=self.volatile)

    # comparisons produce boolean columns
    def __eq__(self, o):  # type: ignore[override]
        return self._binop(o, operator.eq, "==")

    def __ne__(self, o):  # type: ignore[override]
        return self._binop(o, operator.ne, "!=")

    def __lt__(self, o):
        return self._binop(o, operator.lt, "<")

    def __le__(self, o):
        return self._binop(o, operator.le, "<=")

    def __gt__(self, o):
        return self._binop(o, operator.gt, ">")

    def __ge__(self, o):
        return self._binop(o, operator.ge, ">=")

    # boolean logic (use & | ~ like Spark/pandas)
    def __and__(self, o):
        out = self._binop(o, jnp.logical_and, "AND")
        # record the conjunction shape for the optimizer's conjunct split
        # (plan.split_conjuncts) -- but NOT on a folded-to-constant result:
        # splitting it back into pre-fold sides would undo the fold
        if out.refs != frozenset() or out.volatile:
            other_c = o if isinstance(o, Column) else lit(o)
            out._and_parts = (self, other_c)
        return out

    def __or__(self, o):
        return self._binop(o, jnp.logical_or, "OR")

    def __invert__(self):
        return Column(
            lambda cols: jnp.logical_not(self(cols)), f"(NOT {self.name})",
            refs=self.refs, volatile=self.volatile,
        )

    __hash__ = object.__hash__  # __eq__ is overridden for the DSL

    # ------------------------------------------------- SQL predicate helpers
    def isin(self, values) -> "Column":
        """SQL ``IN``: membership against a literal list or a (subquery)
        result array.  Device columns use a vectorized isin; string columns
        fall back to host numpy."""

        def fn(cols):
            v = self(cols)
            vals = list(values)
            if isinstance(v, jnp.ndarray):
                arr = jnp.asarray(vals)
                return jnp.isin(v, arr)
            import numpy as _np

            return _np.isin(_np.asarray(v), _np.asarray(vals))

        return Column(fn, f"({self.name} IN ...)",
                      refs=self.refs, volatile=self.volatile)

    def between(self, lo, hi) -> "Column":
        """SQL ``BETWEEN lo AND hi`` (inclusive both ends)."""
        return ((self >= lo) & (self <= hi)).alias(
            f"({self.name} BETWEEN ...)"
        )

    def like(self, pattern: str) -> "Column":
        """SQL ``LIKE``: ``%`` = any run, ``_`` = any one char; string
        columns only (host-side regex -- strings never live in HBM)."""
        import re as _re

        rx = _re.compile(
            "".join(
                ".*" if ch == "%" else "." if ch == "_" else _re.escape(ch)
                for ch in pattern
            )
            + r"\Z"
        )

        def fn(cols):
            import numpy as _np

            v = _np.asarray(self(cols))
            return _np.fromiter(
                (rx.match(str(x)) is not None for x in v), bool, len(v)
            )

        return Column(fn, f"({self.name} LIKE {pattern!r})",
                      refs=self.refs, volatile=self.volatile)

    def cast(self, type_name: str) -> "Column":
        """SQL ``CAST(x AS t)`` for t in int/bigint/float/double/string/
        bool.  Numeric casts stay on device; string casts come to host."""
        t = type_name.lower()

        def fn(cols):
            v = self(cols)
            import numpy as _np

            if t in ("int", "integer", "bigint", "long"):
                if isinstance(v, jnp.ndarray):
                    return v.astype(jnp.int32 if t in ("int", "integer")
                                    else jnp.int64)
                return _np.asarray(v).astype(
                    _np.int32 if t in ("int", "integer") else _np.int64
                )
            if t in ("float", "double", "real"):
                if isinstance(v, jnp.ndarray):
                    return v.astype(jnp.float32 if t == "float"
                                    else jnp.float64)
                return _np.asarray(v, _np.float64 if t != "float"
                                   else _np.float32)
            if t in ("string", "varchar", "text"):
                arr = _np.asarray(v)
                if arr.dtype.kind in "iu":
                    return _np.asarray([str(int(x)) for x in arr], object)
                if arr.dtype.kind == "f":
                    return _np.asarray([str(float(x)) for x in arr], object)
                return arr.astype(object)
            if t in ("bool", "boolean"):
                if isinstance(v, jnp.ndarray):
                    return v != 0
                return _np.asarray(v).astype(bool)
            raise ValueError(f"unsupported CAST target {type_name!r}")

        return Column(fn, f"CAST({self.name} AS {t})",
                      refs=self.refs, volatile=self.volatile)

    def is_null(self) -> "Column":
        """SQL ``IS NULL``: NaN for float columns, never-null otherwise
        (the columnar store's documented null story)."""

        def fn(cols):
            v = self(cols)
            import numpy as _np

            if isinstance(v, jnp.ndarray):
                return jnp.isnan(v) if jnp.issubdtype(
                    v.dtype, jnp.floating
                ) else jnp.zeros(v.shape, bool)
            arr = _np.asarray(v)
            if arr.dtype.kind == "f":
                return _np.isnan(arr)
            return _np.zeros(arr.shape, bool)

        return Column(fn, f"({self.name} IS NULL)",
                      refs=self.refs, volatile=self.volatile)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Column<{self.name}>"


def _union_refs(*cols: Column):
    """Union of child ref-sets; None (unknown) poisons the union."""
    out = frozenset()
    for c in cols:
        if c.refs is None:
            return None
        out |= c.refs
    return out


def col(name: str) -> Column:
    """Reference a frame column by name."""

    def fn(cols):
        if name not in cols:
            raise KeyError(
                f"no column {name!r}; have {sorted(cols)}"
            )
        return cols[name]

    return Column(fn, name, refs=frozenset({name}))


def lit(value) -> Column:
    """A literal broadcast against the frame's rows."""
    return Column(lambda cols: value, repr(value), refs=frozenset())


class CaseBuilder:
    """``when(cond, val).when(...).otherwise(default)`` -- SQL CASE WHEN.

    Lowers to a right-folded ``jnp.where`` chain: one fused select kernel,
    first matching branch wins (SQL semantics).
    """

    def __init__(self, branches):
        self._branches = list(branches)

    def when(self, cond: Column, value) -> "CaseBuilder":
        v = value if isinstance(value, Column) else lit(value)
        return CaseBuilder(self._branches + [(cond, v)])

    def otherwise(self, value) -> Column:
        default = value if isinstance(value, Column) else lit(value)
        branches = self._branches

        def fn(cols):
            import numpy as _np

            def is_texty(x):
                if isinstance(x, str):
                    return True
                if isinstance(x, jnp.ndarray):
                    return False
                a = _np.asarray(x)
                return a.dtype.kind in "OUS"

            out = default(cols)
            for cond, v in reversed(branches):
                c = cond(cols)
                val = v(cols)
                if is_texty(val) or is_texty(out):
                    # string branches select on host (strings never live in
                    # HBM); result is an object column
                    res = _np.where(_np.asarray(c), val, out)
                    out = res.astype(object) if res.dtype.kind in "US" else res
                elif isinstance(out, jnp.ndarray) or isinstance(
                    val, jnp.ndarray
                ) or isinstance(c, jnp.ndarray):
                    out = jnp.where(c, val, out)
                else:
                    out = _np.where(_np.asarray(c), val, out)
            return out

        parts = [default] + [x for cond, v in branches for x in (cond, v)]
        return Column(fn, "CASE", refs=_union_refs(*parts),
                      volatile=any(p.volatile for p in parts))

    def end(self) -> Column:
        """CASE without ELSE: unmatched rows get NaN (the null story)."""
        return self.otherwise(float("nan"))


def when(cond: Column, value) -> CaseBuilder:
    v = value if isinstance(value, Column) else lit(value)
    return CaseBuilder([(cond, v)])


def _host_str(v):
    import numpy as np

    return np.asarray(v, object)


def _host_rows(args):
    """Normalize evaluated args for a host string function: every arg
    becomes a length-n host array (scalars/literals broadcast)."""
    import numpy as np

    arrs = [np.asarray(x) for x in args]
    n = max((a.shape[0] for a in arrs if a.ndim > 0), default=1)
    return n, [
        a if a.ndim > 0 else np.asarray([a[()]] * n, object) for a in arrs
    ]


def _mk_math(jf):
    return lambda args: jf(args[0])


#: scalar function library (name -> impl over evaluated args); math runs on
#: device via jnp, string functions on host (strings never live in HBM)
FUNCTIONS: Dict[str, Callable] = {
    "ABS": _mk_math(jnp.abs),
    "SQRT": _mk_math(jnp.sqrt),
    "EXP": _mk_math(jnp.exp),
    "LN": _mk_math(jnp.log),
    "LOG": _mk_math(jnp.log),
    "LOG10": _mk_math(jnp.log10),
    "FLOOR": _mk_math(jnp.floor),
    "CEIL": _mk_math(jnp.ceil),
    "CEILING": _mk_math(jnp.ceil),
    "SIN": _mk_math(jnp.sin),
    "COS": _mk_math(jnp.cos),
    "SIGN": _mk_math(jnp.sign),
    "POW": lambda a: jnp.power(a[0], a[1]),
    "POWER": lambda a: jnp.power(a[0], a[1]),
    "ROUND": lambda a: (
        jnp.round(a[0], int(a[1])) if len(a) > 1 else jnp.round(a[0])
    ),
    "GREATEST": lambda a: __import__("functools").reduce(jnp.maximum, a),
    "LEAST": lambda a: __import__("functools").reduce(jnp.minimum, a),
    "COALESCE": lambda a: __import__("functools").reduce(
        lambda x, y: jnp.where(jnp.isnan(x), y, x), a
    ),
    "UPPER": lambda a: _host_str([str(x).upper() for x in _host_str(a[0])]),
    "LOWER": lambda a: _host_str([str(x).lower() for x in _host_str(a[0])]),
    "LENGTH": lambda a: __import__("numpy").asarray(
        [len(str(x)) for x in _host_str(a[0])], __import__("numpy").int32
    ),
    "TRIM": lambda a: _host_str([str(x).strip() for x in _host_str(a[0])]),
    "CONCAT": lambda a: _concat(a),
    "REPLACE": lambda a: _replace(a),
    "SUBSTR": lambda a: _substr(a),
    "SUBSTRING": lambda a: _substr(a),
}


def _concat(args):
    n, arrs = _host_rows(args)
    return _host_str(
        ["".join(str(a[i]) for a in arrs) for i in range(n)]
    )


def _replace(args):
    n, (s, old, new) = _host_rows(args)
    return _host_str(
        [str(s[i]).replace(str(old[i]), str(new[i])) for i in range(n)]
    )


def _substr(args):
    n, arrs = _host_rows(args)
    s, start = arrs[0], arrs[1]
    length = arrs[2] if len(arrs) > 2 else None
    out = []
    for i in range(n):
        lo = int(start[i]) - 1  # SQL substr is 1-based
        out.append(
            str(s[i])[lo : lo + int(length[i])] if length is not None
            else str(s[i])[lo:]
        )
    return _host_str(out)


def call_function(name: str, args) -> Column:
    """Build a Column applying library function ``name`` to arg Columns.

    The arg columns are evaluated, then the function body runs once over
    whole arrays -- the scalar-function analog of whole-stage codegen.
    CONCAT/REPLACE/SUBSTR treat scalar (literal) args as scalars.
    """
    fn = FUNCTIONS[name.upper()]

    def run(cols):
        return fn([a(cols) for a in args])

    label = f"{name.lower()}({', '.join(a.name for a in args)})"
    return Column(run, label, refs=_union_refs(*args),
                  volatile=any(a.volatile for a in args))


def udf_column(fn: Callable, args, name: str) -> Column:
    """Row-wise python UDF (Spark ``spark.udf.register`` analog): evaluated
    per row on host -- the same contract as the reference's python UDFs
    (arbitrary python, no vectorization promises)."""
    import numpy as np

    def run(cols):
        vals = [np.asarray(a(cols)) for a in args]
        if not any(v.ndim > 0 for v in vals):
            # all-literal call: return a scalar so the frame broadcasts it
            # like any other literal expression
            return fn(*[v[()] for v in vals])
        n = max(len(v) for v in vals if v.ndim > 0)
        rows = [
            fn(*[v[i] if v.ndim > 0 else v[()] for v in vals])
            for i in range(n)
        ]
        out = np.asarray(rows)
        if out.dtype.kind in "US":
            out = out.astype(object)
        return out

    # volatile: arbitrary python may have effects/non-determinism, so the
    # optimizer must neither move nor fold UDF calls
    return Column(run, f"{name}(...)", refs=_union_refs(*args),
                  volatile=True)
