from asyncframework_tpu.sql.expressions import Column, col, lit
from asyncframework_tpu.sql.frame import ColumnarFrame

__all__ = ["ColumnarFrame", "Column", "col", "lit"]
