from asyncframework_tpu.sql.expressions import Column, col, lit
from asyncframework_tpu.sql.frame import ColumnarFrame
from asyncframework_tpu.sql.io import (
    read_csv,
    read_json,
    read_parquet,
    write_csv,
)
from asyncframework_tpu.sql.parser import SQLContext, sql

__all__ = [
    "ColumnarFrame", "Column", "col", "lit",
    "read_csv", "read_json", "read_parquet", "write_csv",
    "SQLContext", "sql",
]
