"""ModelReplica: a snapshot-subscribing predict server (the read path).

The training plane (parallel/ps_dcn.py) publishes versioned model
snapshots -- zero-copy wire bytes + CRC per version, ``have=``-negotiated
NM/delta pulls.  That machinery IS a replica cache-invalidation protocol
(ASYNC's versioned broadcast, arXiv:1907.08526; ASAP's staleness-bounded
reads, arXiv:1612.08608), so a replica is thin by construction:

- a **background refresh loop** sends ``SUBSCRIBE`` (a wave-gate-free,
  membership-free delta pull -- see ``ParameterServer._handle_subscribe``)
  every ``async.serve.refresh.interval.s``, through the stock
  :class:`~asyncframework_tpu.parallel.ps_dcn.PSClient` basis-cache /
  CRC-fallback machinery: an unchanged version costs a header-only
  NOT_MODIFIED, a changed one a sparse XOR delta, and ANY decode mismatch
  degrades to a full pull -- the replica can lag, never hold a wrong
  model;
- the current model lives behind an **atomic reference swap**
  (:class:`_Served` -- version, host/device arrays, PS clock, freshness
  basis), so PREDICT handlers read ONE reference and compute against a
  coherent (version, weights) pair: a torn model is unrepresentable;
- **PREDICT** RPCs (single row or batched) run a jitted ``ops`` predict
  step (``ops/steps.make_predict_step``), batch sizes bucketed to powers
  of two so a mixed request stream compiles O(log n) executables;
- **freshness-lag SLO**: every reply is stamped with the served version
  and its lag in versions (PS clock - served ts) and ms; a replica whose
  last successful refresh is older than ``async.serve.max.staleness.ms``
  marks itself UNHEALTHY and the frontend fails over -- unless training
  is DONE and the replica already holds the final version, in which case
  it is fresh forever (the PS tearing down must not take reads with it).

The wire rides ``net/frame.py``, so SUBSCRIBE and PREDICT are
fault-schedulable ops for the chaos fabric like any other verb.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.parallel.ps_dcn import PSClient
from asyncframework_tpu.serving import metrics as smetrics
from asyncframework_tpu.serving.server import FramedServer
from asyncframework_tpu.utils.threads import guarded

_send_msg = _frame.send_msg
_recv_msg = _frame.recv_msg


class _Served:
    """One atomically-published served model: immutable once built, so a
    PREDICT handler that read the reference computes against a coherent
    (version, weights) pair no matter how many refreshes land meanwhile."""

    __slots__ = ("ts", "w_host", "w_dev", "clock", "k", "age_ms",
                 "refreshed_mono", "done")

    def __init__(self, ts: int, w_host: np.ndarray, w_dev, clock: int,
                 k: int, age_ms: float, refreshed_mono: float, done: bool):
        self.ts = ts
        self.w_host = w_host
        self.w_dev = w_dev
        self.clock = clock
        self.k = k
        self.age_ms = age_ms
        self.refreshed_mono = refreshed_mono
        self.done = done


class ModelReplica(FramedServer):
    """Subscribe to the PS's versioned snapshots; answer PREDICT RPCs.

    ``start()`` binds the predict server and launches the refresh loop;
    ``refresh_once()`` is the loop body, public so tests can drive the
    subscription deterministically.  ``stop()`` tears both down.
    """

    def __init__(self, ps_host: str, ps_port: int, rid: int = 0,
                 host: str = "0.0.0.0", port: int = 0,
                 loss: str = "least_squares",
                 refresh_interval_s: Optional[float] = None,
                 max_stale_ms: Optional[float] = None,
                 device=None,
                 relay_port: Optional[int] = None,
                 relay_parent: Optional[tuple] = None):
        from asyncframework_tpu.conf import (
            SERVE_MAX_STALE_MS,
            SERVE_REFRESH_S,
            global_conf,
        )

        conf = global_conf()
        super().__init__(f"replica-{int(rid)}")
        self.ps_host, self.ps_port = ps_host, int(ps_port)
        self.rid = int(rid)
        self.loss = loss
        self.refresh_interval_s = (
            float(refresh_interval_s) if refresh_interval_s is not None
            else float(conf.get(SERVE_REFRESH_S))
        )
        self.max_stale_ms = (
            float(max_stale_ms) if max_stale_ms is not None
            else float(conf.get(SERVE_MAX_STALE_MS))
        )
        self.device = device
        # relaycast (asyncframework_tpu/relaycast/): relay_port is not
        # None = this replica runs a RelayNode next to its predict
        # server and fetches through the distribution tree --
        # relay_parent names its planned parent's relay endpoint (None =
        # a direct child of the PS root, which SUBSCRIBEs as usual and
        # re-serves its children).  The fetch path falls back to a
        # direct root SUBSCRIBE on ANY relay failure, so relay mode can
        # lag, never regress safety.
        self.relay_port = relay_port
        self.relay_parent = (tuple(relay_parent) if relay_parent
                             else None)
        self._relay_node = None
        if relay_port is not None:
            # bind EAGERLY (like the predict server below): children may
            # dial this node before our first refresh lands -- they get
            # an honest "no model yet" ERR and fall back to the root,
            # instead of a connection refused that looks like death
            from asyncframework_tpu.relaycast import RelayNode

            self._relay_node = RelayNode(rid=self.rid,
                                         port=int(relay_port),
                                         on_offer=self._on_relay_offer)
        self._predict_step = None   # built lazily with the first model
        self._served: Optional[_Served] = None  # ATOMIC reference swap
        self.d: Optional[int] = None
        self._client: Optional[PSClient] = None
        self._last_ok_mono: Optional[float] = None
        # local observability (shipped on STATUS; process-global serving
        # counters are bumped too so an in-process replica shows up in
        # /api/status next to the frontend's numbers)
        self.predicts = 0
        self.predict_unhealthy = 0
        self.refreshes = 0
        self.refresh_errors = 0
        self._stats_lock = threading.Lock()
        # serializes refresh_once: the background loop and any manual
        # caller (tests, an admin resync) share ONE PSClient connection,
        # and interleaved send/recv on a framed stream desyncs it
        self._refresh_lock = threading.Lock()
        self._refresh_thread: Optional[threading.Thread] = None
        self.bind(host, port)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ModelReplica":
        self.start_accepting()
        if self._relay_node is not None:
            self._relay_node.start()
        self._refresh_thread = threading.Thread(
            target=self._refresh_loop, name=f"replica-{self.rid}-refresh",
            daemon=True,
        )
        self._refresh_thread.start()
        return self

    def stop(self) -> None:
        self.stop_server()
        if self._relay_node is not None:
            self._relay_node.stop()
        if self._client is not None:
            # the refresh thread shares this client's connection: say BYE
            # only once any in-flight refresh has drained (bounded wait --
            # a refresh stuck in its retry budget just forfeits the BYE;
            # the PS treats EOF as goodbye)
            if self._refresh_lock.acquire(timeout=2.0):
                try:
                    self._client.bye()
                except (ConnectionError, OSError):
                    pass
                finally:
                    self._refresh_lock.release()

    # -------------------------------------------------------------- refresh
    def _ensure_client(self):
        if self._client is None:
            # shard-map resolution first (one SHARDMAP round trip): a
            # sharded PS group answers its per-range map and the replica
            # subscribes every range (shardgroup.ShardedSubscriber --
            # partial refresh + per-range freshness); the classic single
            # PS answers empty and gets the stock client.  Delta mode
            # unconditionally either way: the refresh loop is exactly the
            # workload NM/XDELTA negotiation exists for (the CRC fallback
            # keeps it degrade-to-full, never wrong).
            from asyncframework_tpu.parallel import shardgroup as _sg

            smap, epochs, epoch = _sg.fetch_group_info(
                self.ps_host, self.ps_port
            )
            # fencing epochs ride the same handshake: a fenced (zombie)
            # shard answers the subscriber's stamped reads REJECT_FENCED
            # instead of serving a range it no longer owns, and the
            # subscriber self-heals onto the replacement's epoch
            if smap is not None:
                # relay + shard group is not a supported combination:
                # per-range relays would need a per-shard tree each --
                # the sharded subscriber's fan-out pull is the path
                self._client = _sg.ShardedSubscriber(smap, epochs=epochs)
            elif self._relay_node is not None:
                from asyncframework_tpu.relaycast import RelaySource

                node = self._relay_node
                if epoch and epoch > node.epoch:
                    node.epoch = int(epoch)
                self._client = RelaySource(
                    self.ps_host, self.ps_port, node,
                    parent=self.relay_parent, rid=self.rid,
                )
            else:
                self._client = PSClient(self.ps_host, self.ps_port,
                                        pull_mode="delta", epoch=epoch)
        return self._client

    def _on_relay_offer(self) -> None:
        """A parent (or the PS root) announced a new version: refresh
        NOW instead of waiting out the poll interval.  Serialized by the
        refresh lock like every other caller; failures are the refresh
        path's problem (counted there), never the offer handler's."""
        try:
            self.refresh_once()
        except (ConnectionError, OSError):  # pragma: no cover - paced
            pass                            # retry on the poll loop

    def _sharded(self):
        """The ShardedSubscriber when this replica reads a shard group,
        else None (duck-typing on the one surface that differs)."""
        cl = self._client
        return cl if hasattr(cl, "stale_ranges") else None

    def refresh_once(self) -> bool:
        """One SUBSCRIBE round trip; True iff a (possibly unchanged) model
        was validated and (re)published.  Transport errors surface as
        False -- the loop paces and retries; the served reference is only
        ever replaced by a CRC-validated model.  Serialized against the
        background loop (one connection, framed stream)."""
        with self._refresh_lock:
            return self._refresh_once_locked()

    def _refresh_once_locked(self) -> bool:
        import jax

        try:
            cl = self._ensure_client()
            wenc_before = dict(cl.pull_wenc)
            fb_before = cl.delta_fallbacks
            got = cl.subscribe(self.rid)
        except (ConnectionError, OSError):
            with self._stats_lock:
                self.refresh_errors += 1
            smetrics.bump("refresh_errors")
            return False
        if got is None:  # pragma: no cover - SUBSCRIBE never says DONE
            return False
        ts, w_host, clock, k, age_ms, done = got
        for shape, n in cl.pull_wenc.items():
            delta = n - wenc_before.get(shape, 0)
            if delta:
                smetrics.bump(f"refresh_{shape}", delta)
        if cl.delta_fallbacks > fb_before:
            smetrics.bump("refresh_fallbacks",
                          cl.delta_fallbacks - fb_before)
        prev = self._served
        if (prev is not None and prev.ts == ts
                and not getattr(cl, "changed_since_last", False)):
            # unchanged version (NM fast path): reuse the device buffer,
            # refresh only the freshness bookkeeping.  Against a shard
            # group ts is a SUM of per-shard versions, and a shard
            # restart rolls its clock back -- sum collisions happen, so
            # the subscriber's vector-compare flag gates the reuse (a
            # stock PSClient has no flag: its ts is a single monotone
            # clock and equality IS identity)
            w_dev = prev.w_dev
        else:
            if self.device is None:
                self.device = jax.devices()[0]
            w_dev = jax.device_put(np.asarray(w_host, np.float32),
                                   self.device)
        if self.d is None:
            self.d = int(w_host.shape[0])
        if self._predict_step is None:
            from asyncframework_tpu.ops import steps

            self._predict_step = steps.make_predict_step(self.loss)
        now = time.monotonic()
        # the atomic swap: PREDICT handlers holding the old reference keep
        # serving the old (coherent) version; new reads see the new one
        self._served = _Served(ts, w_host, w_dev, clock, k, age_ms, now,
                               done)
        self._last_ok_mono = now
        with self._stats_lock:
            self.refreshes += 1
        smetrics.bump("refreshes")
        return True

    def _refresh_loop(self) -> None:
        while not self._stop.is_set():
            ok = self.refresh_once()
            served = self._served
            if (ok and served is not None and served.done
                    and served.ts >= served.clock):
                # training finished and we hold the final version: the
                # model can never change again -- stop polling the PS
                # (which may be tearing down) and serve forever
                return
            self._stop.wait(self.refresh_interval_s if ok else
                            max(self.refresh_interval_s, 0.05))

    # ------------------------------------------------------------ freshness
    def _lag(self, served: _Served) -> Dict[str, float]:
        """Freshness lag of ``served`` NOW, in versions and ms.

        versions = PS clock at last refresh minus served version (the
        send-time re-stamp on SUBSCRIBE makes this 0 when only dropped
        pushes ticked the clock).  ms = the PS-reported age of the served
        version at reply time plus time since that reply when the replica
        KNOWS it is behind; otherwise time-since-refresh alone -- an upper
        bound on how stale the replica could possibly be (versions may
        have appeared since the last refresh).  A replica holding the
        final version of a DONE run is fresh forever."""
        now = time.monotonic()
        lag_v = max(0, served.clock - served.ts)
        since_ms = (now - served.refreshed_mono) * 1e3
        if served.done and lag_v == 0:
            return {"lag_versions": 0, "lag_ms": 0.0}
        if lag_v > 0:
            return {"lag_versions": lag_v,
                    "lag_ms": served.age_ms + since_ms}
        return {"lag_versions": 0, "lag_ms": since_ms}

    def healthy(self) -> bool:
        """False once the last successful refresh is older than the
        ``async.serve.max.staleness.ms`` SLO (0 = no gate) -- except for a
        replica holding the final version of a finished run, which cannot
        go stale."""
        served = self._served
        if served is None:
            return False  # no model yet: nothing correct to serve
        if served.done and served.ts >= served.clock:
            return True
        if self.max_stale_ms <= 0:
            return True
        sub = self._sharded()
        if sub is not None:
            # per-range gate: a partially-dark group keeps publishing
            # (live ranges refresh), so health must price the STALEST
            # range, not the last assembled publish
            age = sub.oldest_ok_age_ms()
            return age is not None and age <= self.max_stale_ms
        last_ok = self._last_ok_mono
        return (last_ok is not None
                and (time.monotonic() - last_ok) * 1e3 <= self.max_stale_ms)

    def status(self) -> Dict:
        served = self._served
        with self._stats_lock:
            out = {
                "rid": self.rid,
                "port": self.port,
                "healthy": self.healthy(),
                "predicts": self.predicts,
                "predict_unhealthy": self.predict_unhealthy,
                "refreshes": self.refreshes,
                "refresh_errors": self.refresh_errors,
            }
        cl = self._client
        if cl is not None:
            out["refresh_wenc"] = dict(cl.pull_wenc)
            out["refresh_fallbacks"] = cl.delta_fallbacks
        sub = self._sharded()
        if sub is not None:
            # UNHEALTHY-per-range surface: which ranges are fresh, which
            # are dark, and how stale the stalest is
            out["ranges"] = sub.range_status()
            if self.max_stale_ms > 0:
                out["stale_ranges"] = sub.stale_ranges(self.max_stale_ms)
        node = self._relay_node
        if node is not None:
            # relaycast surface: tree position, learned children, fetch
            # traffic, and how this replica is currently sourcing bytes
            relay = node.status()
            relay["parent"] = (list(self.relay_parent)
                               if self.relay_parent else None)
            if cl is not None:
                relay["via_parent"] = getattr(cl, "via_parent", 0)
                relay["via_root"] = getattr(cl, "via_root", 0)
            out["relay"] = relay
        if served is not None:
            out.update(ts=served.ts, clock=served.clock, k=served.k,
                       **self._lag(served))
        return out

    # ------------------------------------------------------------- serving
    def handle_op(self, conn: socket.socket, op: Optional[str],
                  header: dict, payload: bytes) -> bool:
        if op == "PREDICT":
            self._handle_predict(conn, header, payload)
        elif op == "STATUS":
            _send_msg(conn, {"op": "STATUS", **self.status()})
        else:
            return False
        return True

    def _handle_predict(self, conn: socket.socket, header: dict,
                        payload: bytes) -> None:
        served = self._served
        if served is None or not self.healthy():
            with self._stats_lock:
                self.predict_unhealthy += 1
            lag = self._lag(served) if served is not None else {}
            sub = self._sharded()
            if sub is not None and self.max_stale_ms > 0:
                # name the dark ranges: the caller learns WHICH slice of
                # the model went stale, not just that something did
                lag["stale_ranges"] = sub.stale_ranges(self.max_stale_ms)
            _send_msg(conn, {"op": "UNHEALTHY", "rid": self.rid, **lag})
            return
        n = int(header.get("n", 0))
        d = served.w_host.shape[0]
        if n <= 0 or len(payload) != 4 * n * d:
            _send_msg(conn, {"op": "ERR",
                             "msg": f"PREDICT wants n*d={n}*{d} f32 rows, "
                                    f"got {len(payload)} bytes"})
            return
        X = np.frombuffer(payload, np.float32).reshape(n, d)
        y = self._predict(served, X)
        lag = self._lag(served)
        with self._stats_lock:
            self.predicts += 1
        smetrics.bump("replica_predicts")
        _send_msg(
            conn,
            {"op": "PREDICTION", "rid": self.rid, "n": n,
             "ts": served.ts, **lag},
            np.ascontiguousarray(y, np.float32).tobytes(),
        )

    def _predict(self, served: _Served, X: np.ndarray) -> np.ndarray:
        """The jitted predict step against the served weights; batch rows
        padded to the next power of two so shapes (= compiled
        executables) stay O(log n) across a mixed request stream."""
        import jax

        n = X.shape[0]
        cap = 1 << max(0, (n - 1).bit_length())
        if cap != n:
            Xp = np.zeros((cap, X.shape[1]), np.float32)
            Xp[:n] = X
        else:
            Xp = X
        X_dev = jax.device_put(Xp, self.device)
        y = self._predict_step(X_dev, served.w_dev)
        return np.asarray(y)[:n]


def serve_replica(ps: str, rid: int = 0, host: str = "0.0.0.0",
                  port: int = 0, loss: str = "least_squares",
                  frontend: Optional[str] = None,
                  announce=print,
                  hello_interval_s: float = 2.0,
                  relay_port: Optional[int] = None,
                  relay_parent: Optional[str] = None) -> ModelReplica:
    """CLI helper (``async-serve replica``): start a replica, keep it
    registered with a frontend, and announce the bound port as one JSON
    line on stdout (launchers parse it).

    Registration is a LOOP, not a one-shot: HELLO is idempotent (same
    endpoint -> same slot) and doubles as a liveness heartbeat, so a
    restarted frontend rebuilds its rotation from the replicas' next
    HELLOs instead of starting a permanent empty-rotation outage, and a
    frontend that was down at replica boot is joined as soon as it
    appears."""
    import json

    ps_host, ps_port = ps.rsplit(":", 1)
    rparent = None
    if relay_parent:
        ph, pp = relay_parent.rsplit(":", 1)
        rparent = (ph, int(pp))
    rep = ModelReplica(ps_host, int(ps_port), rid=rid, host=host,
                       port=port, loss=loss, relay_port=relay_port,
                       relay_parent=rparent).start()
    if frontend:
        fh, fp = frontend.rsplit(":", 1)

        def hello_once() -> None:
            from asyncframework_tpu.parallel.supervisor import (
                proc_start_time,
            )

            sock = _frame.connect((fh, int(fp)), timeout=5.0)
            try:
                hdr = {"op": "HELLO",
                       "proc": f"replica-{os.getpid()}",
                       "replica": True, "port": rep.port,
                       "host": socket.gethostname(),
                       "pid": os.getpid(), "rid": rid}
                pstart = proc_start_time(os.getpid())
                if pstart is not None:
                    # pid-reuse protection for the frontend's local pid
                    # probe: WHICH process holds this pid, not just that
                    # one does
                    hdr["pstart"] = pstart
                _send_msg(sock, hdr)
                _recv_msg(sock)
            finally:
                sock.close()

        def hello_loop() -> None:
            while not rep._stop.wait(hello_interval_s):
                try:
                    hello_once()
                except (ConnectionError, OSError):
                    pass  # frontend down/restarting: next beat retries

        try:
            hello_once()
        except (ConnectionError, OSError):
            pass  # not fatal: the loop below keeps trying
        threading.Thread(target=guarded(hello_loop, f"replica-{rid}-hello"),
                         name=f"replica-{rid}-hello",
                         daemon=True).start()
    line = {"role": "replica", "rid": rid, "port": rep.port,
            "pid": os.getpid()}
    if rep._relay_node is not None:
        # the node bound in __init__, so an ephemeral ask announces the
        # real port and launchers learn the tree endpoint here
        line["relay_port"] = int(rep._relay_node.port)
    announce(json.dumps(line), flush=True)
    return rep
