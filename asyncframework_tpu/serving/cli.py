"""``async-serve``: the serving-tier process entry point.

Two roles::

    # a predict replica subscribed to a PS, optionally HELLOing a frontend
    async-serve replica --ps HOST:PORT [--port P] [--frontend HOST:PORT]
                        [--rid N] [--loss least_squares|logistic]
                        [--conf k=v ...]

    # a frontend: replica registration front door + client predict proxy
    async-serve frontend [--port P] [--replicas h:p,h:p,...]
                         [--conf k=v ...]

Each role prints ONE JSON line on stdout once bound (``{"role": ...,
"port": ...}``) so launchers (bench.py --serve, tests, k8s readiness
wrappers) can parse the ephemeral port, then serves until SIGTERM/EOF.
``--conf`` overlays any registered ``async.serve.*`` / ``async.net.*``
knob, same precedence as async-submit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="async-serve", description=__doc__.split("\n\n")[0]
    )
    sub = p.add_subparsers(dest="role", required=True)
    r = sub.add_parser("replica", help="snapshot-subscribing predict server")
    r.add_argument("--ps", required=True, metavar="HOST:PORT",
                   help="parameter server to SUBSCRIBE to")
    r.add_argument("--host", default="0.0.0.0")
    r.add_argument("--port", type=int, default=0,
                   help="predict port (0 = ephemeral, printed on stdout)")
    r.add_argument("--rid", type=int, default=0, help="replica id")
    r.add_argument("--loss", default="least_squares",
                   choices=["least_squares", "logistic"])
    r.add_argument("--frontend", default=None, metavar="HOST:PORT",
                   help="HELLO this frontend after binding (joins its "
                        "rotation)")
    r.add_argument("--relay-port", type=int, default=None,
                   help="run a relaycast node on this port (0 = "
                        "ephemeral, announced on stdout); absent = "
                        "relay off, classic direct SUBSCRIBE")
    r.add_argument("--relay-parent", default=None, metavar="HOST:PORT",
                   help="planned relay parent's node endpoint; absent "
                        "with --relay-port = a direct child of the PS "
                        "root")
    r.add_argument("--relay-auto", action="store_true",
                   help="derive rid + relay parent from this pod's "
                        "hostname ordinal (StatefulSet convention "
                        "name-<i>) and the k-ary tree plan "
                        "(async.relay.fanout); needs --relay-port and "
                        "--relay-service")
    r.add_argument("--relay-service", default=None, metavar="SVC",
                   help="headless-service DNS suffix for --relay-auto "
                        "peer addressing (name-<i>.SVC:relay-port)")
    r.add_argument("--conf", action="append", default=[], metavar="K=V")
    f = sub.add_parser("frontend", help="replica registry + predict router")
    f.add_argument("--host", default="0.0.0.0")
    f.add_argument("--port", type=int, default=0,
                   help="front-door port (0 = ephemeral, printed on stdout)")
    f.add_argument("--replicas", default="", metavar="H:P,H:P",
                   help="static replica endpoints (dynamic HELLOs add more)")
    f.add_argument("--conf", action="append", default=[], metavar="K=V")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    if os.environ.get("ASYNCTPU_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    args = build_parser().parse_args(argv)
    from asyncframework_tpu.cli import parse_conf_overlays

    parse_conf_overlays(args.conf)
    from asyncframework_tpu.net.faults import maybe_install_from_conf

    maybe_install_from_conf()  # chaos fabric reaches serving daemons too
    from asyncframework_tpu.metrics.live import start_telemetry_from_conf

    # per-process telemetry endpoint (async.metrics.port; -1 = off):
    # /metrics Prometheus exposition + /api/status counters/health for
    # the serving fleet -- k8s manifests annotate these pods for scraping
    if args.role == "replica":
        start_telemetry_from_conf("replica",
                                  labels={"rid": str(args.rid)})
    else:
        start_telemetry_from_conf("frontend")
    if args.role == "replica":
        from asyncframework_tpu.serving.replica import serve_replica

        rid, relay_parent = args.rid, args.relay_parent
        if args.relay_auto:
            # StatefulSet convention: hostname "async-serve-replica-3"
            # -> rid 3; the parent is a pure function of (rid, fanout)
            # (relaycast/tree.py), addressed through the headless
            # service -- zero coordination, every pod computes the same
            # tree
            import socket as _socket

            from asyncframework_tpu.conf import RELAY_FANOUT, global_conf
            from asyncframework_tpu.relaycast import ROOT, parent_index

            if args.relay_port is None or not args.relay_service:
                raise SystemExit("--relay-auto needs --relay-port and "
                                 "--relay-service")
            hostname = _socket.gethostname()
            base, _, ordinal = hostname.rpartition("-")
            if not ordinal.isdigit():
                raise SystemExit(f"--relay-auto needs an ordinal "
                                 f"hostname (got {hostname!r})")
            rid = int(ordinal)
            fanout = int(global_conf().get(RELAY_FANOUT))
            p = parent_index(rid, fanout)
            relay_parent = None if p == ROOT else (
                f"{base}-{p}.{args.relay_service}:{args.relay_port}"
            )
        rep = serve_replica(args.ps, rid=rid, host=args.host,
                            port=args.port, loss=args.loss,
                            frontend=args.frontend,
                            relay_port=args.relay_port,
                            relay_parent=relay_parent)
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            rep.stop()
        return 0
    # frontend role
    from asyncframework_tpu.serving.frontend import ServingFrontend

    replicas = []
    for tok in (args.replicas or "").split(","):
        tok = tok.strip()
        if tok:
            host, port = tok.rsplit(":", 1)
            replicas.append((host, int(port)))
    fe = ServingFrontend(replicas).serve(port=args.port, host=args.host)
    print(json.dumps({"role": "frontend", "port": fe.port,
                      "pid": os.getpid()}), flush=True)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        fe.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
